"""Rank-style communication API lowered to XLA collectives.

Capability analog of ``paddle.distributed.{all_reduce, all_gather, …}``
(SURVEY D22; reference ``python/paddle/distributed/communication/*.py``,
C++ ``ProcessGroupNCCL`` D1). TPU-native mechanism: every call builds a
tiny ``jax.shard_map`` program over the group's mesh axis and lets XLA
emit the ICI collective (``psum``/``all_gather``/``all_to_all``/
``ppermute``). Under ``jit.to_static`` capture these fuse into the
surrounding XLA program — there is no separate comm stream to manage
(PJRT schedules compute/collective overlap).

Groups may be ``collective.Group`` (1-axis mesh over a device subset) or a
``fleet.topology.AxisGroup`` (one axis of the hybrid mesh) — both expose
``mesh``/``axis``/``nranks``.

Single-controller convention (see collective.py): a per-rank local tensor
of shape ``S`` is represented as one global Tensor of shape ``[nranks, *S]``
whose slice ``r`` is rank ``r``'s copy, sharded over the group axis. All
operations are in-place on that Tensor (reference semantics) and
non-differentiable (collectives used inside model code — TP layers,
sequence parallel — use the differentiable GSPMD layers instead).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from .collective import Group, _resolve

AXIS = Group.AXIS


class ReduceOp:
    """Reference ``paddle.distributed.ReduceOp`` parity."""
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


def _reduce_fn(op, ax):
    if op == ReduceOp.SUM:
        return lambda a: lax.psum(a, ax)
    if op == ReduceOp.MAX:
        return lambda a: lax.pmax(a, ax)
    if op == ReduceOp.MIN:
        return lambda a: lax.pmin(a, ax)
    if op == ReduceOp.AVG:
        return lambda a: lax.pmean(a, ax)
    if op == ReduceOp.PROD:
        # no pprod primitive: gather the factors and multiply (sign-safe,
        # unlike the exp-of-psum-of-logs trick)
        return lambda a: jnp.prod(
            lax.all_gather(a, ax, axis=0, tiled=False), axis=0)
    raise ValueError(f"unknown ReduceOp {op}")


def _axis_of(g) -> str:
    return getattr(g, "axis", AXIS)


def _value(x):
    if isinstance(x, Tensor):
        return x._read()
    return jnp.asarray(x)


def _assign(t: Tensor, val):
    """In-place, autograd-opaque write (collectives don't join the tape)."""
    t._write(val)
    t._node = None


def _put(mesh, x, spec):
    """Pin x to the group mesh sharding (no-op on tracers: inside a jit
    trace the sharding is a constraint XLA already knows from shard_map)."""
    if isinstance(x, jax.core.Tracer):
        return x
    return jax.device_put(x, NamedSharding(mesh, spec))


def _check_rank_axis(name, x, g):
    if x.ndim == 0 or x.shape[0] != g.nranks:
        raise ValueError(
            f"{name}: expected leading rank axis of size {g.nranks} "
            f"(single-controller convention: tensor = stack of per-rank "
            f"local tensors), got shape {tuple(x.shape)}")


def _group_rank(g, r: int, what: str) -> int:
    """Map a global rank to its index within the group; reject ranks outside
    the group (the reference raises likewise)."""
    gr = g.get_group_rank(r) if hasattr(g, "get_group_rank") else (
        r if 0 <= r < g.nranks else -1)
    if gr < 0:
        raise ValueError(f"{what}={r} is not a member of {g!r}")
    return gr


def _smap(g, body, x, in_spec=None, out_spec=None):
    ax = _axis_of(g)
    in_spec = P(ax) if in_spec is None else in_spec
    out_spec = P(ax) if out_spec is None else out_spec
    from ..core.meshutil import shard_map as _shard_map
    f = _shard_map(body, mesh=g.mesh, in_specs=in_spec,
                   out_specs=out_spec)
    return f(_put(g.mesh, x, in_spec if isinstance(in_spec, P) else P(ax)))


# --- collectives -----------------------------------------------------------

def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None,
               sync_op: bool = True):
    """Reference ``communication/all_reduce.py``; lowers to ``lax.psum``."""
    g = _resolve(group)
    ax = _axis_of(g)
    x = _value(tensor)
    _check_rank_axis("all_reduce", x, g)
    if g.nranks == 1:
        return tensor
    y = _smap(g, lambda a: _reduce_fn(op, ax)(a), x)
    _assign(tensor, y)
    return tensor


def all_gather(tensor_list: List, tensor: Tensor, group=None,
               sync_op: bool = True):
    """Reference ``communication/all_gather.py``: after the call,
    ``tensor_list[i]`` holds rank i's tensor (replicated content, kept
    sharded over the rank axis for HBM parity with the per-rank layout)."""
    g = _resolve(group)
    ax = _axis_of(g)
    x = _value(tensor)
    _check_rank_axis("all_gather", x, g)
    n = g.nranks
    if n == 1:
        tensor_list.append(Tensor(x, stop_gradient=True))
        return tensor_list

    def body(a):  # a: [1, *S]
        full = lax.all_gather(a, ax, axis=0, tiled=True)  # [n, *S]
        return tuple(full[i:i + 1] for i in range(n))

    outs = _smap(g, body, x, out_spec=tuple(P(ax) for _ in range(n)))
    for o in outs:
        tensor_list.append(Tensor(o, stop_gradient=True))
    return tensor_list


def broadcast(tensor: Tensor, src: int = 0, group=None,
              sync_op: bool = True):
    """Reference ``communication/broadcast.py``."""
    g = _resolve(group)
    ax = _axis_of(g)
    x = _value(tensor)
    _check_rank_axis("broadcast", x, g)
    if g.nranks == 1:
        return tensor
    s = _group_rank(g, src, "src")

    def body(a):
        full = lax.all_gather(a, ax, axis=0, tiled=True)
        return full[s:s + 1]

    _assign(tensor, _smap(g, body, x))
    return tensor


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM, group=None,
           sync_op: bool = True):
    """Reference ``communication/reduce.py``: result lands on rank ``dst``;
    other ranks keep their input (reference leaves them unspecified)."""
    g = _resolve(group)
    ax = _axis_of(g)
    x = _value(tensor)
    _check_rank_axis("reduce", x, g)
    if g.nranks == 1:
        return tensor
    d = _group_rank(g, dst, "dst")

    def body(a):
        s = _reduce_fn(op, ax)(a)
        r = lax.axis_index(ax)
        return jnp.where(r == d, s, a)

    _assign(tensor, _smap(g, body, x))
    return tensor


def scatter(tensor: Tensor, tensor_list: Optional[List] = None, src: int = 0,
            group=None, sync_op: bool = True):
    """Reference ``communication/scatter.py``: rank i receives
    ``tensor_list[i]`` as held by rank ``src``."""
    g = _resolve(group)
    ax = _axis_of(g)
    n = g.nranks
    if tensor_list is None:
        raise ValueError("scatter requires tensor_list on the src rank")
    vals = [_value(t) for t in tensor_list]
    if len(vals) != n:
        raise ValueError(f"scatter: need {n} tensors, got {len(vals)}")
    s = _group_rank(g, src, "src")
    if n == 1:
        _assign(tensor, vals[0])
        return tensor
    stacked = jnp.stack(vals, axis=0)  # [L=n, n_rank, *S]

    def body(a):  # a: [n, 1, *S] (sharded on rank axis, dim 1)
        full = lax.all_gather(a, ax, axis=1, tiled=True)  # [n, n, *S]
        r = lax.axis_index(ax)
        mine = lax.dynamic_index_in_dim(full, r, 0, keepdims=False)  # [n,*S]
        return mine[s:s + 1]

    y = _smap(g, body, stacked, in_spec=P(None, ax), out_spec=P(ax))
    _assign(tensor, y)
    return tensor


def gather(tensor: Tensor, gather_list: Optional[List] = None, dst: int = 0,
           group=None, sync_op: bool = True):
    """Reference ``communication/gather.py``. Single-controller: the gathered
    list is materialized for all ranks (dst only reads it)."""
    g = _resolve(group)
    if gather_list is None:
        gather_list = []
    return all_gather(gather_list, tensor, group=g)


def reduce_scatter(tensor: Tensor, tensor_list: List, op=ReduceOp.SUM,
                   group=None, sync_op: bool = True):
    """Reference ``communication/reduce_scatter.py``: rank r receives
    ``sum over ranks q of tensor_list_q[r]``; lowers to ``lax.psum_scatter``."""
    g = _resolve(group)
    ax = _axis_of(g)
    n = g.nranks
    vals = [_value(t) for t in tensor_list]
    if len(vals) != n:
        raise ValueError(f"reduce_scatter: need {n} tensors, got {len(vals)}")
    if n == 1:
        _assign(tensor, vals[0])
        return tensor
    stacked = jnp.stack(vals, axis=1)  # [n_rank, L=n, *S]

    def body(a):  # [1, n, *S]
        loc = a[0]  # [n, *S]
        if op == ReduceOp.SUM:
            return lax.psum_scatter(loc, ax, scatter_dimension=0,
                                    tiled=True)  # [1, *S]
        if op == ReduceOp.AVG:
            return lax.psum_scatter(loc, ax, scatter_dimension=0,
                                    tiled=True) / n
        full = _reduce_fn(op, ax)(loc)  # [n, *S] reduced elementwise
        r = lax.axis_index(ax)
        return lax.dynamic_index_in_dim(full, r, 0, keepdims=True)

    y = _smap(g, body, stacked, in_spec=P(ax), out_spec=P(ax))
    _assign(tensor, y)
    return tensor


def alltoall(out_tensor_list: List, in_tensor_list: List, group=None,
             sync_op: bool = True):
    """Reference ``communication/all_to_all.py``: rank r's out[i] = rank i's
    in[r]; lowers to ``lax.all_to_all``."""
    g = _resolve(group)
    ax = _axis_of(g)
    n = g.nranks
    vals = [_value(t) for t in in_tensor_list]
    if len(vals) != n:
        raise ValueError(f"alltoall: need {n} tensors, got {len(vals)}")
    if n == 1:
        out_tensor_list.append(Tensor(vals[0], stop_gradient=True))
        return out_tensor_list
    stacked = jnp.stack(vals, axis=1)  # [n_rank, L=n, *S]

    def body(a):  # [1, n, *S]
        b = lax.all_to_all(a, ax, split_axis=1, concat_axis=0)  # [n, 1, *S]
        return tuple(b[i] for i in range(n))  # each [1, *S]

    outs = _smap(g, body, stacked, in_spec=P(ax),
                 out_spec=tuple(P(ax) for _ in range(n)))
    for o in outs:
        out_tensor_list.append(Tensor(o, stop_gradient=True))
    return out_tensor_list


def alltoall_single(out_tensor: Tensor, in_tensor: Tensor,
                    in_split_sizes=None, out_split_sizes=None, group=None,
                    sync_op: bool = True):
    """Reference ``communication/all_to_all.py`` alltoall_single (equal
    splits; the uneven-split variant is served by ``alltoall``)."""
    g = _resolve(group)
    ax = _axis_of(g)
    n = g.nranks
    x = _value(in_tensor)
    _check_rank_axis("alltoall_single", x, g)
    if in_split_sizes or out_split_sizes:
        raise NotImplementedError(
            "alltoall_single with uneven splits: use alltoall")
    if n == 1:
        _assign(out_tensor, x)
        return out_tensor

    # per-rank local [m, *S]: split dim0 into n chunks, chunk j -> rank j,
    # concat received chunks on dim0 (the reference's equal-split fast path)
    def body(a):  # local [1, m, *S]
        loc = a[0]
        b = lax.all_to_all(loc, ax, split_axis=0, concat_axis=0, tiled=True)
        return b[None]

    y = _smap(g, body, x)
    _assign(out_tensor, y)
    return out_tensor


# --- point to point --------------------------------------------------------

def _ppermute_merge(tensor: Tensor, perm, g):
    """One collective-permute; slices not receiving data keep their value."""
    ax = _axis_of(g)
    x = _value(tensor)
    _check_rank_axis("p2p", x, g)

    def body(a):
        return lax.ppermute(a, ax, perm)

    y = _smap(g, body, x)
    dsts = [d for _, d in perm]
    idx = jnp.arange(g.nranks).reshape((-1,) + (1,) * (x.ndim - 1))
    mask = jnp.isin(idx, jnp.asarray(dsts))
    return jnp.where(mask, y, x)


def send(tensor: Tensor, dst: int = 0, group=None, sync_op: bool = True,
         src: int = 0):
    """Single-controller p2p: copies rank ``src``'s slice to rank ``dst``
    via ``lax.ppermute`` (ICI collective-permute). In the reference this is
    a per-process NCCL send (``communication/send.py``); here the one
    program expresses both sides, so ``send`` performs the full transfer and
    ``recv`` validates/reads it."""
    g = _resolve(group)
    perm = [(_group_rank(g, src, "src"), _group_rank(g, dst, "dst"))]
    _assign(tensor, _ppermute_merge(tensor, perm, g))
    return tensor


def recv(tensor: Tensor, src: int = 0, group=None, sync_op: bool = True,
         dst: int = 0):
    """Pairs with ``send`` (see above): pulls rank ``src``'s slice into rank
    ``dst``'s slot of ``tensor``."""
    return send(tensor, dst=dst, group=group, src=src)


def isend(tensor, dst=0, group=None):
    return send(tensor, dst=dst, group=group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src=src, group=group)


class P2POp:
    """Reference ``communication/batch_isend_irecv.py`` P2POp: op is
    ``isend``/``irecv``; peer is the remote rank; ``rank`` (extension) is
    the local rank the op runs on (explicit because one controller drives
    every rank)."""

    def __init__(self, op, tensor, peer, group=None, rank=0):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group
        self.rank = rank


def batch_isend_irecv(p2p_op_list: List[P2POp]):
    """Executes the batch's matched send/recv pairs as ppermutes (one per
    distinct payload tensor — sends sharing a tensor fuse into a single
    collective-permute, exactly how pipeline-parallel P2P should ride ICI;
    reference ``p2p_communication.py:52`` batches NCCL send/recv).

    Each ``isend`` moves its own tensor's slice [rank] to slice [peer]; a
    matching ``irecv`` (peer/rank mirrored) designates the destination
    tensor — when it is a different buffer than the send's, the received
    slice is written there."""
    if not p2p_op_list:
        return []
    g = _resolve(p2p_op_list[0].group)
    sends = [op for op in p2p_op_list if op.op in (isend, send)]
    recvs = [op for op in p2p_op_list if op.op in (irecv, recv)]

    # group sends by payload tensor id so shared buffers fuse into one
    # ppermute while distinct buffers each get their own transfer
    by_tensor: dict[int, list[P2POp]] = {}
    for op in sends:
        by_tensor.setdefault(id(op.tensor), []).append(op)

    for ops in by_tensor.values():
        tensor = ops[0].tensor
        perm = [(_group_rank(g, op.rank, "rank"),
                 _group_rank(g, op.peer, "peer")) for op in ops]
        merged = _ppermute_merge(tensor, perm, g)
        # route received slices into matched recv buffers; destinations whose
        # recv designates a DIFFERENT buffer must not clobber the sender
        # tensor's copy of that slice
        ext_dsts = []
        for op in ops:
            for r in recvs:
                if r.peer == op.rank and r.rank == op.peer \
                        and r.tensor is not tensor:
                    x = _value(r.tensor)
                    d = _group_rank(g, op.peer, "peer")
                    ext_dsts.append(d)
                    idx = jnp.arange(g.nranks).reshape(
                        (-1,) + (1,) * (x.ndim - 1))
                    _assign(r.tensor, jnp.where(idx == d, merged, x))
        if ext_dsts:
            x0 = _value(tensor)
            idx = jnp.arange(g.nranks).reshape((-1,) + (1,) * (x0.ndim - 1))
            keep = jnp.isin(idx, jnp.asarray(ext_dsts))
            merged = jnp.where(keep, x0, merged)
        _assign(tensor, merged)
    return []


# --- sync ------------------------------------------------------------------

def barrier(group=None):
    """Reference ``communication/group.py`` barrier: an all_reduce on a
    scalar, then a host sync."""
    g = _resolve(group)
    t = Tensor(jnp.ones((g.nranks, 1), dtype=jnp.int32))
    all_reduce(t, group=g)
    v = t._read()
    if not isinstance(v, jax.core.Tracer):
        jax.block_until_ready(v)


def wait(tensor, group=None, use_calc_stream=True):
    """Reference ``communication/wait``; PJRT futures make every result
    awaitable — block on the buffer."""
    v = _value(tensor)
    if not isinstance(v, jax.core.Tracer):
        jax.block_until_ready(v)
    return tensor


def get_backend(group=None):
    return "xla:ici"

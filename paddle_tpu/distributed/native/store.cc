// Native TCP key-value rendezvous store server (SURVEY D3 — the analog
// of the reference's C++ TCPStore, paddle/phi/core/distributed/store/
// tcp_store.h:121 + socket.cpp). Thread-per-connection; one mutex +
// condition_variable guards the table so blocking GETs wake on SET/ADD.
//
// Wire protocol (lengths big-endian):
//   request:  [1B op][4B klen][key][payload]
//     op 1 SET:   payload = [4B vlen][value bytes]
//     op 2 GET:   payload = [8B timeout_ms]   (blocks until key or timeout)
//     op 3 ADD:   payload = [8B amount]       (int counter; returns value)
//     op 4 DEL:   payload = none
//     op 5 CLOSE: payload = none              (closes this connection)
//   response: [1B ok][4B vlen][value]
//     ADD -> value = [8B int]; DEL -> value = [1B existed]; GET -> bytes.
//
// C API (ctypes): pdtpu_store_start(host, port) -> handle (>0) or
// -errno; pdtpu_store_port(h); pdtpu_store_stop(h).
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> data;
};

bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

uint32_t rd32(const unsigned char* b) {
  return (uint32_t(b[0]) << 24) | (uint32_t(b[1]) << 16) |
         (uint32_t(b[2]) << 8) | uint32_t(b[3]);
}

int64_t rd64(const unsigned char* b) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | b[i];
  return static_cast<int64_t>(v);
}

void wr32(unsigned char* b, uint32_t v) {
  b[0] = v >> 24;
  b[1] = v >> 16;
  b[2] = v >> 8;
  b[3] = v;
}

void wr64(unsigned char* b, uint64_t v) {
  for (int i = 7; i >= 0; --i) {
    b[i] = v & 0xff;
    v >>= 8;
  }
}

bool reply(int fd, bool ok, const std::string& value) {
  std::vector<unsigned char> out(5 + value.size());
  out[0] = ok ? 1 : 0;
  wr32(out.data() + 1, static_cast<uint32_t>(value.size()));
  std::memcpy(out.data() + 5, value.data(), value.size());
  return write_exact(fd, out.data(), out.size());
}

void serve(Store* st, int fd) {
  for (;;) {
    unsigned char hdr[5];
    if (!read_exact(fd, hdr, 5)) break;
    uint8_t op = hdr[0];
    uint32_t klen = rd32(hdr + 1);
    if (klen > (64u << 20)) break;  // sanity
    std::string key(klen, '\0');
    if (klen && !read_exact(fd, key.data(), klen)) break;

    if (op == 1) {  // SET
      unsigned char l4[4];
      if (!read_exact(fd, l4, 4)) break;
      uint32_t vlen = rd32(l4);
      if (vlen > (256u << 20)) break;
      std::string value(vlen, '\0');
      if (vlen && !read_exact(fd, value.data(), vlen)) break;
      {
        std::lock_guard<std::mutex> g(st->mu);
        st->data[key] = std::move(value);
      }
      st->cv.notify_all();
      if (!reply(fd, true, "")) break;
    } else if (op == 2) {  // GET (blocking)
      unsigned char t8[8];
      if (!read_exact(fd, t8, 8)) break;
      int64_t timeout_ms = rd64(t8);
      std::unique_lock<std::mutex> lk(st->mu);
      bool ok = st->cv.wait_for(
          lk, std::chrono::milliseconds(timeout_ms),
          [&] { return st->stop.load() || st->data.count(key) > 0; });
      ok = ok && st->data.count(key) > 0;
      std::string value = ok ? st->data[key] : "";
      lk.unlock();
      if (!reply(fd, ok, value)) break;
    } else if (op == 3) {  // ADD
      unsigned char a8[8];
      if (!read_exact(fd, a8, 8)) break;
      int64_t amount = rd64(a8);
      int64_t cur;
      {
        std::lock_guard<std::mutex> g(st->mu);
        auto it = st->data.find(key);
        int64_t prev = 0;
        if (it != st->data.end() && it->second.size() == 8)
          prev = rd64(reinterpret_cast<const unsigned char*>(
              it->second.data()));
        cur = prev + amount;
        std::string enc(8, '\0');
        wr64(reinterpret_cast<unsigned char*>(enc.data()),
             static_cast<uint64_t>(cur));
        st->data[key] = std::move(enc);
      }
      st->cv.notify_all();
      std::string out(8, '\0');
      wr64(reinterpret_cast<unsigned char*>(out.data()),
           static_cast<uint64_t>(cur));
      if (!reply(fd, true, out)) break;
    } else if (op == 4) {  // DEL
      bool existed;
      {
        std::lock_guard<std::mutex> g(st->mu);
        existed = st->data.erase(key) > 0;
      }
      st->cv.notify_all();
      if (!reply(fd, true, std::string(1, existed ? 1 : 0))) break;
    } else if (op == 5) {  // CLOSE
      reply(fd, true, "");
      break;
    } else {
      reply(fd, false, "bad op");
      break;
    }
  }
  ::close(fd);
}

void accept_loop(Store* st) {
  for (;;) {
    int fd = ::accept(st->listen_fd, nullptr, nullptr);
    if (st->stop.load()) {
      if (fd >= 0) ::close(fd);
      return;
    }
    if (fd < 0) {
      if (errno == EINTR) continue;
      return;  // listener closed
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(serve, st, fd).detach();
  }
}

constexpr int kMaxStores = 64;
Store* g_stores[kMaxStores] = {nullptr};
std::mutex g_stores_mu;

}  // namespace

extern "C" {

// Returns a handle >= 1, or -errno on failure. port 0 = ephemeral;
// host: dotted quad (the caller's bind address — loopback by default,
// NOT INADDR_ANY: the store is unauthenticated).
int pdtpu_store_start(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -errno;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (host == nullptr || host[0] == '\0') {
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
  } else if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -EINVAL;
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);

  auto* st = new Store();
  st->listen_fd = fd;
  st->port = ntohs(addr.sin_port);
  st->accept_thread = std::thread(accept_loop, st);

  std::lock_guard<std::mutex> g(g_stores_mu);
  for (int i = 0; i < kMaxStores; ++i) {
    if (g_stores[i] == nullptr) {
      g_stores[i] = st;
      return i + 1;
    }
  }
  st->stop = true;
  ::shutdown(fd, SHUT_RDWR);
  ::close(fd);
  st->accept_thread.join();
  delete st;
  return -EMFILE;
}

int pdtpu_store_port(int handle) {
  std::lock_guard<std::mutex> g(g_stores_mu);
  if (handle < 1 || handle > kMaxStores || !g_stores[handle - 1]) return -1;
  return g_stores[handle - 1]->port;
}

void pdtpu_store_stop(int handle) {
  Store* st = nullptr;
  {
    std::lock_guard<std::mutex> g(g_stores_mu);
    if (handle < 1 || handle > kMaxStores) return;
    st = g_stores[handle - 1];
    g_stores[handle - 1] = nullptr;
  }
  if (!st) return;
  st->stop = true;
  st->cv.notify_all();
  ::shutdown(st->listen_fd, SHUT_RDWR);
  ::close(st->listen_fd);
  if (st->accept_thread.joinable()) st->accept_thread.join();
  // serve threads are detached and exit as clients disconnect; the Store
  // object is intentionally leaked on stop to avoid racing them — stores
  // are per-process singletons in practice (bounded by kMaxStores).
}

}  // extern "C"

"""ctypes bindings for the native store server (``store.cc``).

Compiled on first use via the shared ``utils.native_build`` helper (the
same pattern as ``paddle_tpu/io/native``); ``start`` returns None when
the toolchain is unavailable so the caller can fall back to the Python
server.
"""
from __future__ import annotations

import ctypes
import os

from ...utils.native_build import build_and_load

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libpaddle_tpu_store.so")
_SRC = os.path.join(_HERE, "store.cc")
_configured = False


def _load():
    global _configured
    lib = build_and_load(_SRC, _SO)
    if lib is not None and not _configured:
        lib.pdtpu_store_start.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.pdtpu_store_start.restype = ctypes.c_int
        lib.pdtpu_store_port.argtypes = [ctypes.c_int]
        lib.pdtpu_store_port.restype = ctypes.c_int
        lib.pdtpu_store_stop.argtypes = [ctypes.c_int]
        lib.pdtpu_store_stop.restype = None
        _configured = True
    return lib


class NativeStoreServer:
    """A running C++ store server (from ``start``)."""

    def __init__(self, handle, lib):
        self._handle = handle
        self._lib = lib

    @property
    def port(self):
        return self._lib.pdtpu_store_port(self._handle)

    def stop(self):
        if self._handle is not None:
            self._lib.pdtpu_store_stop(self._handle)
            self._handle = None


def start(port=0, host="127.0.0.1"):
    """Start a native store server bound to ``host`` (loopback by
    default — the store is unauthenticated); None if the lib can't
    build/load."""
    lib = _load()
    if lib is None:
        return None
    handle = lib.pdtpu_store_start(host.encode(), int(port))
    if handle < 1:
        return None
    return NativeStoreServer(handle, lib)

"""``paddle.distributed.sharding`` parity namespace (reference
``python/paddle/distributed/sharding/group_sharded.py``): re-exports the
GSPMD sharding-stage implementation living with the fleet optimizer
(``fleet/sharding_optimizer.py`` — levels os/os_g/p_g_os = ZeRO stages
1/2/3 as sharding annotations over the ``sharding`` mesh axis)."""
from __future__ import annotations

from ..fleet.sharding_optimizer import group_sharded_parallel  # noqa: F401


def save_group_sharded_model(model, output, optimizer=None):
    """Reference ``save_group_sharded_model :249``: persists the FULL
    (global) state — the single controller already sees global values."""
    import os

    from ... import framework as fw
    os.makedirs(output, exist_ok=True)
    fw.save(model.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        inner = getattr(optimizer, "_inner", optimizer)
        if hasattr(inner, "state_dict"):
            fw.save(inner.state_dict(),
                    os.path.join(output, "model.pdopt"))


__all__ = ["group_sharded_parallel", "save_group_sharded_model"]

"""Elastic membership, re-rendezvous, and hang detection.

Capability analog of the reference ElasticManager
(``python/paddle/distributed/fleet/elastic/manager.py:126``: etcd
heartbeat membership, scale-up/down, rank re-map) and of the collective
hang watchdog (``paddle/phi/core/distributed/comm_task_manager.h:37``
aborts comms after ``pg_timeout``) — TPU-shaped:

* membership rides the framework's own TCPStore instead of etcd: each
  node agent appends itself to a registration log and heartbeats a key;
  the master agent derives the alive set and publishes a new
  ``generation`` (member list + rank re-map) whenever it changes;

  KNOWN LIMITATION (partially mitigated): when node-rank-0's launcher
  HOSTS the store, losing that node still ends rendezvous (the
  reference's external etcd survives its clients, ``manager.py:126``) —
  host the store externally (``--master`` on a machine outside the job)
  to remove that leg. Since the resilience layer landed
  (``paddle_tpu.resilience``; README "Fault tolerance") a store-host
  loss is no longer fatal to the JOB either way: client ops
  retry/backoff through transient blips, and a hard loss is recovered
  by relaunching with ``Model.fit(resume=True)``, which restores from
  the newest COMPLETE versioned checkpoint. The SCAN is no longer a SPOF either way: the
  scanning master heartbeats ``elastic/master_hb``; on loss, standby
  agents elect the alive node first in registration order, which takes
  over scanning and generation publishing (see ``_standby_loop``;
  usurper demotion handles partition-healed double masters);
* on a generation change every agent stops its workers and respawns them
  with the re-mapped ``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM`` env
  (the launcher is the supervisor — on TPU the collectives live inside
  compiled XLA programs, so "abort the comm" means "kill and relaunch
  the process", there is no finer-grained handle);
* hang detection is a per-step progress heartbeat: each worker touches a
  progress file every compiled step (``jit`` does this automatically when
  ``PADDLE_PROGRESS_FILE`` is set; ``report_progress`` for custom loops).
  A desynced SPMD program stops completing steps on every rank, the file
  goes stale, and the launcher kills/restarts within the timeout — the
  TPU analog of the reference's comm-task timeout abort.
"""
from __future__ import annotations

import os
import pickle
import threading
import time

__all__ = ["ElasticManager", "report_progress"]

_REG_COUNT = "elastic/nreg"
_REG_KEY = "elastic/reg/{}"
_HB_KEY = "elastic/hb/{}"
_GEN_LATEST = "elastic/gen_latest"
_MEMBERS_KEY = "elastic/members/{}"
_MASTER_HB = "elastic/master_hb"
# master-maintained set of registration-slot indexes whose node has
# departed (ISSUE 15 satellite: departed nodes' keys must not live
# forever).  The slot KEYS are deleted; the scan skips retired indexes
# without paying a blocking get on each deleted slot.
_RETIRED_KEY = "elastic/reg_retired"
_KEEP_GENS = 3  # elastic/members/<g> history kept for late waiters


def report_progress(step=None):
    """Touch this worker's progress heartbeat (no-op when the launcher did
    not request one). Compiled-step invocations already call this through
    the jit executor; explicit calls serve custom eager loops."""
    path = os.environ.get("PADDLE_PROGRESS_FILE")
    if not path:
        return
    try:
        with open(path, "w") as f:
            f.write("" if step is None else str(step))
    except OSError:
        pass


class ElasticManager:
    """One per node agent (launcher process). The node whose store is
    ``is_master`` also runs the membership scan and publishes generations.
    """

    def __init__(self, store, node_id, is_master, heartbeat_interval=1.0,
                 heartbeat_timeout=5.0, min_nodes=1):
        self.store = store
        self.node_id = str(node_id)
        self.is_master = bool(is_master)
        self.hb_interval = float(heartbeat_interval)
        self.hb_timeout = float(heartbeat_timeout)
        # the FIRST generation waits for min_nodes (the reference waits for
        # np nodes before the initial rendezvous); later scale-downs below
        # it still publish — a survivor must be able to continue
        self.min_nodes = int(min_nodes)
        self._stop = threading.Event()
        self._gen = 0
        self._members: list[str] = []
        self._lock = threading.Lock()
        self._hb_seq = 0
        # liveness is derived from heartbeat CHANGES observed on the
        # master's own clock (remote time.time() would make clock skew >
        # timeout look like death): nid -> (last value, local time seen)
        self._hb_seen: dict[str, tuple[bytes, float]] = {}
        # GC bookkeeping (master role): nodes that ever appeared in a
        # published generation — only THOSE are "departed" when they
        # drop out (a freshly registered joiner whose first heartbeat
        # is still in flight must never be collected); plus the nids
        # already collected, whose heartbeat key is re-deleted each
        # pass in case a partition-healed zombie recreated it
        self._ever_members: set[str] = set()
        self._gc_tombstones: set[str] = set()

    # -------------------------------------------------------------- join --
    def start(self):
        """Register, start heartbeating (and the master scan), then block
        until the first generation that includes this node is published.
        Returns (generation, members)."""
        idx = self.store.add(_REG_COUNT, 1) - 1
        self.store.set(_REG_KEY.format(idx), self.node_id.encode())
        self._reg_idx = idx
        self._beat()
        threading.Thread(target=self._hb_loop, daemon=True).start()
        # every agent runs the role loop: the designated master scans
        # first, and on demotion (usurped by an earlier-registered
        # scanner) falls back to STANDBY — watching the scanner's
        # heartbeat and taking over (alive node first in registration
        # order wins) when it goes silent. No agent ever stops
        # monitoring, so the scan survives any single death as long as
        # the store does (host it externally to cover that leg).
        threading.Thread(target=self._role_loop, daemon=True).start()
        while True:
            gen, members = self.wait_generation(self._gen, timeout=None)
            if self.node_id in members:
                return gen, members

    def stop(self):
        self._stop.set()

    def _role_loop(self):
        # an uncaught error in either role must demote to standby, not
        # kill the thread: a dead role thread with a live _hb_loop makes
        # every standby defer to this node forever (advisor r4, medium)
        while not self._stop.is_set():
            try:
                if self.is_master:
                    self._scan_loop()
                    self.is_master = False
                self._standby_loop()
                return  # clean exit: store gone or stopped
            except Exception:
                self.is_master = False
                self._stop.wait(self.hb_interval)

    # ---------------------------------------------------------- heartbeat --
    def _beat(self):
        self._hb_seq += 1
        self.store.set(_HB_KEY.format(self.node_id),
                       str(self._hb_seq).encode())

    def _hb_loop(self):
        while not self._stop.is_set():
            try:
                self._beat()
                self._ensure_registered()
            except OSError:
                return  # store gone: the job is over
            self._stop.wait(self.hb_interval)

    def _ensure_registered(self):
        """Self-healing counterpart of the master's key GC: the
        documented re-admission path ('dropped: wait to be re-seen',
        ``launch/main.py``) relied on a dropped node's registration
        slot living forever — its resumed heartbeat on the old slot
        was enough for the scan to re-admit it.  The GC retires the
        slot and tombstones the heartbeat key, so a transiently-
        dropped but still-alive node must RE-REGISTER: whenever this
        node is outside the current membership and its slot was
        retired, append a fresh registration slot (only while dropped,
        so the steady-state beat stays one store set)."""
        with self._lock:
            members = list(self._members)
        if not members or self.node_id in members:
            return
        try:
            if getattr(self, "_reg_idx", None) in self._retired():
                idx = self.store.add(_REG_COUNT, 1) - 1
                self.store.set(_REG_KEY.format(idx),
                               self.node_id.encode())
                self._reg_idx = idx
        except OSError:
            pass

    # ------------------------------------------------------- master scan --
    def _retired(self):
        """Slot indexes GC'd by a master (empty set when the key is
        absent or unreadable — a stale read only costs one slow scan
        pass, never correctness)."""
        try:
            return set(pickle.loads(
                self.store.get(_RETIRED_KEY, timeout=0.25)))
        except Exception:
            return set()

    def _reg_slots(self):
        """[(slot, node_id)] of live registration slots in order. A slot
        whose value is not yet set (joiner crashed between add and set)
        is skipped — it must not kill the scan; retired slots (key GC'd)
        are skipped WITHOUT a blocking get."""
        n = self.store.add(_REG_COUNT, 0)
        retired = self._retired()
        out = []
        for i in range(n):
            if i in retired:
                continue
            try:
                nid = self.store.get(_REG_KEY.format(i),
                                     timeout=2.0).decode()
            except (TimeoutError, ValueError):
                continue
            out.append((i, nid))
        return out

    def _registered(self):
        """Ordered, deduped registration log (append-only; re-joins
        re-append, order = first appearance)."""
        seen, out = set(), []
        for _i, nid in self._reg_slots():
            if nid not in seen:
                seen.add(nid)
                out.append(nid)
        return out

    def _fresh_value(self, key, val):
        """True while ``val`` is new or changed within ``hb_timeout`` on
        OUR clock (remote clocks never enter the liveness decision);
        observations are recorded under ``key`` in ``_hb_seen``."""
        now = time.time()
        prev = self._hb_seen.get(key)
        if prev is None or prev[0] != val:
            self._hb_seen[key] = (val, now)
            return True
        return now - prev[1] <= self.hb_timeout

    def _alive(self):
        alive = []
        for nid in self._registered():
            try:
                val = self.store.get(_HB_KEY.format(nid), timeout=1.0)
            except Exception:
                continue
            if self._fresh_value(("hb", nid), val):
                alive.append(nid)
        return alive

    def _scan_loop(self):
        # a PROMOTED scanner inherits a world where the first rendezvous
        # already happened: min_nodes applies only before any generation
        # exists (else a failover below min_nodes waits forever), and
        # ``current`` seeds from the latest published members so an
        # unchanged membership does not trigger a gratuitous respawn
        current: list[str] = []
        published = False
        try:
            g = int(self.store.get(_GEN_LATEST, timeout=1.0).decode())
            if g > 0:
                published = True
                current = pickle.loads(
                    self.store.get(_MEMBERS_KEY.format(g), timeout=1.0))
        except Exception:
            g = 0
        self._ever_members.update(current)
        # a PROMOTED master must also learn nodes that departed under
        # its predecessor, or their keys never qualify for GC: seed
        # _ever_members from the retained membership history too.
        # Generations older than the kept window are unknowable — that
        # residue is bounded by one key set per pre-promotion departure
        # beyond _KEEP_GENS churn events ago.
        for hg in range(max(1, g - _KEEP_GENS), g):
            try:
                self._ever_members.update(pickle.loads(
                    self.store.get(_MEMBERS_KEY.format(hg),
                                   timeout=0.25)))
            except Exception:
                pass
        # seed the retired-slot set so scans never pay the absent-key
        # wait; only-if-absent (an unconditional set would wipe a
        # previous master's retirements at promotion)
        try:
            self.store.get(_RETIRED_KEY, timeout=0.05)
        except Exception:
            try:
                self.store.set(_RETIRED_KEY, pickle.dumps([]))
            except OSError:
                pass
        mseq = 0
        while not self._stop.is_set():
            if self._usurped():
                self.is_master = False  # a lower-index master is alive
                return
            mseq += 1
            try:
                self.store.set(_MASTER_HB,
                               f"{self.node_id}:{mseq}".encode())
                # a scanning master is alive by definition: beat our
                # own node heartbeat from the scan thread too, so a
                # scheduling stall of the hb thread alone can never
                # make the master evict ITSELF from the membership it
                # is publishing
                self._beat()
            except OSError:
                return  # store gone: the job is over
            try:
                alive = self._alive()
            except ConnectionError:
                return  # store gone: the job is over
            except OSError:
                alive = current  # transient: keep the last view
            if not published and len(alive) < self.min_nodes:
                self._stop.wait(self.hb_interval)
                continue
            if alive and alive != current:
                # the publish is guarded like the _MASTER_HB set above: a
                # transient store timeout must NOT kill the scanner (the
                # node's _hb_loop keeps beating, so standbys would defer
                # to a wedged master forever). ``current`` is only
                # advanced on success so a failed publish retries.
                try:
                    gen = self.store.add("elastic/gen", 1)
                    self.store.set(_MEMBERS_KEY.format(gen),
                                   pickle.dumps(alive))
                    self.store.set(_GEN_LATEST, str(gen).encode())
                except ConnectionError:
                    return  # store gone: the job is over
                except OSError:
                    pass    # transient (incl. TimeoutError): retry
                else:
                    current = alive
                    published = True
                    self._ever_members.update(alive)
                    try:
                        self._gc_departed(alive, gen)
                    except Exception:
                        pass  # GC must never kill the scanner
            self._stop.wait(self.hb_interval)

    def _hb_alive_now(self, nid):
        """Freshness re-check at GC time (shares the scan's
        change-on-our-clock observations in ``_hb_seen``)."""
        try:
            val = self.store.get(_HB_KEY.format(nid), timeout=0.25)
        except Exception:
            return False
        return self._fresh_value(("hb", nid), val)

    def _gc_departed(self, members, gen):
        """Master-side key GC (ISSUE 15 satellite): a departed node's
        ``elastic/reg/<i>`` and ``elastic/hb/<nid>`` keys — and old
        ``elastic/members/<g>`` history — otherwise live in the store
        forever, so long-running elastic jobs leak one key set per
        churn event. Runs once per published generation (bounded by the
        registration log length). Only nodes that APPEARED in a
        published generation are collected; a registered joiner whose
        first heartbeat is still in flight is left alone. The retired
        set is written BEFORE the slot keys are deleted so a concurrent
        scan never pays the blocking get on a deleted slot for more
        than one pass. Tombstoned heartbeat keys are re-deleted each
        pass: a partition-healed zombie's heartbeat loop may recreate
        its key, and re-admission requires a fresh registration
        (``_ensure_registered`` — a dropped agent re-appends itself
        when it finds its slot retired). A node whose heartbeat is
        CURRENTLY fresh is never doomed: it either healed before its
        slot was collected (the pre-GC re-admission path — the next
        publish re-includes it) or just re-registered; dooming it in
        the window between its recovery and the next publish would
        strand a healthy agent."""
        retired = self._retired()
        slots = self._reg_slots()
        live_nids = {nid for _i, nid in slots}
        doomed = [(i, nid) for i, nid in slots
                  if nid not in members and nid in self._ever_members
                  and not self._hb_alive_now(nid)]
        if doomed:
            retired.update(i for i, _nid in doomed)
            self.store.set(_RETIRED_KEY, pickle.dumps(sorted(retired)))
            doomed_nids = {nid for _i, nid in doomed}
            for i, _nid in doomed:
                self.store.delete_key(_REG_KEY.format(i))
            # every slot of a doomed nid is doomed together (same
            # membership test), so its hb key has no live claimant
            for nid in doomed_nids:
                self.store.delete_key(_HB_KEY.format(nid))
                self._hb_seen.pop(("hb", nid), None)
            self._gc_tombstones.update(doomed_nids)
        for nid in self._gc_tombstones - live_nids - set(members):
            self.store.delete_key(_HB_KEY.format(nid))
        # membership history: keep the last _KEEP_GENS generations for
        # late wait_generation readers; the probe window below is
        # bounded — older generations were pruned by earlier passes
        # (a freshly promoted master may leave a few ancients behind)
        for g in range(gen - _KEEP_GENS, max(0, gen - _KEEP_GENS - 20),
                       -1):
            self.store.delete_key(_MEMBERS_KEY.format(g))

    # --------------------------------------------------- standby master --
    def _master_hb_node(self):
        """(node_id, raw_value) of the current master heartbeat, or
        (None, None) when absent."""
        try:
            val = self.store.get(_MASTER_HB, timeout=1.0)
        except Exception:
            return None, None
        try:
            return val.decode().rsplit(":", 1)[0], val
        except Exception:
            return None, val

    def _usurped(self):
        """True when ANOTHER scanner earlier in registration order is
        heartbeating — this master stands down (recovery from a network
        partition that elected a second master)."""
        nid, val = self._master_hb_node()
        if nid is None or nid == self.node_id:
            return False
        if not self._fresh_value(("mhb", nid), val):
            return False
        reg = self._registered()
        try:
            return reg.index(nid) < reg.index(self.node_id)
        except ValueError:
            return False

    def _standby_loop(self):
        seen, seen_t = None, time.time()
        while not self._stop.is_set():
            _, val = self._master_hb_node()
            now = time.time()
            if val is not None and val != seen:
                seen, seen_t = val, now
            elif now - seen_t > 2 * self.hb_timeout:
                # scanner is silent on OUR clock. The alive node first in
                # registration order is the rightful successor.
                try:
                    alive = self._alive()
                except Exception:
                    return  # store gone with the master: unrecoverable
                succ = next((n for n in self._registered() if n in alive),
                            None)
                if succ == self.node_id:
                    # seed the usurper-check history with the DEAD
                    # master's last heartbeat at its stale timestamp —
                    # otherwise the new scanner's first _usurped() sees
                    # that value as a fresh first observation and
                    # immediately demotes itself
                    if seen is not None:
                        try:
                            old = seen.decode().rsplit(":", 1)[0]
                            self._hb_seen[("mhb", old)] = (seen, seen_t)
                        except Exception:
                            pass
                    self.is_master = True
                    self._scan_loop()        # runs until demoted/stopped
                    self.is_master = False
                    seen, seen_t = None, time.time()  # re-arm post-term
                # on a FAILED promotion bid keep the staleness clock
                # running: the first _alive() observation of the dead
                # master counts it alive until hb_timeout passes on our
                # clock — re-arming here would double the takeover
                # latency by re-latching the same stale heartbeat
            self._stop.wait(self.hb_interval)

    # ------------------------------------------------------------- watch --
    def wait_generation(self, known_gen, timeout=0.5):
        """Return (gen, members); blocks up to ``timeout`` for a NEWER
        generation than ``known_gen`` (None = wait forever). Falls back to
        the current one on timeout."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            try:
                gen = int(self.store.get(_GEN_LATEST, timeout=1.0).decode())
            except Exception:
                gen = 0
            if gen > known_gen or (deadline and time.time() > deadline):
                break
            if deadline is None:
                time.sleep(self.hb_interval / 2)
            else:
                # never sleep past the caller's deadline: a 20ms-budget
                # poll (the elastic supervisor probes once per train
                # step) must not pay a full 50ms quantum
                time.sleep(max(0.0, min(0.05, deadline - time.time())))
        if gen == 0:
            return 0, []
        with self._lock:
            if gen == self._gen and self._members:
                # unchanged generation: serve the cached members and
                # skip the store round-trip — hot-path polls cost one
                # get, not three
                return gen, list(self._members)
        members = pickle.loads(
            self.store.get(_MEMBERS_KEY.format(gen), timeout=5.0))
        with self._lock:
            self._gen, self._members = gen, members
        return gen, members

    def rank_of(self, members):
        """Re-mapped node rank under the given membership."""
        return members.index(self.node_id)

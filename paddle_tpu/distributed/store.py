"""TCP key-value rendezvous store (reference ``TCPStore``,
``paddle/phi/core/distributed/store/tcp_store.h:121`` — SURVEY D3).

One process (``is_master=True``, conventionally rank 0) hosts the table;
every process (master included) connects as a client. The server is the
NATIVE C++ one (``native/store.cc`` — matching the reference's C++
TCPStore) when the toolchain can build it, with a Python fallback
speaking the identical binary wire protocol, so clients never care which
side serves them:

  request  [1B op][4B klen][key][payload]   (lengths big-endian)
  response [1B ok][4B vlen][value]
  ops: 1 SET([4B vlen][value]) / 2 GET([8B timeout_ms], blocking) /
       3 ADD([8B amount] int counter) / 4 DEL / 5 CLOSE

Used by ``paddle.distributed.rpc`` for worker-info exchange and barriers;
the collective path does NOT need it (the JAX coordination service owns
that bootstrap), matching SURVEY §7's "TCPStore-compatible bootstrap".

``_send_frame``/``_recv_frame`` (length-prefixed pickle) remain here as
shared helpers for the Python-to-Python protocols (rpc, ps) — the store
itself no longer uses pickle so the C++ server can serve it.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time

_OP_SET, _OP_GET, _OP_ADD, _OP_DEL, _OP_CLOSE = 1, 2, 3, 4, 5
_OP_NAMES = {1: "set", 2: "get", 3: "add", 4: "delete", 5: "close"}


# --- generic pickle framing (rpc/ps protocols, NOT the store's) ----------

def _send_frame(sock, obj):
    payload = pickle.dumps(obj)
    sock.sendall(struct.pack("!I", len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("store connection closed")
        buf += chunk
    return buf


def _recv_frame(sock):
    (n,) = struct.unpack("!I", _recv_exact(sock, 4))
    return pickle.loads(_recv_exact(sock, n))


# --- binary store protocol ------------------------------------------------

def _store_request(sock, op, key, payload=b""):
    k = key.encode() if isinstance(key, str) else bytes(key or b"")
    sock.sendall(struct.pack("!BI", op, len(k)) + k + payload)
    ok, vlen = struct.unpack("!BI", _recv_exact(sock, 5))
    value = _recv_exact(sock, vlen) if vlen else b""
    return bool(ok), value


class _Server:
    """Python fallback server — byte-identical protocol to store.cc."""

    def __init__(self, host, port):
        self._data = {}
        self._cv = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    @property
    def port(self):
        return self._sock.getsockname()[1]

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                op, klen = struct.unpack("!BI", _recv_exact(conn, 5))
                if klen > (64 << 20):  # same sanity cap as store.cc
                    return
                key = _recv_exact(conn, klen)  # bytes, like the C++ side
                if op == _OP_SET:
                    (vlen,) = struct.unpack("!I", _recv_exact(conn, 4))
                    if vlen > (256 << 20):  # same cap as store.cc
                        return
                    value = _recv_exact(conn, vlen)
                    with self._cv:
                        self._data[key] = value
                        self._cv.notify_all()
                    conn.sendall(struct.pack("!BI", 1, 0))
                elif op == _OP_GET:
                    (tmo,) = struct.unpack("!q", _recv_exact(conn, 8))
                    with self._cv:
                        ok = self._cv.wait_for(
                            lambda: key in self._data,
                            timeout=tmo / 1000.0)
                        value = self._data.get(key, b"")
                    conn.sendall(struct.pack("!BI", 1 if ok else 0,
                                             len(value)) + value)
                elif op == _OP_ADD:
                    (amount,) = struct.unpack("!q", _recv_exact(conn, 8))
                    with self._cv:
                        prev = self._data.get(key, b"")
                        cur = (struct.unpack("!q", prev)[0]
                               if len(prev) == 8 else 0) + amount
                        self._data[key] = struct.pack("!q", cur)
                        self._cv.notify_all()
                    conn.sendall(struct.pack("!BI", 1, 8)
                                 + struct.pack("!q", cur))
                elif op == _OP_DEL:
                    with self._cv:
                        existed = self._data.pop(key, None) is not None
                        self._cv.notify_all()
                    conn.sendall(struct.pack("!BI", 1, 1)
                                 + (b"\x01" if existed else b"\x00"))
                elif op == _OP_CLOSE:
                    conn.sendall(struct.pack("!BI", 1, 0))
                    return
                else:
                    msg = b"bad op"
                    conn.sendall(struct.pack("!BI", 0, len(msg)) + msg)
                    return
        except (ConnectionError, EOFError, OSError, struct.error):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


def _start_server(host, port):
    """Native C++ server when it builds (PDTPU_NATIVE_STORE=0 forces the
    Python fallback); both bind the caller's host (the store is
    unauthenticated — callers choose the exposure)."""
    if os.environ.get("PDTPU_NATIVE_STORE", "1") != "0":
        from . import native
        srv = native.start(port, host=host)
        if srv is not None:
            return srv
    return _Server(host, port)


class TCPStore:
    """Client (+ optionally the host) of the rendezvous table."""

    def __init__(self, host, port, world_size=1, is_master=False,
                 timeout=300):
        self._server = _start_server(host, port) if is_master else None
        self._addr = (host, self._server.port if is_master else port)
        self._timeout = timeout
        self._lock = threading.Lock()
        deadline = time.time() + timeout
        while True:
            try:
                self._sock = socket.create_connection(self._addr, timeout=5)
                # connect probe used 5s; ops must block indefinitely — the
                # wait budget is enforced SERVER-side (a client-side recv
                # timeout would desync the framed protocol: the late reply
                # would be read as the next call's response)
                self._sock.settimeout(None)
                break
            except OSError:
                if time.time() > deadline:
                    from ..core.errors import StoreTimeoutError
                    raise StoreTimeoutError(
                        f"TCPStore: no master at {self._addr} "
                        f"after {timeout}s "
                        f"[{StoreTimeoutError.error_code}]")
                time.sleep(0.05)

    @property
    def port(self):
        return self._addr[1]

    @property
    def is_native(self):
        """True when this (master) store is served by the C++ server."""
        from .native import NativeStoreServer
        return isinstance(self._server, NativeStoreServer)

    def _reconnect(self):
        """Best-effort fresh connection; a failure here surfaces on the
        next request attempt (which the retry loop owns)."""
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            self._sock = socket.create_connection(self._addr, timeout=5)
            self._sock.settimeout(None)
        except OSError:
            pass

    def _call(self, op, key, payload=b""):
        # Transient failures (peer restarting, connection reset) are
        # retried with exponential backoff after a reconnect
        # (resilience.retry). GET/SET/DEL are idempotent and retry
        # unconditionally. ADD is NOT: a reply lost after the server
        # applied the increment would double-count on resend — one
        # barrier arrival counted twice releases the barrier early and
        # desyncs every later generation — so in practice only
        # injected (pre-send) faults retry for ADD; every error from
        # the exchange itself is tagged in-flight and propagates to the
        # caller. CLOSE never retries (the common failure is the server
        # already being gone).
        from ..resilience import faults
        from ..resilience.retry import retry_call

        # the lock covers one request/response exchange (and the
        # reconnect that swaps the socket) but NOT the backoff sleeps —
        # holding it across retries would stall every other thread's
        # store op (e.g. the elastic heartbeat) behind one blip, turning
        # the transient failure into the peer-death it was meant to
        # ride out
        def attempt():
            with self._lock:
                faults.maybe_raise("store_transient",
                                   _OP_NAMES.get(op, str(op)))
                try:
                    return _store_request(self._sock, op, key, payload)
                except (ConnectionError, OSError) as e:
                    e._pdtpu_in_flight = True  # may have reached server
                    raise

        def non_retryable(e):
            return op == _OP_ADD and getattr(e, "_pdtpu_in_flight",
                                             False)

        def recover(e, k):
            with self._lock:
                self._reconnect()

        if op == _OP_CLOSE:
            return attempt()
        return retry_call(attempt, max_attempts=4, base_delay=0.05,
                          retry_on=(ConnectionError, OSError),
                          giveup=non_retryable, on_retry=recover)

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        self._call(_OP_SET, key,
                   struct.pack("!I", len(value)) + bytes(value))

    def get(self, key, timeout=None):
        # deadline expiry is a SERVED answer ("key never appeared"),
        # not a transport failure: it surfaces as the coded
        # StoreTimeoutError (PDT-E022; TimeoutError subclass) so the
        # elastic/supervisor paths can tell a partition or a peer that
        # never published from a programming error — and it is never
        # retried (retry/backoff stays reserved for ConnectionError)
        from ..core.errors import StoreTimeoutError
        tmo = self._timeout if timeout is None else timeout
        ok, value = self._call(_OP_GET, key,
                               struct.pack("!q", int(tmo * 1000)))
        if not ok:
            raise StoreTimeoutError(
                f"TCPStore.get({key!r}) timed out after {tmo}s "
                f"[{StoreTimeoutError.error_code}]")
        return value

    def add(self, key, amount=1):
        _, value = self._call(_OP_ADD, key, struct.pack("!q", amount))
        return struct.unpack("!q", value)[0]

    def delete_key(self, key):
        _, value = self._call(_OP_DEL, key)
        return value == b"\x01"

    def wait(self, keys, timeout=None):
        """Block until every key exists; ``StoreTimeoutError``
        (PDT-E022) past the deadline, like ``get``."""
        for k in keys:
            self.get(k, timeout)

    def barrier(self, name, world_size, timeout=None):
        """All ``world_size`` callers block until everyone arrived.
        Reusable: arrival counts map to generations, so calling the same
        barrier name once per iteration keeps synchronizing."""
        n = self.add(f"__barrier/{name}", 1)
        gen = (n - 1) // world_size
        if n >= (gen + 1) * world_size:
            self.set(f"__barrier/{name}/done/{gen}", b"1")
        self.get(f"__barrier/{name}/done/{gen}", timeout)

    def close(self):
        try:
            self._call(_OP_CLOSE, "")
        except (ConnectionError, OSError):
            pass
        self._sock.close()
        if self._server is not None:
            self._server.stop()

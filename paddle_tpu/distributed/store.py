"""TCP key-value rendezvous store (reference ``TCPStore``,
``paddle/phi/core/distributed/store/tcp_store.h:121`` — SURVEY D3).

One process (``is_master=True``, conventionally rank 0) hosts the table;
every process (master included) connects as a client. Used by
``paddle.distributed.rpc`` for worker-info exchange and barriers; the
collective path does NOT need it (the JAX coordination service owns that
bootstrap), matching SURVEY §7's "TCPStore-compatible bootstrap" row.

Wire protocol: length-prefixed pickle frames ``(op, key, value)`` →
``(ok, value)``.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
import time


def _send_frame(sock, obj):
    payload = pickle.dumps(obj)
    sock.sendall(struct.pack("!I", len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("store connection closed")
        buf += chunk
    return buf


def _recv_frame(sock):
    (n,) = struct.unpack("!I", _recv_exact(sock, 4))
    return pickle.loads(_recv_exact(sock, n))


class _Server:
    def __init__(self, host, port):
        self._data = {}
        self._cv = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self._stop = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    @property
    def port(self):
        return self._sock.getsockname()[1]

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                op, key, value = _recv_frame(conn)
                if op == "set":
                    with self._cv:
                        self._data[key] = value
                        self._cv.notify_all()
                    _send_frame(conn, (True, None))
                elif op == "get":
                    with self._cv:
                        ok = self._cv.wait_for(
                            lambda: key in self._data, timeout=value)
                        _send_frame(conn, (ok, self._data.get(key)))
                elif op == "add":
                    with self._cv:
                        cur = int(self._data.get(key, 0)) + int(value)
                        self._data[key] = cur
                        self._cv.notify_all()
                    _send_frame(conn, (True, cur))
                elif op == "delete":
                    with self._cv:
                        existed = self._data.pop(key, None) is not None
                        self._cv.notify_all()
                    _send_frame(conn, (True, existed))
                elif op == "close":
                    _send_frame(conn, (True, None))
                    return
                else:
                    _send_frame(conn, (False, f"bad op {op}"))
        except (ConnectionError, EOFError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


class TCPStore:
    """Client (+ optionally the host) of the rendezvous table."""

    def __init__(self, host, port, world_size=1, is_master=False,
                 timeout=300):
        self._server = _Server(host, port) if is_master else None
        self._addr = (host, self._server.port if is_master else port)
        self._timeout = timeout
        self._lock = threading.Lock()
        deadline = time.time() + timeout
        while True:
            try:
                self._sock = socket.create_connection(self._addr, timeout=5)
                # connect probe used 5s; ops must block indefinitely — the
                # wait budget is enforced SERVER-side (a client-side recv
                # timeout would desync the framed protocol: the late reply
                # would be read as the next call's response)
                self._sock.settimeout(None)
                break
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"TCPStore: no master at {self._addr} "
                        f"after {timeout}s")
                time.sleep(0.05)

    @property
    def port(self):
        return self._addr[1]

    def _call(self, op, key, value=None):
        with self._lock:
            _send_frame(self._sock, (op, key, value))
            return _recv_frame(self._sock)

    def set(self, key, value):
        self._call("set", key, value)

    def get(self, key, timeout=None):
        ok, value = self._call("get", key,
                               self._timeout if timeout is None else timeout)
        if not ok:
            raise TimeoutError(f"TCPStore.get({key!r}) timed out")
        return value

    def add(self, key, amount=1):
        return self._call("add", key, amount)[1]

    def delete_key(self, key):
        return self._call("delete", key)[1]

    def wait(self, keys, timeout=None):
        for k in keys:
            self.get(k, timeout)

    def barrier(self, name, world_size, timeout=None):
        """All ``world_size`` callers block until everyone arrived.
        Reusable: arrival counts map to generations, so calling the same
        barrier name once per iteration keeps synchronizing."""
        n = self.add(f"__barrier/{name}", 1)
        gen = (n - 1) // world_size
        if n >= (gen + 1) * world_size:
            self.set(f"__barrier/{name}/done/{gen}", b"1")
        self.get(f"__barrier/{name}/done/{gen}", timeout)

    def close(self):
        try:
            self._call("close", None)
        except (ConnectionError, OSError):
            pass
        self._sock.close()
        if self._server is not None:
            self._server.stop()

"""Process groups over the TPU device mesh.

Capability analog of the reference ProcessGroup stack (SURVEY D1/D3;
``paddle/fluid/distributed/collective/process_group.h:47``,
``python/paddle/distributed/collective.py:186`` ``new_group``) — TPU-native
mechanism: there is no NCCL communicator and no TCPStore rendezvous. A
*group* is a 1-axis ``jax.sharding.Mesh`` over a subset of devices; every
collective lowers to an XLA collective (``psum``/``all_gather``/
``ppermute``…) riding ICI, issued either eagerly through ``jax.shard_map``
or fused into the surrounding jit program. Bootstrap is JAX's distributed
runtime (coordination service) instead of TCPStore.

Single-controller SPMD convention: one Python process drives all devices.
A "rank" is a device index within the group. Tensors passed to the
rank-style communication API (communication.py) carry an explicit leading
rank axis of size ``group.nranks`` — the stack of the per-rank local
tensors that a multi-process framework would hold separately.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

_groups: dict[int, "Group"] = {}
_next_gid = 0


class Group:
    """A communication group = an ordered list of devices + a 1-axis mesh.

    Analog of reference ``python/paddle/distributed/communication/group.py``
    Group (pg + ranks); here the "process group backend" is the XLA
    collective compiler, keyed by the mesh axis name.
    """

    AXIS = "pg"  # every group's mesh uses this axis name

    def __init__(self, gid: int, ranks: Sequence[int], devices):
        self.id = gid
        self.ranks = list(ranks)
        self.nranks = len(ranks)
        self.devices = list(devices)
        self.mesh = Mesh(np.array(self.devices), (self.AXIS,))
        self.name = f"pg_{gid}"

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, global_rank: int) -> int:
        return self.ranks.index(global_rank) if global_rank in self.ranks else -1

    @property
    def process_group(self):  # reference API parity (returns backend handle)
        return self

    def psum_mean(self, flat):
        """ONE cached collective program: psum-mean of a replicated flat
        buffer over this group's axis. Shared by the serialized
        ``DataParallel.apply_collective_grads`` AND the overlap
        scheduler (``distributed/overlap.py``) — one program is what
        makes the two paths bitwise-identical. The jitted shard_map
        wrapper is built once per group so per-step calls hit jax's
        compile cache.  Each ISSUANCE runs under a
        ``collective.psum_mean`` tracing span (observability.tracing;
        the dispatch is async, so the span brackets the launch — the
        wait, if any, shows up in the caller's drain span).  With the
        ``collective_timeout_ms`` flag set the dispatch is additionally
        armed on the collective watchdog (ISSUE 15): a dead/wedged peer
        that wedges the launch raises a coded
        ``CollectiveTimeoutError`` (PDT-E021) with thread stacks in a
        flight record instead of hanging the caller."""
        from ..observability import tracing as _tracing
        from ..observability import watchdog as _watchdog

        f = getattr(self, "_psum_mean_fn", None)
        if f is None:
            from ..core.meshutil import shard_map as smap
            from jax.sharding import PartitionSpec as P
            n = self.nranks
            ax = self.AXIS
            f = jax.jit(smap(
                lambda a, _ax=ax, _n=n: jax.lax.psum(a, _ax) / _n,
                mesh=self.mesh, in_specs=P(), out_specs=P()))
            self._psum_mean_fn = f
            # whole-program audit (collective schedule etc.) once per
            # group program, at the call that first compiles it
            from .. import analysis as _analysis
            _analysis.audit_jitted(f, (flat,),
                                   where=f"collective.psum_mean.g{self.id}")
        with _tracing.span("collective.psum_mean", group=self.id,
                           nranks=self.nranks,
                           size=int(getattr(flat, "size", 0))), \
                _watchdog.arm_collective("collective.psum_mean",
                                         key=f"pg_{self.id}"):
            return f(flat)

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


def _world_devices():
    return list(jax.devices())


def _ensure_world() -> Group:
    if 0 not in _groups:
        devs = _world_devices()
        _groups[0] = Group(0, list(range(len(devs))), devs)
        global _next_gid
        _next_gid = max(_next_gid, 1)
    return _groups[0]


def get_group(gid: int = 0) -> Group:
    """Reference ``collective.py`` ``_get_group_map``/``get_group`` analog."""
    if gid == 0:
        return _ensure_world()
    if gid not in _groups:
        raise ValueError(f"Group {gid} is not initialized by new_group")
    return _groups[gid]


def _get_default_group() -> Group:
    return _ensure_world()


def _resolve(group: Optional[Group]) -> Group:
    if group is None:
        return _ensure_world()
    if isinstance(group, int):
        return get_group(group)
    return group


def new_group(ranks: Optional[Sequence[int]] = None, backend=None,
              timeout=None) -> Group:
    """Create a communication group over device indices ``ranks``.

    Analog of ``python/paddle/distributed/collective.py:186``. The NCCL
    communicator-init broadcast is replaced by mesh construction — XLA
    materializes the communicator lazily at first collective compile.
    """
    global _next_gid
    world = _ensure_world()
    if ranks is None:
        ranks = list(world.ranks)
    ranks = sorted(ranks)
    for r in ranks:
        if r not in world.ranks:
            raise ValueError(f"rank {r} not in world {world.ranks}")
    devs = [world.devices[r] for r in ranks]
    g = Group(_next_gid, ranks, devs)
    _groups[g.id] = g
    _next_gid += 1
    return g


def destroy_process_group(group: Optional[Group] = None):
    """Reference ``collective.py`` analog; drops group bookkeeping."""
    global _groups
    if group is None:
        _groups = {}
    else:
        _groups.pop(_resolve(group).id, None)


def is_initialized() -> bool:
    return 0 in _groups

"""Tensor/function RPC (reference ``python/paddle/distributed/rpc/rpc.py``:
73 init_rpc, :143 rpc_sync, :183 rpc_async; C++ agent ``rpc_agent.h`` —
SURVEY D10).

Each worker runs a threaded TCP server executing pickled
``(fn, args, kwargs)`` requests; worker discovery and barriers go through
the ``TCPStore`` hosted by rank 0 at ``master_endpoint``. Python-level —
the payloads here are control-plane objects and host arrays; bulk tensor
traffic belongs on the ICI collectives, not RPC (same division as the
reference, whose RPC is explicitly a 'minimal' agent).
"""
from __future__ import annotations

import os
import pickle
import socket
import threading
import time
from collections import namedtuple
from concurrent.futures import Future, ThreadPoolExecutor

from ..store import TCPStore, _recv_frame, _send_frame

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_DEFAULT_RPC_TIMEOUT = 30.0

_state = None  # (store, server_sock, infos: {name: WorkerInfo}, me)


class _RpcServer:
    def __init__(self, host):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(128)
        self._pool = ThreadPoolExecutor(max_workers=8)
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    @property
    def port(self):
        return self._sock.getsockname()[1]

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            self._pool.submit(self._serve, conn)

    def _serve(self, conn):
        try:
            fn, args, kwargs = _recv_frame(conn)
            try:
                result = (True, fn(*args, **kwargs))
            except Exception as e:  # ship the failure to the caller
                result = (False, e)
            _send_frame(conn, result)
        except (ConnectionError, EOFError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass
        self._pool.shutdown(wait=False)


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Reference ``rpc.py:73``: start this worker's agent and exchange
    ``WorkerInfo`` with every peer through the master store."""
    global _state
    if _state is not None:
        raise RuntimeError("init_rpc already called")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) \
        if rank is None else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER_ENDPOINT")
    if master_endpoint is None:
        if world_size > 1:
            raise ValueError("init_rpc: master_endpoint (or "
                             "PADDLE_MASTER_ENDPOINT) is required when "
                             "world_size > 1")
        master_endpoint = "127.0.0.1:0"
    host, port = master_endpoint.rsplit(":", 1)

    # The agent executes arbitrary pickled calls from any connecting client
    # and has no authentication (same trust model as store.py): never bind
    # INADDR_ANY. Loopback-only for local jobs; otherwise bind this
    # worker's resolved address so only the job network can reach it.
    if world_size == 1 or host in ("127.0.0.1", "localhost"):
        ip = "127.0.0.1"
    else:
        ip = socket.gethostbyname(socket.gethostname())
    server = _RpcServer(ip)
    store = TCPStore(host, int(port), world_size=world_size,
                     is_master=(rank == 0))
    me = WorkerInfo(name, rank, ip, server.port)
    store.set(f"__rpc/worker/{rank}", pickle.dumps(me))
    infos = {}
    for r in range(world_size):
        info = pickle.loads(store.get(f"__rpc/worker/{r}"))
        if info.name in infos:
            raise ValueError(f"duplicate rpc worker name {info.name!r}")
        infos[info.name] = info
    # _state must be live BEFORE the barrier: a peer may fire an rpc the
    # instant its own barrier releases, racing this thread's assignment
    _state = (store, server, infos, me)
    store.barrier("rpc_init", world_size)
    return me


def _require_state():
    if _state is None:
        raise RuntimeError("call paddle.distributed.rpc.init_rpc first")
    return _state


def get_worker_info(name):
    return _require_state()[2][name]


def get_all_worker_infos():
    return list(_require_state()[2].values())


def get_current_worker_info():
    return _require_state()[3]


def _connect(info, timeout):
    """Connect to a peer agent, retrying transient refusals with
    backoff (resilience.retry) — a worker mid-restart under the elastic
    manager refuses connections for a moment. Only the CONNECT phase
    retries: once the request is on the wire a retry could execute the
    call twice, so send/recv failures propagate to the caller."""
    from ...resilience import faults
    from ...resilience.retry import retry_call

    def attempt():
        faults.maybe_raise("rpc_transient", info.name)
        return socket.create_connection((info.ip, info.port),
                                        timeout=timeout)

    return retry_call(attempt, max_attempts=4, base_delay=0.05,
                      retry_on=(ConnectionError,))


def _invoke(to, fn, args, kwargs, timeout):
    """One call on worker ``to``.  Under an active trace (ISSUE 12) the
    call runs inside an ``rpc.client`` span and the callable ships
    wrapped in :class:`tracing.RemoteTraceContext`, so the server's
    spans land in the caller's trace — same ``(fn, args, kwargs)`` wire
    frame, and with ``PDTPU_METRICS=off`` the payload goes out
    unwrapped (bitwise pre-observability behavior)."""
    from ...core import state as _core_state
    from ...observability import tracing as _tracing
    from ...observability import watchdog as _watchdog

    info = get_worker_info(to)
    # stall watchdog (ISSUE 14): an invoke wedged past the deadline
    # (dead peer mid-frame, socket timeout longer than anyone wants to
    # wait blind) gets every thread's stack + a flight record — no
    # interrupt; the socket timeout still owns cancellation
    wd = _watchdog.arm("rpc.invoke",
                       float(_core_state.get_flag("watchdog_stall_ms")),
                       key=str(to))
    try:
        with _tracing.span("rpc.client", to=str(to),
                           fn=getattr(fn, "__name__", str(fn))):
            ctx = _tracing.inject()
            if ctx is not None:
                fn = _tracing.RemoteTraceContext(ctx, fn)
            conn = _connect(info, timeout)
            if timeout and timeout > 0:
                conn.settimeout(timeout)
            try:
                _send_frame(conn,
                            (fn, tuple(args or ()), dict(kwargs or {})))
                ok, value = _recv_frame(conn)
            finally:
                conn.close()
    finally:
        wd.disarm()
    if not ok:
        raise value
    return value


def rpc_sync(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """Blocking remote call: run ``fn(*args, **kwargs)`` on worker ``to``
    and return its result (reference ``rpc.py:143``)."""
    return _invoke(to, fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """Non-blocking remote call returning a Future with ``wait()``
    (reference ``rpc.py:183``).  The caller's trace context is captured
    HERE, on the calling thread — the worker thread's thread-local
    context is empty, so without the re-attach the ``rpc.client`` span
    would start a disconnected root trace instead of joining the
    caller's (``attach(None)`` is a no-op when no span is open)."""
    from ...observability import tracing as _tracing

    ctx = _tracing.inject()
    fut = Future()

    def run():
        try:
            with _tracing.attach(ctx):
                fut.set_result(_invoke(to, fn, args, kwargs, timeout))
        except Exception as e:
            fut.set_exception(e)

    threading.Thread(target=run, daemon=True).start()
    fut.wait = lambda t=None: fut.result(t)  # reference Future API
    return fut


def shutdown(graceful=True):
    """Reference ``rpc.py`` shutdown: barrier (graceful) then stop. The
    master's store must outlive every peer's final store op, so rank 0
    waits for all closed-signals before tearing the store down."""
    global _state
    if _state is None:
        return
    store, server, infos, me = _state
    n = len(infos)
    if graceful and n > 1:
        store.barrier("rpc_shutdown", n)
    if n > 1:
        if me.rank == 0:
            deadline = time.time() + 30
            while (store.add("__rpc/closed", 0) < n - 1
                   and time.time() < deadline):
                time.sleep(0.01)
        else:
            store.add("__rpc/closed", 1)
    server.stop()
    store.close()
    _state = None

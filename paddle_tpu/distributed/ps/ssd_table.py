"""Disk-spilling sparse table (capability analog of the reference's
SSD/rocksdb-backed tables, ``paddle/fluid/distributed/ps/table/
ssd_sparse_table.cc`` + ``depends/rocksdb``): a bounded in-memory LRU of
hot rows over a log-structured file store for the cold tail, so the
embedding table can exceed the server's memory budget.

Store layout: one append-only data file of raw row blobs
(value + optimizer-state arrays) with an in-memory ``{id: (offset,
length)}`` index; overwrites append and orphan the old blob; compaction
rewrites live blobs into a fresh file once garbage exceeds live bytes
(the LSM analog, collapsed to one level — no merge hierarchy needed for
a value-per-key workload).

PERFORMANCE HONESTY: this is the capability analog of the reference's
rocksdb path, correctness-grade, not throughput-grade. Pull/push batch
their numpy work (misses are read in file-offset order, new rows are
initialized in one RNG call, and the optimizer update runs as one
vectorized ``apply_batch`` pass), but the store is still a single
Python-locked file with a stop-the-world full-file compaction. A
production embedding workload (millions of rows/s) would need sharded
C++ stores with background incremental compaction and overlapped I/O —
the reference spends ``ssd_sparse_table.cc`` + rocksdb on exactly
that."""
from __future__ import annotations

import os
import struct
import tempfile
import threading
from collections import OrderedDict

import numpy as np

from .service import _Accessor


class _LogStore:
    def __init__(self, path):
        self.path = path
        self.f = open(path, "w+b")
        self.index: dict[int, tuple[int, int]] = {}
        self.live_bytes = 0
        self.garbage_bytes = 0

    def put(self, key, blob: bytes):
        old = self.index.get(key)
        if old is not None:
            self.garbage_bytes += old[1]
            self.live_bytes -= old[1]
        self.f.seek(0, os.SEEK_END)
        off = self.f.tell()
        self.f.write(blob)
        self.index[key] = (off, len(blob))
        self.live_bytes += len(blob)
        if self.garbage_bytes > max(self.live_bytes, 1 << 20):
            self._compact()

    def get(self, key):
        off, length = self.index[key]
        self.f.seek(off)
        return self.f.read(length)

    def __contains__(self, key):
        return key in self.index

    def _compact(self):
        newf = open(self.path + ".compact", "w+b")
        newidx = {}
        for k, (off, length) in self.index.items():
            self.f.seek(off)
            blob = self.f.read(length)
            newidx[k] = (newf.tell(), length)
            newf.write(blob)
        self.f.close()
        os.replace(self.path + ".compact", self.path)
        self.f = newf
        self.index = newidx
        self.garbage_bytes = 0

    def close(self):
        try:
            self.f.close()
            os.unlink(self.path)
        except OSError:
            pass


class SsdSparseTable:
    """Same pull/push surface as the in-memory ``_SparseTable``; rows
    beyond ``max_mem_rows`` spill to the log store (LRU eviction)."""

    def __init__(self, dim, accessor, initializer_scale=0.01, seed=0,
                 max_mem_rows=4096, path=None):
        self.dim = dim
        self.accessor = _Accessor(**accessor)
        self.max_mem_rows = int(max_mem_rows)
        self._rng = np.random.default_rng(seed)
        self.lock = threading.Lock()
        # hot set: id -> (value, state), LRU order
        self._hot: OrderedDict[int, tuple] = OrderedDict()
        if path is None:
            fd, path = tempfile.mkstemp(prefix="pdtpu_ssd_", suffix=".tbl")
            os.close(fd)
        self.store = _LogStore(path)
        self._state_keys = sorted(self.accessor.init_state((dim,)).keys())

    # ------------------------------------------------------ serialization
    def _encode(self, value, state) -> bytes:
        parts = [value.astype(np.float32).tobytes()]
        for k in self._state_keys:
            v = state[k]
            if isinstance(v, np.ndarray):
                parts.append(v.astype(np.float32).tobytes())
            else:                      # scalar counters (adam "t")
                parts.append(struct.pack("<q", int(v)))
        return b"".join(parts)

    def _decode(self, blob: bytes):
        n = self.dim * 4
        value = np.frombuffer(blob[:n], np.float32).copy()
        state = self.accessor.init_state((self.dim,))
        off = n
        for k in self._state_keys:
            v = state[k]
            if isinstance(v, np.ndarray):
                state[k] = np.frombuffer(blob[off:off + n],
                                         np.float32).copy()
                off += n
            else:
                state[k] = struct.unpack("<q", blob[off:off + 8])[0]
                off += 8
        return value, state

    # ------------------------------------------------------------- rows
    def _evict_if_needed(self):
        while len(self._hot) > self.max_mem_rows:
            k, (v, s) = self._hot.popitem(last=False)  # LRU
            self.store.put(k, self._encode(v, s))

    def _load_batch(self, ids):
        """Materialize all ids into the hot set in one pass: hot hits
        move-to-end, disk misses are read in file-offset order (sequential
        I/O), and never-seen rows are initialized with one RNG call."""
        misses = list(dict.fromkeys(
            i for i in ids if i not in self._hot))
        disk = [i for i in misses if i in self.store.index]
        fresh = [i for i in misses if i not in self.store.index]
        for i in sorted(disk, key=lambda k: self.store.index[k][0]):
            self._hot[i] = self._decode(self.store.get(i))
        if fresh:
            init = self._rng.normal(
                0, 0.01, (len(fresh), self.dim)).astype(np.float32)
            for i, row in zip(fresh, init):
                # per-row copy: a view would pin the whole batch array
                # in memory for as long as any single row stays hot
                self._hot[i] = (row.copy(),
                                self.accessor.init_state((self.dim,)))
        for i in ids:
            self._hot.move_to_end(i)
        # NOTE: eviction runs in pull/push AFTER the access — a batch
        # larger than max_mem_rows may transiently overshoot the budget
        # but must stay resident while being read/updated

    # ------------------------------------------------------------ api
    def pull(self, ids):
        ids = [int(i) for i in ids]
        with self.lock:
            self._load_batch(ids)
            out = np.stack([self._hot[i][0] for i in ids])
            self._evict_if_needed()
            return out

    def push(self, ids, grads):
        ids = [int(i) for i in ids]
        grads = np.asarray(grads, np.float32)
        with self.lock:
            self._load_batch(ids)
            # duplicate ids in one push must apply sequentially (each
            # update sees the previous one) — batch only the unique-id
            # fast path
            if len(set(ids)) == len(ids):
                entries = [self._hot[i] for i in ids]
                values = np.stack([e[0] for e in entries])
                states = [e[1] for e in entries]
                out = self.accessor.apply_batch(values, grads, states)
                for i, row, s in zip(ids, out, states):
                    self._hot[i] = (row.copy(), s)
            else:
                for i, g in zip(ids, grads):
                    value, state = self._hot[i]
                    self._hot[i] = (
                        self.accessor.apply(value, g, state), state)
            self._evict_if_needed()

    @property
    def mem_rows(self):
        return len(self._hot)

    @property
    def disk_rows(self):
        return len(self.store.index)

"""PS server/client transport + tables (reference
``ps/service/brpc_ps_server.h`` / ``brpc_ps_client.h``, tables
``ps/table/`` memory_sparse_table / memory_dense_table).
"""
from __future__ import annotations

import socket
import threading

import numpy as np

from ..store import _recv_frame, _send_frame


class _Accessor:
    """Server-side optimizer state for one table (the reference's
    sparse/dense 'accessor' concept, ``ps/table/sparse_sgd_rule.h``)."""

    def __init__(self, rule="sgd", lr=0.01, beta1=0.9, beta2=0.999,
                 eps=1e-8):
        self.rule, self.lr = rule, lr
        self.beta1, self.beta2, self.eps = beta1, beta2, eps

    def init_state(self, shape):
        if self.rule == "adam":
            return {"m": np.zeros(shape, np.float32),
                    "v": np.zeros(shape, np.float32), "t": 0}
        return {}

    def apply(self, value, grad, state):
        if self.rule == "sum":
            return value + grad
        if self.rule == "adam":
            state["t"] += 1
            t = state["t"]
            state["m"] = self.beta1 * state["m"] + (1 - self.beta1) * grad
            state["v"] = (self.beta2 * state["v"]
                          + (1 - self.beta2) * grad * grad)
            mhat = state["m"] / (1 - self.beta1 ** t)
            vhat = state["v"] / (1 - self.beta2 ** t)
            return value - self.lr * mhat / (np.sqrt(vhat) + self.eps)
        return value - self.lr * grad  # sgd

    def apply_batch(self, values, grads, states):
        """Vectorized ``apply`` over n stacked rows (one numpy pass
        instead of n Python-level calls). ``states`` is the list of
        per-row state dicts; mutated in place like ``apply``."""
        if self.rule == "sum":
            return values + grads
        if self.rule == "adam":
            m = np.stack([s["m"] for s in states])
            v = np.stack([s["v"] for s in states])
            t = np.array([[s["t"] + 1] for s in states], np.float64)
            m = self.beta1 * m + (1 - self.beta1) * grads
            v = self.beta2 * v + (1 - self.beta2) * grads * grads
            mhat = m / (1 - self.beta1 ** t)
            vhat = v / (1 - self.beta2 ** t)
            out = values - self.lr * mhat / (np.sqrt(vhat) + self.eps)
            for i, s in enumerate(states):
                # copies, not views — a view would pin the whole batch's
                # m/v arrays alive through one surviving row
                s["m"], s["v"] = m[i].copy(), v[i].copy()
                s["t"] = s["t"] + 1
            return out.astype(np.float32)
        return values - self.lr * grads  # sgd


class _DenseTable:
    def __init__(self, shape, accessor, n_workers, sync):
        self.value = np.zeros(shape, np.float32)
        self.accessor = _Accessor(**accessor)
        self.state = self.accessor.init_state(shape)
        self.n_workers, self.sync = n_workers, sync
        self.version = 0
        self._pending = None
        self._n_pending = 0
        self.cv = threading.Condition()

    def push(self, grad):
        """Returns the version that will contain this push — callers pull
        with min_version=<return> to observe their own update (sync mode:
        the step completes when the n-th worker pushes)."""
        with self.cv:
            if not self.sync:
                self.value = self.accessor.apply(self.value, grad,
                                                 self.state)
                self.version += 1
                target = self.version
            else:
                self._pending = grad if self._pending is None \
                    else self._pending + grad
                self._n_pending += 1
                target = self.version + 1
                if self._n_pending >= self.n_workers:
                    self.value = self.accessor.apply(
                        self.value, self._pending / self.n_workers,
                        self.state)
                    self._pending, self._n_pending = None, 0
                    self.version += 1
            self.cv.notify_all()
            return target

    def pull(self, min_version=0, timeout=60):
        with self.cv:
            if not self.cv.wait_for(lambda: self.version >= min_version,
                                    timeout=timeout):
                raise TimeoutError(
                    f"dense pull: version {min_version} not reached")
            return self.value.copy(), self.version


class _SparseTable:
    def __init__(self, dim, accessor, initializer_scale=0.01, seed=0):
        self.dim = dim
        self.accessor = _Accessor(**accessor)
        self.rows = {}
        self.state = {}
        self._rng = np.random.default_rng(seed)
        self.lock = threading.Lock()

    def _row(self, i):
        i = int(i)
        if i not in self.rows:
            self.rows[i] = self._rng.normal(
                0, 0.01, self.dim).astype(np.float32)
            self.state[i] = self.accessor.init_state((self.dim,))
        return self.rows[i]

    def pull(self, ids):
        with self.lock:
            return np.stack([self._row(i) for i in ids])

    def push(self, ids, grads):
        with self.lock:
            for i, g in zip(ids, grads):
                i = int(i)
                self._row(i)
                self.rows[i] = self.accessor.apply(self.rows[i], g,
                                                   self.state[i])


class PsServer:
    """One PS node (reference ``brpc_ps_server.h``): hosts the shard of
    every table that maps to this server index."""

    def __init__(self, endpoint, n_workers=1, sync=False):
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(128)
        self.n_workers, self.sync = n_workers, sync
        self._dense = {}
        self._sparse = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._barrier_count = {}
        self._barrier_cv = threading.Condition()
        self._thread = None

    @property
    def port(self):
        return self._sock.getsockname()[1]

    # -- lifecycle ----------------------------------------------------
    def run(self):
        """Blocking accept loop (reference fleet.run_server). Polls the
        stop flag: close() alone does not wake a blocked accept()."""
        self._sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def start(self):
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for t in self._sparse.values():  # release spill files (ssd tables)
            close = getattr(getattr(t, "store", None), "close", None)
            if close:
                close()

    # -- request handling ---------------------------------------------
    def _serve(self, conn):
        try:
            while True:
                req = _recv_frame(conn)
                try:
                    reply = (True, self._handle(*req))
                except Exception as e:  # surface, don't kill the socket
                    reply = (False, f"{type(e).__name__}: {e}")
                _send_frame(conn, reply)
        except (ConnectionError, EOFError, OSError):
            pass
        finally:
            conn.close()

    def _handle(self, op, name, *args):
        if op == "create_dense":
            shape, accessor = args
            with self._lock:
                if name not in self._dense:
                    self._dense[name] = _DenseTable(
                        shape, accessor, self.n_workers, self.sync)
            return True
        if op == "init_dense":
            (value,) = args
            self._dense[name].value = np.array(value, np.float32)
            return True
        if op == "create_sparse":
            dim, accessor, seed = args
            accessor = dict(accessor)
            table_class = accessor.pop("table_class", "memory")
            max_mem_rows = accessor.pop("max_mem_rows", 4096)
            with self._lock:
                if name not in self._sparse:
                    if table_class == "ssd":
                        from .ssd_table import SsdSparseTable
                        self._sparse[name] = SsdSparseTable(
                            dim, accessor, seed=seed,
                            max_mem_rows=max_mem_rows)
                    else:
                        self._sparse[name] = _SparseTable(dim, accessor,
                                                          seed=seed)
            return True
        if op == "sparse_stats":
            t = self._sparse[name]
            return (getattr(t, "mem_rows", len(getattr(t, "rows", {}))),
                    getattr(t, "disk_rows", 0))
        if op == "pull_dense":
            (min_version,) = args
            return self._dense[name].pull(min_version)
        if op == "push_dense":
            (grad,) = args
            return self._dense[name].push(np.asarray(grad))
        if op == "sparse_dim":
            return self._sparse[name].dim
        if op == "pull_sparse":
            (ids,) = args
            return self._sparse[name].pull(ids)
        if op == "push_sparse":
            ids, grads = args
            self._sparse[name].push(ids, np.asarray(grads))
            return True
        if op == "barrier":
            (n,) = args
            with self._barrier_cv:
                count = self._barrier_count.get(name, 0) + 1
                self._barrier_count[name] = count
                gen = (count - 1) // n  # generation: barriers are reusable
                self._barrier_cv.notify_all()
                ok = self._barrier_cv.wait_for(
                    lambda: self._barrier_count[name] >= (gen + 1) * n,
                    timeout=120)
            if not ok:
                raise TimeoutError(
                    f"ps barrier {name!r}: peers missing after 120s")
            return True
        if op == "stop":
            self.stop()
            return True
        raise ValueError(f"unknown ps op {op}")


class PsClient:
    """Worker-side connection to every PS node (reference
    ``brpc_ps_client.h``). Sparse ids shard ``id % n_servers``; a dense
    table lives on ``sum(name_bytes) % n_servers`` (stable across
    processes, unlike Python's salted hash)."""

    def __init__(self, endpoints, connect_timeout=300):
        import time

        self.endpoints = list(endpoints)
        self._conns = []
        self._sparse_dims = {}
        deadline = time.time() + connect_timeout
        for ep in self.endpoints:
            host, port = ep.rsplit(":", 1)
            while True:  # retry: workers may come up before their servers
                try:
                    conn = socket.create_connection((host, int(port)),
                                                    timeout=5)
                    break
                except OSError:
                    if time.time() > deadline:
                        raise TimeoutError(
                            f"PsClient: no server at {ep} after "
                            f"{connect_timeout}s")
                    time.sleep(0.1)
            # ops block without a client deadline: waits (barrier, sync
            # pull) are bounded server-side; a client recv timeout would
            # leave the late reply in the stream and desync the framing
            conn.settimeout(None)
            self._conns.append(conn)
        self._locks = [threading.Lock() for _ in self._conns]

    def _call(self, server, *req):
        with self._locks[server]:
            _send_frame(self._conns[server], req)
            ok, value = _recv_frame(self._conns[server])
        if not ok:
            raise RuntimeError(
                f"ps server {self.endpoints[server]}: {value}")
        return value

    def _dense_home(self, name):
        return sum(name.encode()) % len(self._conns)

    # -- dense ---------------------------------------------------------
    def create_dense_table(self, name, shape, rule="sgd", lr=0.01, **kw):
        self._call(self._dense_home(name), "create_dense", name,
                   tuple(shape), dict(rule=rule, lr=lr, **kw))

    def init_dense(self, name, value):
        self._call(self._dense_home(name), "init_dense", name,
                   np.asarray(value, np.float32))

    def pull_dense(self, name, min_version=0):
        value, version = self._call(self._dense_home(name), "pull_dense",
                                    name, min_version)
        return value, version

    def push_dense(self, name, grad):
        return self._call(self._dense_home(name), "push_dense", name,
                          np.asarray(grad, np.float32))

    # -- sparse --------------------------------------------------------
    def create_sparse_table(self, name, dim, rule="sgd", lr=0.01, seed=0,
                            **kw):
        self._sparse_dims[name] = dim
        for s in range(len(self._conns)):
            self._call(s, "create_sparse", name, dim,
                       dict(rule=rule, lr=lr, **kw), seed + s)

    def pull_sparse(self, name, ids):
        ids = np.asarray(ids).reshape(-1)
        n = len(self._conns)
        if len(ids) == 0:
            if name not in self._sparse_dims:  # attach-only client
                self._sparse_dims[name] = self._call(0, "sparse_dim", name)
            return np.empty((0, self._sparse_dims[name]), np.float32)
        parts, idxs = [], []
        for s in range(n):
            mask = (ids % n) == s
            if mask.any():
                parts.append(self._call(s, "pull_sparse", name,
                                        ids[mask].tolist()))
                idxs.append(np.flatnonzero(mask))
        dim = parts[0].shape[1]
        rows = np.empty((len(ids), dim), np.float32)
        for part, idx in zip(parts, idxs):
            rows[idx] = part
        return rows

    def push_sparse(self, name, ids, grads):
        ids = np.asarray(ids).reshape(-1)
        grads = np.asarray(grads, np.float32)
        n = len(self._conns)
        for s in range(n):
            mask = (ids % n) == s
            if mask.any():
                self._call(s, "push_sparse", name, ids[mask].tolist(),
                           grads[mask])

    # -- control -------------------------------------------------------
    def barrier(self, name, n_workers):
        self._call(0, "barrier", name, n_workers)

    def stop_servers(self):
        for s in range(len(self._conns)):
            try:
                self._call(s, "stop", None)
            except (ConnectionError, OSError):
                pass

    def close(self):
        for c in self._conns:
            c.close()


class GeoSparseMirror:
    """Geo-async sparse training (reference geo mode,
    ``python/paddle/distributed/fleet/meta_optimizers/parameter_server_optimizer.py``
    geo strategy + ``ps/table`` geo recorder): the worker trains a LOCAL
    copy of the embedding rows and every ``geo_steps`` updates ships the
    accumulated DELTAS to the servers (accessor rule ``sum``), then
    refreshes its touched rows from the global table. Between syncs,
    training is fully local — the async trade that geo-SGD makes.
    """

    def __init__(self, client, name, dim, geo_steps=10, lr=0.01, seed=0,
                 max_mirror_rows=100_000):
        self.client = client
        self.name = name
        self.dim = dim
        self.geo_steps = int(geo_steps)
        self.lr = lr
        self.max_mirror_rows = int(max_mirror_rows)
        client.create_sparse_table(name, dim, rule="sum", seed=seed)
        self._local: dict[int, np.ndarray] = {}
        self._base: dict[int, np.ndarray] = {}
        self._touched: set[int] = set()
        self._step = 0

    def _ensure(self, ids):
        missing = [i for i in ids if int(i) not in self._local]
        if missing:
            rows = self.client.pull_sparse(self.name, missing)
            for i, r in zip(missing, rows):
                self._local[int(i)] = r.copy()
                self._base[int(i)] = r.copy()

    def lookup(self, ids):
        ids = np.asarray(ids).reshape(-1)
        self._ensure(ids)
        return np.stack([self._local[int(i)] for i in ids])

    def update(self, ids, grads):
        """Local SGD on the mirrored rows; geo-sync when due."""
        ids = np.asarray(ids).reshape(-1)
        self._ensure(ids)
        for i, g in zip(ids, np.asarray(grads, np.float32)):
            self._local[int(i)] = self._local[int(i)] - self.lr * g
            self._touched.add(int(i))
        self._step += 1
        if self._step % self.geo_steps == 0:
            self.sync()

    def sync(self, full_refresh=False):
        """Push accumulated deltas and refresh the rows touched since the
        last sync (per-sync traffic scales with the working set, not the
        lifetime vocabulary). ``full_refresh=True`` re-pulls every
        mirrored row — the end-of-training convergence pull."""
        touched = [i for i in self._touched
                   if not np.array_equal(self._local[i], self._base[i])]
        if touched:
            deltas = np.stack([self._local[i] - self._base[i]
                               for i in touched])
            self.client.push_sparse(self.name, touched, deltas)
        refresh = list(self._local) if full_refresh else touched
        if refresh:
            rows = self.client.pull_sparse(self.name, refresh)
            for i, r in zip(refresh, rows):
                self._local[int(i)] = r.copy()
                self._base[int(i)] = r.copy()
        # evict BEFORE clearing the touched set so just-refreshed hot rows
        # survive the mirror cap (cold rows go first)
        if len(self._local) > self.max_mirror_rows:
            for i in [k for k in self._local
                      if k not in self._touched][:len(self._local)
                                                 - self.max_mirror_rows]:
                self._local.pop(i, None)
                self._base.pop(i, None)
        self._touched.clear()

"""Parameter-server mode (SURVEY D9/D24/C26; reference
``paddle/fluid/distributed/ps/`` brpc PS + ``python/paddle/distributed/ps/``
+ fleet PS role flow ``fleet/base/role_maker.py:854-909``).

The reference's PS is a brpc service hosting dense and sparse tables with
server-side optimizers ("accessors"), pulled/pushed by CPU trainers — the
sparse-embedding path is the reason PS exists (tables too big for any one
worker). This TPU-native build keeps that capability with a threaded TCP
server per PS node (same framed-pickle wire as ``distributed.store``),
sparse rows sharded ``id % n_servers`` across server nodes:

- dense tables:   whole-table pull / grad push, server-side SGD/Adam/sum;
- sparse tables:  row pull by id (lazy-init), row-grad push, per-row
                  Adam/SGD state on the server;
- sync mode:      the server folds ``n_workers`` pushes into one update
                  and bumps the table version; workers pull-by-version
                  (the reference's sync a_sync=False semantics);
- async mode:     every push applies immediately (a_sync=True, default).

Worker-side surface: ``SparseEmbedding`` (the distributed lookup-table
layer), ``PSOptimizer`` (push grads / pull fresh params around an eager
step), and the fleet role flow (``fleet.init(is_collective=False)``,
``is_server/run_server/init_worker/stop_worker``).
"""
from .service import GeoSparseMirror, PsClient, PsServer
from .ssd_table import SsdSparseTable
from .layers import SparseEmbedding
from .optimizer import PSOptimizer

__all__ = ["PsServer", "PsClient", "SparseEmbedding", "PSOptimizer",
           "GeoSparseMirror", "SsdSparseTable"]

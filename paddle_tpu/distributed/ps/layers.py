"""Worker-side PS layers: the distributed lookup table (reference
``paddle.static.nn.sparse_embedding`` / ``ps/table/memory_sparse_table`` —
embeddings too large for any single worker)."""
from __future__ import annotations

import numpy as np

from ...core.dispatch import apply, unwrap
from ...core.tensor import Tensor
from ...nn.layer import Layer


class SparseEmbedding(Layer):
    """Embedding whose rows live on the parameter servers.

    Forward pulls the batch's unique rows into a local leaf tensor and
    gathers from it, so autograd produces a dense grad for exactly those
    rows; ``PSOptimizer.step`` pushes the row grads back and the server
    applies its accessor rule. Eager-mode by design — the pull is a host
    round-trip, the PS workflow of the reference's CPU trainers (SURVEY
    C26); keep TPU-resident embeddings on the GSPMD path instead.
    """

    def __init__(self, client, name, size, rule="adam", lr=0.01, seed=0):
        super().__init__()
        self.client = client
        self.table = name
        self.num_embeddings, self.embedding_dim = size
        client.create_sparse_table(name, self.embedding_dim, rule=rule,
                                   lr=lr, seed=seed)
        self._pending = []  # (unique ids, rows leaf) awaiting grad push

    def forward(self, ids):
        from ...core import state
        idv = np.asarray(unwrap(ids)).reshape(-1)
        uniq, inv = np.unique(idv, return_inverse=True)
        train = state.is_grad_enabled() and self.training
        rows = Tensor(self.client.pull_sparse(self.table, uniq),
                      stop_gradient=not train)
        if train:  # eval/no-grad pulls need no push-back bookkeeping
            self._pending.append((uniq, rows))

        import jax.numpy as jnp

        def gather(rv):
            out = jnp.take(rv, jnp.asarray(inv), axis=0)
            return out.reshape(tuple(np.shape(unwrap(ids)))
                               + (self.embedding_dim,))

        return apply("ps_sparse_embedding", gather, rows)

    def push_gradients(self):
        """Push accumulated row grads (called by PSOptimizer.step)."""
        for uniq, rows in self._pending:
            if rows.grad is not None:
                self.client.push_sparse(self.table, uniq,
                                        np.asarray(rows.grad._read()))
        self._pending.clear()

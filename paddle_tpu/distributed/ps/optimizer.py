"""PS-mode optimizer: push grads, pull fresh params (reference
``fleet/meta_optimizers/ps_optimizer.py`` + the async communicator
``ps/service/communicator/`` collapsed into explicit push/pull)."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .layers import SparseEmbedding


class PSOptimizer:
    """Server-side optimization for dense params + sparse tables.

    ``step()`` pushes every parameter's grad to its dense table and every
    ``SparseEmbedding``'s row grads to its sparse table, then pulls the
    updated dense values back into the local tensors. In sync mode the
    pull waits for the post-update table version, giving the reference's
    synchronous semantics; async mode (a_sync) pulls whatever is newest.
    """

    def __init__(self, client, parameters=None, layers=None, rule="sgd",
                 lr=0.01, sync=False, prefix="param"):
        self.client = client
        self.sync = sync
        self._params = []
        self._embeddings = []
        params = list(parameters or [])
        if layers is not None:
            for sub in layers.sublayers(include_self=True):
                if isinstance(sub, SparseEmbedding):
                    self._embeddings.append(sub)
            params = params or list(layers.parameters())
        for i, p in enumerate(params):
            name = f"{prefix}/{i}"
            client.create_dense_table(name, tuple(p.shape), rule=rule,
                                      lr=lr)
            client.init_dense(name, np.asarray(p._read()))
            self._params.append((name, p))

    def step(self):
        versions = {}  # push returns the version CONTAINING this update
        for name, p in self._params:
            if p.grad is not None:
                versions[name] = self.client.push_dense(
                    name, np.asarray(p.grad._read()))
        for e in self._embeddings:
            e.push_gradients()
        for name, p in self._params:
            want = versions.get(name, 0) if self.sync else 0
            value, _ = self.client.pull_dense(name, min_version=want)
            p._write(value.reshape(np.asarray(p._read()).shape))

    def clear_grad(self):
        for _, p in self._params:
            p.clear_gradient()

from .api import (  # noqa: F401
    ProcessMesh, Shard, Replicate, Partial, Placement,
    shard_tensor, dtensor_from_fn, reshard, shard_layer, shard_optimizer,
    to_static, Strategy, get_mesh, set_mesh,
)

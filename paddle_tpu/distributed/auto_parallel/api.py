"""Semi-automatic parallelism: the GSPMD path.

Capability analog of the reference semi-auto API (SURVEY D6/D7/D20;
``python/paddle/distributed/auto_parallel/api.py:126`` shard_tensor, ``:304``
reshard, ``:403`` shard_layer, ``:960`` shard_optimizer; DistTensor
``paddle/phi/core/distributed/auto_parallel/dist_tensor.h:39``; SPMD rules
``paddle/phi/infermeta/spmd_rules/``). TPU-native mechanism: the reference
implements SPMD propagation + an explicit reshard engine (pairwise
``{r,s,p}_to_{r,s,p}`` conversions) in C++; on TPU that whole machinery IS
XLA's GSPMD partitioner. ``shard_tensor`` pins a ``jax.sharding.
NamedSharding``; every op — eager (per-op jit) or captured by
``jit.to_static`` — propagates shardings through XLA's SPMD pass, which
also decides and inserts the collectives the reference's reshard functions
hand-code. ``Partial`` placements are metadata here: a single-controller
global-view array always holds summed values; unsummed partials exist only
inside compiled programs where XLA places the ``psum``.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.dispatch import apply
from ...core.tensor import Tensor, Parameter
from ...nn.layer import Layer


# --- placements (reference placement_types.h vocabulary) -------------------

class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("S", self.dim))


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("R")


class Partial(Placement):
    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and \
            other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("P", self.reduce_type))


# --- ProcessMesh -----------------------------------------------------------

class ProcessMesh:
    """Reference ``auto_parallel/process_mesh.py`` ProcessMesh: an N-D
    arrangement of device (process) ids with named dims. Wraps a
    ``jax.sharding.Mesh`` over the actual devices."""

    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 shape=None, process_ids=None):
        arr = np.asarray(mesh if mesh is not None else
                         np.asarray(process_ids).reshape(shape))
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError("dim_names must match mesh ndim")
        self._ids = arr
        self.dim_names = list(dim_names)
        devices = np.asarray(jax.devices(), dtype=object)
        dev_arr = np.empty(arr.shape, dtype=object)
        for idx in np.ndindex(arr.shape):
            dev_arr[idx] = devices[arr[idx]]
        self.jmesh = Mesh(dev_arr, tuple(self.dim_names))

    @property
    def shape(self):
        return list(self._ids.shape)

    @property
    def ndim(self):
        return self._ids.ndim

    @property
    def process_ids(self):
        return self._ids.flatten().tolist()

    @property
    def mesh(self):
        return self._ids

    def get_dim_size(self, name: str) -> int:
        return self._ids.shape[self.dim_names.index(name)]

    def get_rank_by_dim_and_process_id(self, dim, process_id):
        axis = self.dim_names.index(dim) if isinstance(dim, str) else dim
        loc = np.argwhere(self._ids == process_id)
        return int(loc[0][axis]) if len(loc) else -1

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._ids, other._ids)
                and self.dim_names == other.dim_names)

    def __hash__(self):
        return hash((self._ids.tobytes(), tuple(self.dim_names)))

    def __repr__(self):
        return f"ProcessMesh({self._ids.tolist()}, {self.dim_names})"


_global_mesh: Optional[ProcessMesh] = None


def set_mesh(mesh: ProcessMesh):
    """Reference ``auto_parallel/api.py`` set_mesh / fleet.auto global mesh."""
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _global_mesh


def _to_partition_spec(mesh, placements) -> P:
    """placements[i] describes mesh dim i (reference convention). Build the
    PartitionSpec over tensor dims; multiple mesh dims may shard one tensor
    dim (they compose in mesh-dim order). ``mesh`` may be a ProcessMesh or
    a raw jax Mesh."""
    dim_names = mesh.dim_names if isinstance(mesh, ProcessMesh) \
        else list(mesh.axis_names)
    by_tensor_dim: dict[int, list[str]] = {}
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            by_tensor_dim.setdefault(pl.dim, []).append(
                dim_names[mesh_dim])
    if not by_tensor_dim:
        return P()
    nspec = max(by_tensor_dim) + 1
    entries = []
    for d in range(nspec):
        names = by_tensor_dim.get(d)
        if not names:
            entries.append(None)
        elif len(names) == 1:
            entries.append(names[0])
        else:
            entries.append(tuple(names))
    return P(*entries)


def _normalize_placements(mesh: ProcessMesh, placements):
    if placements is None:
        return [Replicate() for _ in range(mesh.ndim)]
    placements = list(placements)
    while len(placements) < mesh.ndim:
        placements.append(Replicate())
    return placements


def shard_tensor(data, mesh: ProcessMesh, placements,
                 dtype=None, place=None, stop_gradient=None) -> Tensor:
    """Reference ``auto_parallel/api.py:126``: global tensor -> DistTensor.

    Lays the value out as a ``NamedSharding`` over the mesh; the array stays
    a single global-view ``jax.Array`` whose shards live on the right chips.
    """
    if isinstance(data, Tensor):
        if stop_gradient is None:
            stop_gradient = data.stop_gradient
        val = data._read()
        is_param = isinstance(data, Parameter)
    else:
        val = jnp.asarray(data, dtype=dtype)
        is_param = False
        if stop_gradient is None:
            stop_gradient = True
    placements = _normalize_placements(mesh, placements)
    spec = _to_partition_spec(mesh, placements)
    if not isinstance(val, jax.core.Tracer):
        val = jax.device_put(val, NamedSharding(mesh.jmesh, spec))
    if is_param:
        out = Parameter(val, trainable=not stop_gradient)
    else:
        out = Tensor(val, stop_gradient=stop_gradient)
    out._dist = (mesh, placements)
    if isinstance(data, Tensor) and data.name:
        out.name = data.name
    return out


def dtensor_from_fn(fn: Callable, mesh: ProcessMesh, placements,
                    *args, **kwargs) -> Tensor:
    """Reference ``auto_parallel/api.py`` dtensor_from_fn."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x: Tensor, mesh: ProcessMesh, placements) -> Tensor:
    """Reference ``auto_parallel/api.py:304`` + the C++ reshard engine
    (``{r,s,p}_to_{r,s,p}_reshard_function.cc``): here a single
    ``device_put`` — XLA plans the all-gather/slice/all-to-all movement.
    Differentiable: the cotangent reshards back through the same machinery.
    """
    placements = _normalize_placements(mesh, placements)
    spec = _to_partition_spec(mesh, placements)
    sharding = NamedSharding(mesh.jmesh, spec)

    def _reshard_impl(v):
        return jax.device_put(v, sharding)

    out = apply("reshard", _reshard_impl, x)
    out._dist = (mesh, placements)
    return out


def shard_layer(layer: Layer, process_mesh: ProcessMesh,
                shard_fn: Optional[Callable] = None,
                input_fn: Optional[Callable] = None,
                output_fn: Optional[Callable] = None) -> Layer:
    """Reference ``auto_parallel/api.py:403``: convert a Layer's parameters
    to dist tensors in place. ``shard_fn(name, sublayer, mesh)`` mutates
    sublayer params via ``shard_tensor``; default replicates everything."""

    def _default_shard(name, sub, mesh):
        for pname, p in list(sub._parameters.items()):
            if p is not None and not p.is_dist():
                sub._parameters[pname] = _as_dist_param(p, mesh,
                                                       [Replicate()])

    fn = shard_fn or _default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    # shard_fn implementations may have replaced parameter objects wholesale;
    # normalize: any plain Tensor left in _parameters becomes dist-replicated
    for name, sub in layer.named_sublayers(include_self=True):
        for pname, p in list(sub._parameters.items()):
            if p is not None and not p.is_dist():
                sub._parameters[pname] = _as_dist_param(
                    p, process_mesh, [Replicate()])
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lyr, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lyr, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


def _as_dist_param(p: Tensor, mesh, placements) -> Parameter:
    """In-place sharding. ``mesh`` may be a ProcessMesh or a raw jax Mesh
    (fleet layers store the latter); ``placements`` a placement list or a
    ready PartitionSpec."""
    jmesh = mesh.jmesh if isinstance(mesh, ProcessMesh) else mesh
    if isinstance(placements, P):
        spec = placements
    else:
        if isinstance(mesh, ProcessMesh):
            placements = _normalize_placements(mesh, placements)
        spec = _to_partition_spec(mesh, placements)
    v = p._read()
    if isinstance(v, jax.ShapeDtypeStruct):
        # lazy (LazyGuard) parameter: annotate the abstract value
        v = jax.ShapeDtypeStruct(v.shape, v.dtype,
                                 sharding=NamedSharding(jmesh, spec))
    elif not isinstance(v, jax.core.Tracer):
        v = jax.device_put(v, NamedSharding(jmesh, spec))
    # mutate in place so optimizer param identity is preserved
    p._write(v)
    p._dist = (mesh, placements)
    return p


def shard_parameter(p: Tensor, mesh: ProcessMesh, placements) -> Tensor:
    """Convenience used by shard_fn implementations: shard an existing
    Parameter in place (identity-preserving, unlike shard_tensor)."""
    return _as_dist_param(p, mesh, placements)


class _ShardOptimizer:
    """Reference ``auto_parallel/api.py:960`` shard_optimizer: makes the
    optimizer state distributed. Accumulators created by ``zeros_like``
    inherit the parameter's sharding automatically (XLA); ``shard_fn(name,
    param, accumulator) -> placements`` overrides — e.g. ZeRO-style sharding
    of moments along dp."""

    def __init__(self, optimizer, shard_fn=None):
        self._inner = optimizer
        self._shard_fn = shard_fn

    def step(self):
        self._inner.step()
        if self._shard_fn is not None:
            self._apply_shard_fn()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        out = self._inner.minimize(loss, startup_program, parameters,
                                   no_grad_set)
        if self._shard_fn is not None:
            self._apply_shard_fn()
        return out

    def _apply_shard_fn(self):
        opt = self._inner
        params = {id(p): p for p in getattr(opt, "_parameters", [])}
        for acc_name, store in opt._accumulators.items():
            for pid, acc in store.items():
                p = params.get(pid)
                if p is None or acc.is_dist():
                    continue
                mesh = p.process_mesh or _global_mesh
                if mesh is None:
                    continue
                placements = self._shard_fn(acc_name, p, acc)
                if placements is not None:
                    _as_dist_param(acc, mesh, placements)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def shard_optimizer(optimizer, shard_fn=None):
    return _ShardOptimizer(optimizer, shard_fn)


# --- strategy + dist to_static --------------------------------------------

class Strategy:
    """Reference ``auto_parallel/strategy.py``: config container. Most knobs
    (fusion, reshard planning) are XLA's; kept for API parity."""

    class _Flags:
        def __init__(self, **kw):
            self.__dict__.update(kw)

    def __init__(self, config=None):
        self.sharding = Strategy._Flags(enable=False, stage=1, degree=8)
        self.fused_passes = Strategy._Flags(enable=False, fused_passes_list=[])
        self.gradient_merge = Strategy._Flags(enable=False, k_steps=1)
        self.pipeline = Strategy._Flags(enable=False, schedule_mode="1F1B",
                                        micro_batch_size=1,
                                        accumulate_steps=1)
        self.amp = Strategy._Flags(enable=False, dtype="bfloat16", level="O2")
        if config:
            for k, v in config.items():
                cur = getattr(self, k, None)
                if isinstance(v, dict) and isinstance(cur, Strategy._Flags):
                    cur.__dict__.update(v)
                else:
                    setattr(self, k, v)


def to_static(layer_or_fn, loader=None, loss=None, optimizer=None,
              strategy=None):
    """Reference ``auto_parallel/api.py`` dist-aware to_static: the regular
    jit capture already compiles sharded steps into one SPMD program, so
    this simply defers to ``paddle_tpu.jit.to_static``."""
    from ... import jit as _jit
    return _jit.to_static(layer_or_fn)

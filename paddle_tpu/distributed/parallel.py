"""Parallel environment + DataParallel.

Capability analog of ``python/paddle/distributed/parallel.py`` (SURVEY D5;
``init_parallel_env`` at ``:943``, ``DataParallel`` at ``:202``, C++
``EagerReducer`` ``collective/reducer.h:88``). TPU-native mechanism: the
single controller already sees every chip, so "initializing the parallel
environment" creates the world group over ``jax.devices()`` (multi-host:
``jax.distributed.initialize`` has already federated the processes via the
TPU coordination service — the TCPStore analog).

``DataParallel`` is GSPMD data parallelism, not gradient bucketing: the
global batch is sharded over the ``dp`` mesh axis while parameters stay
replicated; XLA inserts the gradient ``psum`` where the replicated weights
meet the sharded batch — a fused, ICI-riding equivalent of the reference's
bucketed overlapped all-reduce. Loss parity with single-device runs is
exact because the loss is computed on the global batch.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer import Layer
from . import collective as _coll


class ParallelEnv:
    """Reference ``parallel.py`` ParallelEnv: rank/world topology view."""

    @property
    def rank(self):
        return jax.process_index() * max(jax.local_device_count(), 1)

    @property
    def local_rank(self):
        return 0

    @property
    def world_size(self):
        return len(jax.devices())

    @property
    def nranks(self):
        return self.world_size

    @property
    def device_id(self):
        return jax.devices()[0].id

    @property
    def dev_id(self):
        return self.device_id

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")


_dist_initialized = False


def init_parallel_env():
    """Reference ``parallel.py:943``: bring up the default process group.

    Multi-host: when the launcher's env contract is present
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``,
    set by ``paddle_tpu.distributed.launch --master ...``), federate the
    per-host controllers via ``jax.distributed.initialize`` — the
    coordination service replaces TCPStore rendezvous; afterwards
    ``jax.devices()`` spans the whole pod and every collective/GSPMD path
    is pod-wide automatically.
    """
    global _dist_initialized
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if addr and nproc > 1 and not _dist_initialized:
        already = getattr(jax._src.distributed.global_state, "client",
                          None) is not None
        if not already:
            jax.distributed.initialize(
                coordinator_address=addr, num_processes=nproc,
                process_id=int(os.environ.get("JAX_PROCESS_ID", "0")))
        _dist_initialized = True
    return _coll._ensure_world()


def get_rank(group=None) -> int:
    """First global rank this controller drives (0 on single-host; the
    reference returns the per-process rank — under single-controller SPMD
    one process drives all local ranks)."""
    if group is not None:
        g = _coll._resolve(group)
        r = ParallelEnv().rank
        return g.get_group_rank(r)
    return ParallelEnv().rank


def get_world_size(group=None) -> int:
    if group is not None:
        return _coll._resolve(group).nranks
    return ParallelEnv().world_size


def is_available() -> bool:
    return True


def parallel_helper_is_initialized():
    return _coll.is_initialized()


class DataParallel(Layer):
    """Reference ``parallel.py:202`` DataParallel — GSPMD mechanism.

    Wraps a Layer: parameters are pinned replicated over the dp mesh, and
    every positional batch input is sharded along dim 0. In eager mode each
    op executes SPMD per-op; under ``jit.to_static`` the whole step compiles
    to one partitioned XLA program. Gradient synchronization is implicit
    (psum inserted by XLA), so ``no_sync`` is a no-op context kept for API
    parity — there is no bucketed EagerReducer to pause.
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, overlap_grad_sync=None):
        super().__init__()
        self._layers = layers
        self.group = _coll._resolve(group)
        self.find_unused_parameters = find_unused_parameters
        self.comm_buffer_size = comm_buffer_size
        mesh = Mesh(_np_devices(self.group), ("dp",))
        self._mesh = mesh
        self._replicate(mesh)
        # overlap-scheduled bucketed grad sync (distributed/overlap.py):
        # per-param hooks dispatch one psum-mean per size-capped bucket
        # DURING backward; apply_collective_grads() drains it. Bitwise-
        # identical to the serialized sync (same collective program,
        # elementwise reduction). Default from the dp_overlap_grad_sync
        # flag; nranks==1 needs no sync at all.
        if overlap_grad_sync is None:
            from ..core import state as _state
            overlap_grad_sync = _state.get_flag("dp_overlap_grad_sync")
        self._overlap = None
        if overlap_grad_sync and self.group.nranks > 1:
            from .overlap import OverlapGradSync
            self._overlap = OverlapGradSync(self)

    def _replicate(self, mesh):
        repl = NamedSharding(mesh, P())
        for p in self._layers.parameters():
            v = p._read()
            if not isinstance(v, jax.core.Tracer):
                p._write(jax.device_put(v, repl))
        for _, buf in _named_buffers(self._layers):
            v = buf._read()
            if not isinstance(v, jax.core.Tracer):
                buf._write(jax.device_put(v, repl))

    def _shard_input(self, x):
        if isinstance(x, Tensor):
            v = x._read()
            if (not isinstance(v, jax.core.Tracer)
                    and v.ndim > 0 and v.shape[0] % self.group.nranks == 0):
                sh = NamedSharding(self._mesh, P("dp"))
                t = Tensor(jax.device_put(v, sh),
                           stop_gradient=x.stop_gradient)
                return t
        return x

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_input(x) for x in inputs)
        return self._layers(*inputs, **kwargs)

    def no_sync(self):
        """Under GSPMD there is no bucketed reducer to pause, so this is
        a no-op — unless the overlap scheduler is on, in which case its
        hooks stand down for the scope (gradient-accumulation
        micro-steps must not trigger early bucket collectives)."""
        if self._overlap is not None:
            return self._overlap.pause()
        import contextlib
        return contextlib.nullcontext()

    def scale_loss(self, loss):
        # loss is already the global-batch mean under GSPMD
        return loss

    def _psum_mean(self, flat):
        """ONE collective program: psum-mean of a replicated flat buffer
        over the group. Delegates to ``Group.psum_mean`` — the overlap
        scheduler reduces through the SAME cached program, which is what
        keeps the two sync schedules bitwise-identical."""
        return self.group.psum_mean(flat)

    def apply_collective_grads(self):
        """Bucketed gradient synchronization: ONE collective per dtype
        bucket (the reference EagerReducer's coalesced all-reduce,
        ``collective/reducer.h:88``), not one per parameter.

        Under GSPMD the backward already reduced the grads (replicated
        params x sharded batch), so the psum-mean here is value-
        preserving — it exists for the explicit-sync training idiom and
        for fault-drill re-syncs. When a fused optimizer
        (``optimizer/flat.py``) already holds the grads in flat buckets,
        those buffers are all-reduced DIRECTLY with zero repacking.
        ``self._last_sync_collectives`` reports how many collectives the
        call issued (observability + tests).

        With the overlap scheduler on, most buckets were already
        dispatched DURING backward — this call drains the pending
        results (``OverlapGradSync.finish``) and runs the serialized
        path only for parameters the scheduler did not cover (unused
        params, tracer grads)."""
        from ..observability import tracing as _tracing
        from ..observability import watchdog as _watchdog

        params = [p for p in self._layers.parameters()
                  if not p.stop_gradient and p.grad is not None
                  and not getattr(p, "no_sync", False)]
        self._last_sync_collectives = 0
        # collective watchdog (ISSUE 15): with collective_timeout_ms
        # set, a grad sync wedged behind a dead peer raises PDT-E021
        # with a flight dump instead of hanging the training loop
        with _tracing.span("dp.grad_sync", nranks=self.group.nranks,
                           overlap=self._overlap is not None), \
                _watchdog.arm_collective("dp.grad_sync",
                                         key=f"pg_{self.group.id}"):
            self._apply_collective_grads(params)

    def _apply_collective_grads(self, params):
        # body of apply_collective_grads, under its dp.grad_sync span
        if not params or self.group.nranks == 1:
            if self._overlap is not None:
                self._overlap.finish()
            return
        if self._overlap is not None:
            synced = self._overlap.finish()
            self._last_sync_collectives += self._overlap.last["buckets"]
            params = [p for p in params if id(p) not in synced]
            if not params:
                return
        remaining = []
        by_store: dict[int, tuple] = {}
        for p in params:
            fv = p.grad._flat_view
            if fv is not None and fv[1] >= 0 and fv[0].kind == "grad" \
                    and not fv[0]._dirty:
                st, ps = by_store.setdefault(id(fv[0]), (fv[0], []))
                ps.append(p)
            else:
                remaining.append(p)
        for st, ps in by_store.values():
            if len(ps) != len(st.group.params):
                remaining.extend(ps)  # partial bucket: repack below
                continue
            # zero-repack fast path: the fused optimizer's flat grad
            # bucket IS the comm buffer
            st.set_flat(self._psum_mean(st.storage._read()))
            self._last_sync_collectives += 1
        buckets: dict = {}
        for p in remaining:
            v = p.grad._read()
            buckets.setdefault(jnp.dtype(v.dtype), []).append((p, v))
        for vals in buckets.values():
            flat = jnp.concatenate([jnp.ravel(v) for _, v in vals]) \
                if len(vals) > 1 else jnp.ravel(vals[0][1])
            red = self._psum_mean(flat)
            off = 0
            for p, v in vals:
                n = v.size
                p.grad._write(red[off:off + n].reshape(v.shape))
                off += n
            self._last_sync_collectives += 1

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    # attribute passthrough so wrapped models keep their API
    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


def _np_devices(group):
    import numpy as np
    return np.array(group.devices)


def _named_buffers(layer):
    for name, buf in layer.named_buffers():
        yield name, buf

"""paddle_tpu.distributed — TPU-native distributed stack.

Mirrors ``paddle.distributed`` (SURVEY §2.2): rank-style collectives
(D22/D1), process groups (D1/D3), DataParallel (D5), the semi-auto GSPMD
API (D6/D7/D20), fleet hybrid-parallel orchestration (D13-D17), and
distributed checkpoint (D23) — all lowered to XLA collectives over the
device mesh instead of NCCL/TCPStore.
"""
from .collective import (  # noqa: F401
    Group, new_group, get_group, destroy_process_group, is_initialized,
)
from .communication import (  # noqa: F401
    ReduceOp, all_reduce, all_gather, broadcast, reduce, scatter, gather,
    reduce_scatter, alltoall, alltoall_single, send, recv, isend, irecv,
    P2POp, batch_isend_irecv, barrier, wait, get_backend,
)
from .parallel import (  # noqa: F401
    ParallelEnv, init_parallel_env, get_rank, get_world_size, is_available,
    DataParallel,
)
from .auto_parallel import (  # noqa: F401
    ProcessMesh, Shard, Replicate, Partial, Placement,
    shard_tensor, dtensor_from_fn, reshard, shard_layer, shard_optimizer,
    Strategy, get_mesh, set_mesh,
)
from .auto_parallel.api import shard_parameter, to_static  # noqa: F401

from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from . import launch  # noqa: F401
from . import sharding  # noqa: F401
from . import rpc  # noqa: F401
from . import ps  # noqa: F401
from . import auto_tuner  # noqa: F401
from . import elastic  # noqa: F401
from .ps_dataset import (InMemoryDataset, QueueDataset,  # noqa: F401
                         multi_slot_parser)
from .store import TCPStore  # noqa: F401
from .checkpoint import save_state_dict, load_state_dict  # noqa: F401


def get_world_process_group():
    from .collective import _ensure_world
    return _ensure_world()


def spawn(func, args=(), nprocs=-1, **kwargs):
    """Reference ``paddle.distributed.spawn``: under single-controller SPMD
    there is nothing to spawn — the one process drives all chips. Runs
    ``func`` directly (multi-host pods launch one process per host via the
    launcher, not spawn)."""
    return func(*args)

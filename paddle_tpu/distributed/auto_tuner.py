"""Parallel-config auto-tuner (SURVEY D21; reference
``python/paddle/distributed/auto_tuner/`` — ``tuner.py:21`` AutoTuner with
``search_once``/``add_cfg``, candidate generation ``utils.py:160``, pruning
rules ``prune.py``).

Searches (dp, mp, pp, sharding-stage, micro-batch, recompute) over an
N-chip budget: candidates are pruned by divisibility and a bf16 HBM
estimate, then measured — on TPU a "trial" is just timing a jit-compiled
step on the target mesh (no multi-process relaunch needed, the launcher
hook of the reference collapses away). Best = lowest step time.
"""
from __future__ import annotations

import itertools
import time
from typing import Callable, Dict, List, Optional

__all__ = ["AutoTuner", "default_candidates", "prune"]


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def default_candidates(tuner_cfg: Dict) -> List[Dict]:
    """Reference ``utils.py:160``: the dp/mp/pp/sharding/mbs/recompute
    grid for ``num_gpus`` (chips here)."""
    n = int(tuner_cfg["num_gpus"])
    batch = int(tuner_cfg.get("global_batch_size", 1))
    cands = []
    for dp, mp, pp in itertools.product(_divisors(n), repeat=3):
        if dp * mp * pp != n:
            continue
        for stage in tuner_cfg.get("sharding_stage", [0]):
            for mbs in _divisors(max(batch // dp, 1)):
                for rc in tuner_cfg.get("use_recompute", [False]):
                    cands.append({
                        "dp_degree": dp, "mp_degree": mp,
                        "pp_degree": pp, "sharding_stage": stage,
                        "micro_batch_size": mbs, "use_recompute": rc,
                    })
    return cands


def prune(tuner_cfg: Dict, cur_cfg: Dict) -> Optional[str]:
    """Divisibility + memory pruning (reference ``prune.py`` rules
    collapsed). Returns the prune reason, or None to keep."""
    n = int(tuner_cfg["num_gpus"])
    dp, mp, pp = (cur_cfg["dp_degree"], cur_cfg["mp_degree"],
                  cur_cfg["pp_degree"])
    if dp * mp * pp != n:
        return "num_gpus"
    hidden = tuner_cfg.get("hidden_size")
    if hidden and hidden % mp:
        return "mp"  # prune_by_mp: heads/hidden must divide
    heads = tuner_cfg.get("num_attention_heads")
    if heads and heads % mp:
        return "mp"
    layers = tuner_cfg.get("num_layers")
    if layers and layers % pp:
        return "pp"  # prune_by_pp
    batch = tuner_cfg.get("global_batch_size")
    if batch:
        local = batch // dp
        if batch % dp or local % cur_cfg["micro_batch_size"]:
            return "mbs"  # prune_by_mbs
    limit = tuner_cfg.get("max_mem_usage")  # bytes per chip
    if limit and hidden and layers:
        vocab = tuner_cfg.get("vocab_size", 0)
        params = (12 * layers * hidden * hidden + vocab * hidden)
        # model params split over mp*pp; optimizer states additionally
        # split over dp when sharding (ZeRO) is on
        shard = dp if cur_cfg["sharding_stage"] else 1
        # bf16 weights + fp32 master+moments on the optimizer shard
        per_chip = params * (2 + 12 / max(shard, 1)) / (mp * pp)
        if per_chip > limit:
            return "mem_estimation"  # prune_by_memory_estimation
    return None


class AutoTuner:
    """Reference ``tuner.py:21``: iterate candidate configs, record
    metrics, report the best. ``tune(run_fn)`` drives the whole loop;
    ``search_once``/``add_cfg`` expose the reference's incremental API.
    """

    def __init__(self, tuner_cfg: Dict):
        self.tuner_cfg = dict(tuner_cfg)
        self.metric = tuner_cfg.get("metric_cfg", {}).get(
            "name", "step_time")
        self.history: List[Dict] = []
        self.pruned: List[Dict] = []
        self._queue = []
        for cfg in default_candidates(self.tuner_cfg):
            reason = prune(self.tuner_cfg, cfg)
            if reason is None:
                self._queue.append(cfg)
            else:
                self.pruned.append({**cfg, "pruned_by": reason})
        self._cur = 0

    @property
    def search_space_size(self):
        return len(self._queue)

    def search_once(self) -> Optional[Dict]:
        """Next un-measured candidate, or None when exhausted."""
        if self._cur >= len(self._queue):
            return None
        cfg = self._queue[self._cur]
        self._cur += 1
        return dict(cfg)

    def add_cfg(self, cfg: Dict):
        """Record a measured config (must carry the metric key or
        ``error``)."""
        self.history.append(dict(cfg))

    def best_cfg(self) -> Optional[Dict]:
        ok = [h for h in self.history
              if h.get(self.metric) is not None and "error" not in h]
        return min(ok, key=lambda h: h[self.metric]) if ok else None

    def tune(self, run_fn: Callable[[Dict], float],
             warmup: int = 1, iters: int = 3) -> Optional[Dict]:
        """Measure every candidate with ``run_fn(cfg) -> step_fn`` (or a
        directly-measured float). Failed trials are recorded, not fatal
        (the reference marks OOM/error runs and continues)."""
        while (cfg := self.search_once()) is not None:
            try:
                out = run_fn(cfg)
                if callable(out):
                    for _ in range(warmup):
                        out()
                    t0 = time.perf_counter()
                    for _ in range(iters):
                        out()
                    cfg[self.metric] = (time.perf_counter() - t0) / iters
                else:
                    cfg[self.metric] = float(out)
            except Exception as e:  # config infeasible — keep searching
                cfg["error"] = f"{type(e).__name__}: {e}"
            self.add_cfg(cfg)
        return self.best_cfg()

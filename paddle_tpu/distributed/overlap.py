"""Overlap-scheduled bucketed DP gradient synchronization (ISSUE 11).

Capability analog of the reference ``EagerReducer``
(``paddle/fluid/distributed/collective/reducer.h:88``): the reducer
registers a hook per parameter, groups gradients into size-capped
buckets in the order the BACKWARD WALK finalizes them (last layers
first), and launches one fused all-reduce per bucket as soon as the
bucket's last gradient lands — so the collectives run concurrently with
the remaining backward compute instead of serialized after it.

TPU-native mechanism: the autograd engine (``core/autograd.py``) calls a
tensor's hooks exactly when its gradient is FINAL (all consumers
processed — the reference's ``GradNodeAccumulation`` hook point), and
jax dispatch is asynchronous — issuing the bucket's ``psum-mean``
program during backward puts the ICI collective on the device stream
while eager backward keeps dispatching compute behind it. ``finish()``
(called from ``DataParallel.apply_collective_grads``) drains the
pending results and writes them back; only time the collectives had NOT
already overlapped is spent blocking there.

Parity contract: ``psum-mean`` is elementwise, so bucket composition
does not change values — the overlap-scheduled result is BITWISE
identical to the serialized one-bucket-per-dtype sync (asserted by
``tests/test_overlap.py`` on a CPU mesh), and both run the same cached
collective program (``collective.Group.psum_mean``).

Observability (PR8 registry):

* ``train.comm_ms``      — per-bucket collective wall time histogram
  (dispatch -> result ready)
* ``train.overlap_frac`` — fraction of total collective time that ran
  concurrent with backward (1.0 = fully hidden; serialized sync is 0.0)
* ``train.bucket_syncs`` — bucket collectives issued
* ``train.overlap_bytes``— gradient bytes synced through the scheduler

Tracing (ISSUE 12): each bucket's async launch runs under a
``dp.bucket_sync`` span (which nests a ``collective.psum_mean`` span
from ``collective.Group.psum_mean``) and the blocking drain in
:meth:`OverlapGradSync.finish` under ``dp.grad_sync_drain`` — so an
exported Perfetto trace shows exactly which collectives launched
during backward and how long the drain blocked.  ``fleet_snapshot``
(``observability/aggregate.py``) surfaces ``overlap_frac`` PER RANK,
labeled, so a straggling rank's unhidden communication is attributable
from one merged view.

The scheduler is EAGER-path machinery: under jit capture the whole step
compiles into one program and XLA/GSPMD already schedules the grad
psums into the backward — hooks see tracers and stand down.
"""
from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import tensor as _tm
from ..core.tensor import Tensor
from ..observability import tracing as _tracing

__all__ = ["OverlapGradSync"]


def _metrics_handles():
    from ..observability import metrics as m
    if not m.enabled():
        return None
    reg = m.registry()
    return (
        reg.histogram("train.comm_ms",
                      "DP grad-sync collective wall time per bucket",
                      m.LATENCY_BUCKETS_MS),
        reg.gauge("train.overlap_frac",
                  "fraction of grad-sync collective time overlapped "
                  "with backward compute (last finished step)"),
        reg.counter("train.bucket_syncs",
                    "bucketed grad-sync collectives issued"),
        reg.counter("train.overlap_bytes",
                    "gradient bytes synced by the overlap scheduler"),
    )


class OverlapGradSync:
    """Bucket-ready overlap scheduler for one :class:`DataParallel`.

    ``bucket_mb`` caps a bucket's payload (the reference DataParallel's
    ``comm_buffer_size`` knob, reused): smaller buckets start their
    collectives earlier in the backward walk; one giant bucket degrades
    to the serialized schedule. Buckets never mix dtypes.
    """

    def __init__(self, dp, bucket_mb: Optional[float] = None):
        self.dp = dp
        mb = dp.comm_buffer_size if bucket_mb is None else bucket_mb
        self.bucket_bytes = int(float(mb) * (1 << 20))
        self._params = [p for p in dp._layers.parameters()
                        if not p.stop_gradient
                        and not getattr(p, "no_sync", False)]
        self._pid = {id(p): i for i, p in enumerate(self._params)}
        self._paused = 0
        self._hooks = []
        self.last = {}          # accounting of the last finished step
        self._reset()
        self._install()

    # ------------------------------------------------------------ state --
    def _reset(self):
        self._ready_ids = set()
        self.last_ready_order = []   # backward-walk finalize order
        self._open = {}              # dtype -> [params] awaiting close
        self._open_bytes = {}        # dtype -> payload bytes
        self._closed = []            # buckets awaiting dispatch
        self._pending = []           # (params, reduced, t_dispatch, bytes)
        self._synced_ids = set()

    def _install(self):
        for p in self._params:
            self._hooks.append(p.register_hook(self._make_hook(p)))

    def remove(self):
        """Unhook every parameter (the scheduler becomes inert)."""
        for h in self._hooks:
            h.remove()
        self._hooks = []
        self._reset()

    # ------------------------------------------------------------ hooks --
    def _make_hook(self, p):
        def hook(g):
            self._on_grad_final(p, g)
            return None  # never modifies the gradient
        return hook

    def _on_grad_final(self, p, g):
        if self._paused or self.dp.group.nranks == 1:
            return
        if _tm._tracker is not None:
            return  # jit capture: GSPMD owns the grad psums
        val = g._read() if isinstance(g, Tensor) else g
        if isinstance(val, jax.core.Tracer):
            return
        if id(p) in self._ready_ids:
            # a second backward before finish(): stale scheduling state
            # from the previous walk — start over (pending results are
            # dropped; finish() will fall back to the leftover path)
            self._reset()
        # grads finalized at EARLIER hooks are fully written by now:
        # dispatch every closed bucket before banking this one
        self._flush_closed()
        self._ready_ids.add(id(p))
        self.last_ready_order.append(self._pid[id(p)])
        dt = jnp.dtype(val.dtype)
        nbytes = int(val.size) * dt.itemsize
        self._open.setdefault(dt, []).append(p)
        self._open_bytes[dt] = self._open_bytes.get(dt, 0) + nbytes
        if self._open_bytes[dt] >= self.bucket_bytes:
            self._closed.append(self._open.pop(dt))
            self._open_bytes.pop(dt)

    def _flush_closed(self):
        while self._closed:
            self._dispatch(self._closed.pop(0))

    def _dispatch(self, params):
        """ONE collective for the bucket: concat the final grads (same
        elementwise values the serialized sync reduces), psum-mean
        through the group's cached program, keep the future."""
        vals = []
        for p in params:
            if p.grad is None:      # defensive: leave to the fallback
                return
            v = p.grad._read()
            if isinstance(v, jax.core.Tracer):
                return
            vals.append(v)
        flat = jnp.concatenate([jnp.ravel(v) for v in vals]) \
            if len(vals) > 1 else jnp.ravel(vals[0])
        nbytes = sum(int(v.size) * v.dtype.itemsize for v in vals)
        # span brackets the ASYNC launch (the overlapped half); the
        # blocking half shows in finish()'s dp.grad_sync_drain span
        with _tracing.span("dp.bucket_sync", params=len(params),
                           bytes=nbytes):
            red = self.dp._psum_mean(flat)   # async jax dispatch
        self._pending.append((params, vals, red, time.perf_counter(),
                              nbytes))

    # ----------------------------------------------------------- finish --
    def finish(self):
        """Drain the walk: dispatch still-open buckets, wait on every
        pending collective, write the reduced slices back, record
        comm/overlap accounting. Returns the set of param ids synced."""
        self._flush_closed()
        for params in self._open.values():
            self._dispatch(params)
        self._open = {}
        self._open_bytes = {}
        t_join = time.perf_counter()
        comm_ms = 0.0
        overlapped_ms = 0.0
        total_bytes = 0
        n_buckets = 0
        handles = _metrics_handles()
        with _tracing.span("dp.grad_sync_drain",
                           pending=len(self._pending)):
            for params, vals, red, t_disp, nbytes in self._pending:
                jax.block_until_ready(red)
                t_done = time.perf_counter()
                wall = (t_done - t_disp) * 1e3
                comm_ms += wall
                overlapped_ms += max(0.0, min(
                    wall, (t_join - t_disp) * 1e3))
                off = 0
                for p, v in zip(params, vals):
                    n = v.size
                    p.grad._write(red[off:off + n].reshape(v.shape))
                    off += n
                    self._synced_ids.add(id(p))
                total_bytes += nbytes
                n_buckets += 1
                if handles:
                    handles[0].observe(wall)
        self._pending = []
        frac = (overlapped_ms / comm_ms) if comm_ms > 0 else 0.0
        self.last = {
            "buckets": n_buckets,
            "comm_ms": round(comm_ms, 3),
            "overlap_frac": round(frac, 4),
            "bytes": total_bytes,
            "ready_order": list(self.last_ready_order),
        }
        if handles and n_buckets:
            _, g_frac, c_buckets, c_bytes = handles
            g_frac.set(round(frac, 4))
            c_buckets.inc(n_buckets)
            c_bytes.inc(total_bytes)
        synced = self._synced_ids
        self._reset()
        return synced

    # ------------------------------------------------------------ pause --
    def pause(self):
        """Context: hooks stand down (gradient-accumulation micro-steps
        under ``DataParallel.no_sync``)."""
        sched = self

        class _Pause:
            def __enter__(self):
                sched._paused += 1
                return self

            def __exit__(self, *exc):
                sched._paused -= 1
                return False

        return _Pause()

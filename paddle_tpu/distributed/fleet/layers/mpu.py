"""Tensor-parallel (model-parallel) layers — GSPMD mechanism.

Capability analog of ``python/paddle/distributed/fleet/layers/mpu/
mp_layers.py`` (SURVEY D14; ``VocabParallelEmbedding:47``,
``ColumnParallelLinear:333``, ``RowParallelLinear:540``) and the comm
autograd ops of ``mp_ops.py`` (``_c_identity``/``_c_concat``/
``_c_softmax_with_cross_entropy``).

TPU-native mechanism: the reference stores a weight *slice* per rank and
hand-inserts identity/allreduce collectives with custom autograd rules. On
TPU each layer holds the full-logical-shape parameter pinned with a
``NamedSharding`` over the ``mp`` mesh axis; XLA's SPMD partitioner emits
exactly the Megatron collectives (and their transposes in backward) from
the sharding constraints:

- ColumnParallelLinear: W sharded [None, 'mp'] → local y = x @ W_shard, no
  comm; ``gather_output`` reshards y to replicated (all-gather).
- RowParallelLinear: W sharded ['mp', None] → XLA partial-sums then psum
  (the reference's hand-written allreduce).
- VocabParallelEmbedding: table sharded ['mp', None]; XLA masks + psum —
  the reference's c_embedding kernel.
- ParallelCrossEntropy: softmax over 'mp'-sharded logits; XLA's sharded
  reduce = the reference's _c_softmax_with_cross_entropy.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ....core.dispatch import apply
from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn import initializer as I
from ....nn.layer import Layer


def _default_mesh() -> Mesh:
    from ..fleet import get_hybrid_communicate_group, init
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        hcg = init()
    return hcg.mesh


def _mp_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1) if hasattr(mesh, "shape") else 1


def _pin(param: Tensor, mesh: Mesh, spec: P):
    v = param._read()
    if not isinstance(v, jax.core.Tracer):
        param._write(jax.device_put(v, NamedSharding(mesh, spec)))
    param._dist = (mesh, spec)
    return param


def _constrain(x: Tensor, mesh: Mesh, spec: P) -> Tensor:
    """Differentiable resharding constraint (device_put under vjp)."""
    sh = NamedSharding(mesh, spec)
    return apply("sharding_constraint",
                 lambda v: jax.lax.with_sharding_constraint(v, sh)
                 if isinstance(v, jax.core.Tracer)
                 else jax.device_put(v, sh), x)


class ColumnParallelLinear(Layer):
    """Reference ``mp_layers.py:333``: y = x @ W with W column-sharded over
    the mp axis."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.mesh = mp_group.mesh if mp_group is not None else _default_mesh()
        self.axis = getattr(mp_group, "axis", "mp")
        self.world_size = _mp_axis_size(self.mesh, self.axis)
        if out_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"out_features {out_features} not divisible by mp degree "
                f"{self.world_size}")
        self.gather_output = gather_output
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.weight.is_distributed = True
        _pin(self.weight, self.mesh, P(None, self.axis))
        if has_bias:
            self.bias = self.create_parameter(
                [out_features], attr=None, is_bias=True)
            self.bias.is_distributed = True
            _pin(self.bias, self.mesh, P(self.axis))
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            y = _constrain(y, self.mesh, P())
        return y


class RowParallelLinear(Layer):
    """Reference ``mp_layers.py:540``: W row-sharded; XLA inserts the psum
    the reference codes as mp_allreduce_sum."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.mesh = mp_group.mesh if mp_group is not None else _default_mesh()
        self.axis = getattr(mp_group, "axis", "mp")
        self.world_size = _mp_axis_size(self.mesh, self.axis)
        if in_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"in_features {in_features} not divisible by mp degree "
                f"{self.world_size}")
        self.input_is_parallel = input_is_parallel
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.weight.is_distributed = True
        _pin(self.weight, self.mesh, P(self.axis, None))
        if has_bias:
            # bias is applied after the reduction (replicated), as in the
            # reference (bias added post-allreduce on rank output)
            self.bias = self.create_parameter(
                [out_features], attr=None, is_bias=True)
            _pin(self.bias, self.mesh, P())
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = _constrain(x, self.mesh,
                           P(*([None] * (len(x.shape) - 1) + [self.axis])))
        y = F.linear(x, self.weight, None)
        y = _constrain(y, self.mesh, P())
        if self.bias is not None:
            y = y + self.bias
        return y


class VocabParallelEmbedding(Layer):
    """Reference ``mp_layers.py:47``: embedding table row-sharded over mp;
    out-of-shard ids are masked + psum'd by XLA's gather partitioning."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.mesh = mp_group.mesh if mp_group is not None else _default_mesh()
        self.axis = getattr(mp_group, "axis", "mp")
        self.world_size = _mp_axis_size(self.mesh, self.axis)
        if num_embeddings % max(self.world_size, 1) != 0:
            raise ValueError(
                f"num_embeddings {num_embeddings} not divisible by mp degree "
                f"{self.world_size}")
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.is_distributed = True
        _pin(self.weight, self.mesh, P(self.axis, None))

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Reference ``mp_layers.py`` ParallelCrossEntropy /
    ``mp_ops._c_softmax_with_cross_entropy``: cross entropy on
    vocab-sharded logits without materializing the gathered logits. XLA's
    sharded softmax reduction performs the two-pass max/sum psum the
    reference hand-codes."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)

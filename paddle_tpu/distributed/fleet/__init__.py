"""paddle_tpu.distributed.fleet — hybrid-parallel orchestration.

Analog of ``python/paddle/distributed/fleet`` (SURVEY D13-D17): topology /
HybridCommunicateGroup, tensor-parallel layers (mpu), sharding optimizer,
and the fleet facade.
"""
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401
from .fleet import (  # noqa: F401
    init, DistributedStrategy, distributed_model, distributed_optimizer,
    get_hybrid_communicate_group, set_hybrid_communicate_group,
    worker_index, worker_num, is_server, is_worker, server_num,
    server_endpoints, run_server, init_worker, barrier_worker, stop_worker,
)
from . import layers  # noqa: F401
from .layers.mpu import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy,
)
from .sharding_optimizer import DygraphShardingOptimizer  # noqa: F401
from .pipeline import (  # noqa: F401
    PipelinedBlocks, PipelineLayer, LayerDesc, functional_call,
)
from .recompute import (  # noqa: F401
    recompute, recompute_sequential, recompute_hybrid,
)

"""Pipeline parallelism — SPMD GPipe over a ``pp`` mesh axis.

Capability analog of the reference's pipeline stack (SURVEY D15-D17):
``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
(schedules, 1F1B :663), ``parallel_layers/pp_layers.py`` (PipelineLayer /
LayerDesc), ``pp_utils/p2p_communication.py`` (stage P2P). The reference
runs one process per stage and hand-schedules NCCL send/recv; here the
whole pipeline is ONE SPMD program:

- the repeated block stack's parameters are stacked into ``[L, ...]``
  arrays sharded ``Shard(0)`` over the ``pp`` axis — stage assignment IS
  the sharding;
- a ``jax.shard_map`` + ``lax.scan`` runs the classic fill-drain (GPipe)
  schedule: at tick ``t`` stage ``i`` computes microbatch ``t - i`` and
  hands its activation to stage ``i+1`` via ``lax.ppermute`` (ICI
  neighbor hop — the p2p_communication analog);
- backward is JAX's transpose of the scan: activations flow backward
  through reversed ppermutes, giving the mirrored drain-fill schedule
  without a hand-written 1F1B engine. ``jax.checkpoint`` on the per-layer
  body keeps the live set to O(microbatch) per stage.

Bubble fraction is the textbook ``(pp-1)/(M+pp-1)`` — raise
``num_microbatches`` to amortize, exactly as with the reference's GPipe
mode.

P2P/compute overlap (``pp_overlap_p2p`` flag, default on): every
ppermute send is issued as soon as its payload exists — the forward
activation hop before the same tick's output banking, the backward
cotangent hop before the O(params) leaf-grad accumulation — so XLA's
scheduler can run the ICI transfer under independent compute (the
reference's async ``p2p_communication`` sends). Pure reordering:
values are bitwise-identical with the flag off.

Three schedules, matching the reference's set (D15):

- ``forward()`` (default) — FThenB/GPipe via scan + transpose;
- ``forward()`` with ``interleave=v > 1`` — interleaved virtual pipeline
  (reference ``pipeline_parallel.py:912``): stages hold v round-robin
  chunks, microbatches make v ppermute laps, bubble time shrinks by v;
- ``train_batch()`` — fused 1F1B (reference ``:663``): forward and
  backward micro-steps interleaved in ONE program with an O(pp) residual
  ring instead of O(M) saved activations.
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ...core import state
from ...core import tensor as tensor_mod
from ...core.dispatch import apply
from ...core.tensor import Tensor
from ...nn.layer import Layer


def functional_call(layer: Layer, param_vals: dict, *args):
    """Run ``layer.forward`` as a PURE function of ``param_vals``
    (name -> raw array), torch.func.functional_call-style.

    Used to trace a Layer's computation with externally-managed (stacked /
    sliced / traced) parameter values: the layer's parameter tensors are
    temporarily re-pointed at ``param_vals``, the tape and any jit-capture
    tracker are disabled (the caller owns differentiation — usually the
    dispatch funnel's ``jax.vjp`` around the enclosing composite op), and
    the original buffers are restored afterwards."""
    params = dict(layer.named_parameters())
    missing = set(params) - set(param_vals)
    if missing:
        raise ValueError(f"functional_call missing values for {missing}")
    originals = {n: p._data for n, p in params.items()}

    def wrap(a):
        if isinstance(a, (tuple, list)):
            return type(a)(wrap(e) for e in a)
        return a if isinstance(a, Tensor) else Tensor(a)

    def unwrap(o):
        if isinstance(o, (tuple, list)):
            return type(o)(unwrap(e) for e in o)
        return o._data if isinstance(o, Tensor) else o

    old_tracker = tensor_mod.set_tracker(None)
    old_grad = state.set_grad_enabled(False)
    try:
        for n, p in params.items():
            p._data = param_vals[n]
        out = layer(*[wrap(a) for a in args])
    finally:
        state.set_grad_enabled(old_grad)
        tensor_mod.set_tracker(old_tracker)
        for n, p in params.items():
            p._data = originals[n]
    return unwrap(out)


from ...core.meshutil import pvary as _pvary
from ...core.meshutil import shard_map as _shard_map


def _overlap_p2p() -> bool:
    """pp_overlap_p2p flag (core/state.py): ppermute sends issued before
    the independent work of the same tick so the transfer hides under
    compute. Read at trace time; pure reordering, bitwise-identical."""
    return bool(state.get_flag("pp_overlap_p2p"))


class PipelinedBlocks(Layer):
    """A stack of ``num_layers`` structurally-identical blocks executed as
    an SPMD pipeline (see module docstring). The per-leaf parameters are
    stored STACKED (``[L, *shape]``) so ``Shard(0)`` over the pp axis
    assigns ``L/pp`` consecutive layers to each stage — the analog of the
    reference PipelineLayer's segment allocation (``pp_layers.py``
    ``_segment_network``).

    ``block_factory()`` must build one block Layer; blocks may not carry
    buffers or active dropout (single-program pipelining threads only
    parameters; RNG-bearing blocks would constant-fold their keys).
    """

    def __init__(self, block_factory: Callable[[], Layer], num_layers: int,
                 mesh=None, pp_axis: str = "pp", num_microbatches: int = 1,
                 remat: bool = True, interleave: int = 1):
        super().__init__()
        self.num_layers = num_layers
        self.pp_axis = pp_axis
        self.num_microbatches = num_microbatches
        self.remat = remat
        self.interleave = int(interleave)
        self._mesh = None
        self.template = block_factory()
        if any(True for _ in self.template.named_buffers()):
            raise ValueError("PipelinedBlocks: blocks must be buffer-free "
                             "(running stats can't thread the pipeline)")
        if self.interleave > 1 and mesh is None:
            raise ValueError("interleave > 1 needs the mesh at construction "
                             "(chunk assignment depends on the pp size)")
        # storage order: identity for v=1; round-robin chunks for VPP so a
        # CONTIGUOUS Shard(0) gives stage i its v chunks (layer (c*pp+i)*Lc+k
        # at storage slot i*Lp + c*Lc + k) — the reference's interleaved
        # stage->layers map (pipeline_parallel.py:912 virtual pipeline)
        self.layer_order = np.arange(num_layers)
        if self.interleave > 1:
            pp = self._pp_size(mesh, pp_axis)
            v = self.interleave
            if num_layers % (v * pp):
                raise ValueError(f"num_layers {num_layers} not divisible by "
                                 f"interleave*pp = {v}*{pp}")
            lc = num_layers // (v * pp)
            self.layer_order = np.asarray(
                [(c * pp + i) * lc + k
                 for i in range(pp) for c in range(v) for k in range(lc)])
        # stack L independent initializations leaf-wise -> [L, *shape]
        inits = [self.template] + [block_factory()
                                   for _ in range(num_layers - 1)]
        self._names = [n for n, _ in self.template.named_parameters()]
        for n in self._names:
            leaves = [dict(b.named_parameters())[n]._read() for b in inits]
            leaves = [leaves[j] for j in self.layer_order]
            stacked = Tensor(jnp.stack(leaves, axis=0), stop_gradient=False)
            self.add_parameter(self._mangle(n), _as_param(stacked))
        if mesh is not None:
            self.shard(mesh, pp_axis)

    @staticmethod
    def _pp_size(mesh, pp_axis):
        jmesh = getattr(mesh, "jmesh", mesh)
        return dict(zip(jmesh.axis_names, jmesh.devices.shape))[pp_axis]

    def layer_values(self, name: str):
        """Per-layer values of a stacked leaf in ORIGINAL layer order
        (undoes the VPP storage permutation)."""
        vals = self.stacked_parameter(name)._read()
        inv = np.argsort(self.layer_order)
        return [vals[int(j)] for j in inv]

    @staticmethod
    def _mangle(name: str) -> str:
        return "stacked__" + name.replace(".", "__")

    def stacked_parameter(self, name: str):
        return self._parameters[self._mangle(name)]

    def shard(self, mesh, pp_axis: str = "pp", tp_axis=None,
              tp_rules=None):
        """Pin Shard(0) over ``pp_axis`` on every stacked leaf.

        ``tp_axis``/``tp_rules`` add Megatron TP *inside* the pipeline
        (the reference's pp x mp hybrid, ``topology.py`` +
        ``semi_auto_parallel_simple_net_dp_mp_pp.py``): ``tp_rules`` maps
        a parameter-name substring to the STACKED-array dim to shard over
        ``tp_axis`` (e.g. ``{"qkv.weight": 2, "proj.weight": 1}``). The
        pipeline's shard_map then leaves ``tp_axis`` to GSPMD
        (``axis_names`` excludes it), so XLA inserts the TP collectives
        inside each stage while ppermute rides the pp axis."""
        from ..auto_parallel.api import Replicate, Shard, shard_parameter
        self._mesh = mesh
        self.pp_axis = pp_axis
        if tp_axis is not None and tp_axis not in mesh.dim_names:
            raise ValueError(
                f"tp_axis {tp_axis!r} is not a mesh dim "
                f"{mesh.dim_names} — refusing to silently train "
                "replicated")
        if tp_rules and tp_axis is None:
            raise ValueError("tp_rules given without tp_axis")
        if tp_axis is not None:
            from ...core.meshutil import partial_auto_supported
            if not partial_auto_supported():
                # jax < 0.5: shard_map cannot leave the TP axis to
                # GSPMD (partial-auto is NotImplemented eagerly and the
                # old partitioner crashes on ppermute inside it) —
                # demote to replicated compute over tp_axis: leaves
                # stay pp-sharded only, the axis joins the manual set
                # as one more replicated dim (like dp with no batch
                # shard), and every value is mathematically identical,
                # just computed redundantly per tp shard.  The modern
                # path keeps real Megatron TP.
                import warnings
                warnings.warn(
                    f"PipelinedBlocks.shard: tp_axis={tp_axis!r} "
                    "demoted to replicated compute — this jax's legacy "
                    "shard_map cannot run a partial-auto (GSPMD TP) "
                    "region; upgrade to jax >= 0.5 for in-pipeline "
                    "tensor parallelism", RuntimeWarning, stacklevel=2)
                tp_axis, tp_rules = None, None
        self._tp_axis = tp_axis
        dim = mesh.dim_names.index(pp_axis)
        for n in self._names:
            pl = [Replicate()] * mesh.ndim
            pl[dim] = Shard(0)
            if self._tp_axis and tp_rules:
                for pat, tdim in tp_rules.items():
                    if pat in n:
                        pl[mesh.dim_names.index(tp_axis)] = Shard(tdim)
                        break
            shard_parameter(self.stacked_parameter(n), mesh, pl)
        return self

    def _manual_axes(self, jmesh):
        """Mesh axes the pipeline shard_map handles manually — everything
        except the TP axis, which stays under GSPMD."""
        names = tuple(jmesh.axis_names)
        tp = getattr(self, "_tp_axis", None)
        return frozenset(n for n in names if n != tp)

    def _audit_impl(self, name, impl, args):
        """Whole-program audit (analysis/program.py) of a pipeline
        shard_map body: the ppermute ring + psum schedule is exactly
        what PDT22x reasons about. Once per (pipeline, schedule name),
        at the dispatch that first compiles it — compile-time only."""
        done = self.__dict__.setdefault("_pp_audit_done", set())
        if name in done:
            return
        done.add(name)
        from ... import analysis as _analysis
        from ...core.tensor import Tensor as _T
        vals = tuple(a._read() if isinstance(a, _T) else a for a in args)
        _analysis.audit_jitted(impl, vals, where=f"pipeline.{name}")

    # -- the schedules -------------------------------------------------
    def forward(self, x, batch_axes=None):
        if self._mesh is None:
            raise RuntimeError("call .shard(mesh, pp_axis) first")
        if self.interleave > 1:
            return self._forward_interleaved(x, batch_axes)
        mesh = self._mesh
        jmesh = getattr(mesh, "jmesh", mesh)
        pp = self._pp_size(mesh, self.pp_axis)
        M = self.num_microbatches
        L, ax = self.num_layers, self.pp_axis
        if L % pp:
            raise ValueError(f"num_layers {L} not divisible by pp {pp}")
        template, names = self.template, self._names
        remat = self.remat
        if isinstance(batch_axes, str):
            batch_tuple = (batch_axes,)
        else:
            batch_tuple = tuple(batch_axes or ())
        vary_axes = (ax,) + batch_tuple

        leaf_tensors = [self.stacked_parameter(n) for n in names]

        def impl(xv, *leaves):
            b = xv.shape[0]
            if b % M:
                raise ValueError(f"batch {b} not divisible by "
                                 f"num_microbatches {M}")
            xm = xv.reshape((M, b // M) + xv.shape[1:])

            def block_apply(h, layer_leaves):
                vals = dict(zip(names, layer_leaves))
                y = functional_call(template, vals, h)
                return y, None

            if remat:
                block_apply = jax.checkpoint(block_apply)

            def local(xloc, *lvs):
                i = lax.axis_index(ax)
                mb_shape = xloc.shape[1:]

                def tick(carry, t):
                    h_in, outputs = carry
                    inject = xloc[jnp.clip(t, 0, M - 1)]
                    h = jnp.where(i == 0, inject, h_in)
                    y, _ = lax.scan(block_apply, h, lvs)
                    ring = [(r, (r + 1) % pp) for r in range(pp)]
                    if _overlap_p2p():
                        # issue the neighbor send FIRST: the output
                        # banking below is independent of it, so the ICI
                        # transfer runs under that work instead of after
                        # it (the p2p/compute overlap of the reference's
                        # p2p_communication async sends). Values are
                        # bitwise-identical either way — only the
                        # schedule moves.
                        nxt = lax.ppermute(y, ax, ring)
                    m_out = t - (pp - 1)
                    idx = jnp.clip(m_out, 0, M - 1)
                    valid = (i == pp - 1) & (m_out >= 0)
                    cur = lax.dynamic_index_in_dim(outputs, idx, 0,
                                                   keepdims=False)
                    outputs = lax.dynamic_update_index_in_dim(
                        outputs, jnp.where(valid, y, cur), idx, 0)
                    if not _overlap_p2p():
                        nxt = lax.ppermute(y, ax, ring)
                    return (nxt, outputs), None

                h0 = jnp.zeros(mb_shape, xloc.dtype)
                out0 = jnp.zeros((M,) + mb_shape, xloc.dtype)
                h0, out0 = _pvary((h0, out0), vary_axes)
                (_, outputs), _ = lax.scan(tick, (h0, out0),
                                           jnp.arange(M + pp - 1))
                # results live on the last stage; replicate over pp
                outputs = lax.psum(
                    jnp.where(i == pp - 1, outputs, 0), ax)
                return outputs

            xspec = P(None, batch_axes, *([None] * (xv.ndim - 1)))
            lspec = tuple(P(ax) for _ in leaves)
            out = _shard_map(local, mesh=jmesh,
                             in_specs=(xspec,) + lspec,
                             out_specs=xspec,
                             axis_names=self._manual_axes(jmesh),
                             )(xm, *leaves)
            return out.reshape((b,) + xv.shape[1:])

        # host-side tracing span around the whole pipelined dispatch
        # (ISSUE 12): the ppermute hops themselves are in-program
        # (XLA-scheduled), so the span brackets what the host can see —
        # the dispatch that contains them, with the schedule knobs as
        # attrs.  Under jit capture this runs once, at trace time.
        # The collective watchdog (ISSUE 15, collective_timeout_ms
        # flag) arms the same bracket: a ppermute ring wedged behind a
        # dead stage raises PDT-E021 with stacks instead of hanging.
        from ...observability import tracing as _tracing
        from ...observability import watchdog as _watchdog
        with _tracing.span("pp.forward", stages=pp, microbatches=M,
                           overlap_p2p=_overlap_p2p()), \
                _watchdog.arm_collective("pp.forward", key=self.pp_axis):
            self._audit_impl("pipelined_blocks", impl,
                             (x, *leaf_tensors))
            return apply("pipelined_blocks", impl, x, *leaf_tensors)

    def _forward_interleaved(self, x, batch_axes=None):
        """Interleaved virtual pipeline (reference
        ``pipeline_parallel.py:912`` interleaved 1F1B's stage layout,
        ``pp_layers.py`` virtual-pipeline chunks): each stage holds
        ``v = interleave`` round-robin layer chunks and microbatches
        circulate the ppermute ring ``v`` laps. Per-tick work is a chunk
        (1/v of a stage), so the fill/drain bubble time shrinks by v —
        the VPP bubble equation (pp-1)/(vM) vs GPipe's (pp-1)/M."""
        mesh = self._mesh
        jmesh = getattr(mesh, "jmesh", mesh)
        pp = self._pp_size(mesh, self.pp_axis)
        v, M, ax = self.interleave, self.num_microbatches, self.pp_axis
        lc = self.num_layers // (v * pp)  # layers per chunk
        template, names, remat = self.template, self._names, self.remat
        batch_tuple = ((batch_axes,) if isinstance(batch_axes, str)
                       else tuple(batch_axes or ()))
        vary_axes = (ax,) + batch_tuple
        leaf_tensors = [self.stacked_parameter(n) for n in names]

        def impl(xv, *leaves):
            b = xv.shape[0]
            if b % M:
                raise ValueError(f"batch {b} not divisible by "
                                 f"num_microbatches {M}")
            xm = xv.reshape((M, b // M) + xv.shape[1:])

            def block_apply(h, layer_leaves):
                vals = dict(zip(names, layer_leaves))
                return functional_call(template, vals, h), None

            if remat:
                block_apply = jax.checkpoint(block_apply)

            def chunk_apply(h, lvs, c):
                sl = [lax.dynamic_slice_in_dim(lv, c * lc, lc, axis=0)
                      for lv in lvs]
                y, _ = lax.scan(block_apply, h, tuple(sl))
                return y

            def local(xloc, *lvs):
                i = lax.axis_index(ax)
                mb_shape = xloc.shape[1:]
                done = v * pp  # hop count meaning "finished / empty slot"

                def tick(carry, t):
                    h, hops, mbid, inj, outputs = carry
                    at0 = i == 0
                    finished = hops >= done
                    # stage 0: bank a finished microbatch, inject the next
                    rec = at0 & finished & (mbid >= 0)
                    oc = jnp.clip(mbid, 0, M - 1)
                    cur = lax.dynamic_index_in_dim(outputs, oc, 0,
                                                   keepdims=False)
                    outputs = lax.dynamic_update_index_in_dim(
                        outputs, jnp.where(rec, h, cur), oc, 0)
                    take = at0 & finished & (inj < M)
                    h = jnp.where(take, xloc[jnp.clip(inj, 0, M - 1)], h)
                    mbid = jnp.where(take, inj,
                                     jnp.where(finished, -1, mbid))
                    hops = jnp.where(take, 0, hops)
                    inj = inj + take.astype(inj.dtype)
                    # apply this stage's chunk for the current lap
                    active = hops < done
                    c = jnp.clip(hops // pp, 0, v - 1)
                    y = chunk_apply(h, lvs, c)
                    h = jnp.where(active, y, h)
                    hops = jnp.where(active, hops + 1, hops)
                    ring = [(r, (r + 1) % pp) for r in range(pp)]
                    h = lax.ppermute(h, ax, ring)
                    hops = lax.ppermute(hops, ax, ring)
                    mbid = lax.ppermute(mbid, ax, ring)
                    return (h, hops, mbid, inj, outputs), None

                h0 = jnp.zeros(mb_shape, xloc.dtype)
                out0 = jnp.zeros((M,) + mb_shape, xloc.dtype)
                carry0 = _pvary(
                    (h0, jnp.int32(done), jnp.int32(-1), jnp.int32(0),
                     out0), vary_axes)
                # last microbatch M-1 enters slot (M-1)%pp at tick
                # (M-1)%pp + ((M-1)//pp)*v*pp and is banked v*pp ticks
                # later — run exactly until then (v*M + pp only covers
                # M a multiple of pp)
                t_bank = ((M - 1) % pp) + ((M - 1) // pp) * v * pp + v * pp
                carry = lax.scan(tick, carry0,
                                 jnp.arange(t_bank + 1))[0]
                outputs = carry[4]
                return lax.psum(jnp.where(i == 0, outputs, 0), ax)

            xspec = P(None, batch_axes, *([None] * (xv.ndim - 1)))
            lspec = tuple(P(ax) for _ in leaves)
            out = _shard_map(local, mesh=jmesh,
                             in_specs=(xspec,) + lspec,
                             out_specs=xspec,
                             axis_names=self._manual_axes(jmesh),
                             )(xm, *leaves)
            return out.reshape((b,) + xv.shape[1:])

        self._audit_impl("pipelined_blocks_vpp", impl, (x, *leaf_tensors))
        return apply("pipelined_blocks_vpp", impl, x, *leaf_tensors)

    def train_batch(self, x, target, loss_fn, batch_axes=None,
                    post_params=None):
        """Fused 1F1B train step (reference ``pipeline_parallel.py:663``
        ``train_batch`` / ``forward_backward_pipeline``): ONE SPMD program
        runs forward and backward micro-steps interleaved, holding at most
        ``2*pp`` microbatch residuals per stage (the 1F1B memory property
        — vs O(M) for the scan-transpose GPipe path), recomputing each
        chunk's vjp from the saved chunk input (recompute policy).

        ``loss_fn(y, target_mb)`` (or ``loss_fn(y, target_mb,
        post_vals)`` with ``post_params``) -> scalar mean loss, run on the
        last stage. ``post_params`` lets a trailing trainable epilogue
        (final norm, tied LM head) live inside the schedule: their raw
        values are passed to ``loss_fn`` and their grads flow back like
        the stacked leaves'. Returns the scalar mean loss;
        ``loss.backward()`` flows grads into the stacked leaves, ``x``,
        and the post params through the recorded vjp, so optimizers work
        unchanged.

        Schedule: tick ``t`` runs forward of microbatch ``t - i`` and
        backward of microbatch ``t - (2pp - 1 - i)`` on stage ``i``;
        activations hop forward and cotangents hop backward one ppermute
        per tick. The last stage's loss-vjp is folded into the uniform
        per-tick vjp by differentiating ``where(is_last, loss, <y, g>)``,
        so every tick costs exactly one chunk fwd + one chunk vjp.
        """
        if self._mesh is None:
            raise RuntimeError("call .shard(mesh, pp_axis) first")
        if self.interleave > 1:
            raise NotImplementedError("train_batch schedules plain 1F1B; "
                                      "use interleave=1 (VPP forward is "
                                      "available via __call__)")
        mesh = self._mesh
        jmesh = getattr(mesh, "jmesh", mesh)
        pp = self._pp_size(mesh, self.pp_axis)
        M, ax = self.num_microbatches, self.pp_axis
        L = self.num_layers
        if L % pp:
            raise ValueError(f"num_layers {L} not divisible by pp {pp}")
        template, names = self.template, self._names
        batch_tuple = ((batch_axes,) if isinstance(batch_axes, str)
                       else tuple(batch_axes or ()))
        vary_axes = (ax,) + batch_tuple
        sizes = dict(zip(jmesh.axis_names, jmesh.devices.shape))
        dp_n = int(np.prod([sizes[a] for a in batch_tuple])) \
            if batch_tuple else 1
        leaf_tensors = [self.stacked_parameter(n) for n in names]
        post_params = list(post_params or [])
        n_leaves = len(leaf_tensors)

        def impl(xv, tgt, *leaves_and_post):
            leaves = leaves_and_post[:n_leaves]
            post_vals_in = leaves_and_post[n_leaves:]
            b = xv.shape[0]
            if b % M:
                raise ValueError(f"batch {b} not divisible by "
                                 f"num_microbatches {M}")
            xm = xv.reshape((M, b // M) + xv.shape[1:])
            tm = tgt.reshape((M, b // M) + tgt.shape[1:])
            seed = 1.0 / (M * dp_n)

            def run(xmv, tmv, *lvs_and_post):
                lvs_in = lvs_and_post[:n_leaves]
                post_in = lvs_and_post[n_leaves:]
                def block_apply(h, layer_leaves):
                    vals = dict(zip(names, layer_leaves))
                    return functional_call(template, vals, h), None

                def chunk_fwd(h, lvs):
                    y, _ = lax.scan(block_apply, h, lvs)
                    return y

                def local(xloc, tloc, *lvs_all):
                    lvs = lvs_all[:n_leaves]
                    post = lvs_all[n_leaves:]
                    i = lax.axis_index(ax)
                    is_last = i == pp - 1
                    mb_shape = xloc.shape[1:]
                    R = 2 * pp
                    fwd_ring = [(r, (r + 1) % pp) for r in range(pp)]
                    bwd_ring = [(r, (r - 1) % pp) for r in range(pp)]

                    def objective(h, lvs, pv, t_mb, g):
                        """where(is_last, seed*loss, <y, g>): its
                        (h, lvs, pv) vjp is the loss-vjp on the last
                        stage and the cotangent-g chunk vjp elsewhere."""
                        y = chunk_fwd(h, lvs)
                        loss = (loss_fn(y, t_mb, pv) if pv
                                else loss_fn(y, t_mb))
                        obj = jnp.where(is_last, loss * seed,
                                        jnp.vdot(y, g))
                        return obj, loss

                    def tick(carry, t):
                        (h_fwd, g_bwd, ring, dacc, dpacc, loss_acc,
                         dx) = carry
                        # ---- forward micro-step: mb u = t - i ----
                        u = t - i
                        uc = jnp.clip(u, 0, M - 1)
                        h_in = jnp.where(i == 0, xloc[uc], h_fwd)
                        # bank the chunk input; slot t%R frees before reuse
                        ring = lax.dynamic_update_index_in_dim(
                            ring, h_in, t % R, 0)
                        y = chunk_fwd(h_in, lvs)
                        h_next = lax.ppermute(y, ax, fwd_ring)
                        # ---- backward micro-step: mb m ----
                        m = t - (2 * pp - 1 - i)
                        bvalid = (m >= 0) & (m < M)
                        mc = jnp.clip(m, 0, M - 1)
                        slot = (t - (2 * pp - 1 - 2 * i)) % R
                        h_saved = lax.dynamic_index_in_dim(
                            ring, slot, 0, keepdims=False)
                        obj, vjp, loss = jax.vjp(
                            lambda hh, ll, pv: objective(
                                hh, ll, pv, tloc[mc], g_bwd),
                            h_saved, lvs, tuple(post), has_aux=True)
                        dh, dlvs, dpost = vjp(
                            _pvary(jnp.ones((), obj.dtype), vary_axes))
                        if _overlap_p2p():
                            # issue the cotangent send as soon as dh
                            # exists: the O(params) leaf-grad
                            # accumulation below is independent of it,
                            # so the backward ICI hop runs under that
                            # work (values bitwise-identical; schedule
                            # only)
                            g_next = lax.ppermute(
                                jnp.where(bvalid, dh,
                                          jnp.zeros_like(dh)),
                                ax, bwd_ring)
                        dacc = tuple(
                            da + jnp.where(bvalid, dl, 0)
                            for da, dl in zip(dacc, dlvs))
                        # dpost is auto-psummed over pp+dp (invarying
                        # inputs); mid stages contribute exact zeros, so
                        # gate by the LAST stage's mb validity at this
                        # tick (same value on every device)
                        m_last = t - pp
                        glast = (m_last >= 0) & (m_last < M)
                        dpacc = tuple(
                            da + jnp.where(glast, dp_, 0)
                            for da, dp_ in zip(dpacc, dpost))
                        loss_acc = loss_acc + jnp.where(
                            bvalid & is_last, loss, 0.0)
                        curx = lax.dynamic_index_in_dim(dx, mc, 0,
                                                        keepdims=False)
                        dx = lax.dynamic_update_index_in_dim(
                            dx, jnp.where(bvalid & (i == 0), dh, curx),
                            mc, 0)
                        if not _overlap_p2p():
                            g_next = lax.ppermute(
                                jnp.where(bvalid, dh,
                                          jnp.zeros_like(dh)),
                                ax, bwd_ring)
                        return (h_next, g_next, ring, dacc, dpacc,
                                loss_acc, dx), None

                    # dacc inherits pp-varying from the leaves and stays
                    # dp-INvarying: the vjp transpose auto-psums leaf
                    # cotangents over dp (invarying input x varying seed),
                    # so dl already carries the cross-dp sum
                    dacc0 = tuple(jnp.zeros_like(lv) for lv in lvs)
                    dpacc0 = tuple(jnp.zeros_like(pv) for pv in post)
                    h0, g0, ring0, loss0, dx0 = _pvary((
                        jnp.zeros(mb_shape, xloc.dtype),
                        jnp.zeros(mb_shape, xloc.dtype),
                        jnp.zeros((R,) + mb_shape, xloc.dtype),
                        jnp.zeros((), xloc.dtype),
                        jnp.zeros((M,) + mb_shape, xloc.dtype),
                    ), vary_axes)
                    carry0 = (h0, g0, ring0, dacc0, dpacc0, loss0, dx0)
                    carry, _ = lax.scan(tick, carry0,
                                        jnp.arange(M + 2 * pp - 1))
                    _, _, _, dacc, dpacc, loss_acc, dx = carry
                    from ...core.meshutil import legacy_manual_vjp
                    if legacy_manual_vjp():
                        # jax<0.5 shard_map: the in-body vjp cannot
                        # auto-psum cotangents of replicated inputs —
                        # fold the cross-dp leaf contributions and the
                        # cross-stage (+dp) post contributions here
                        # (mid stages contribute exact zeros to dpacc,
                        # so the pp psum is the identity fold)
                        if batch_tuple:
                            dacc = tuple(lax.psum(da, batch_tuple)
                                         for da in dacc)
                        dpacc = tuple(
                            lax.psum(dp_, (ax,) + batch_tuple)
                            for dp_ in dpacc)
                    # loss lives on the last stage; grads of x on stage 0
                    loss_out = lax.psum(
                        jnp.where(is_last, loss_acc, 0.0), ax)
                    dx = lax.psum(jnp.where(i == 0, dx, 0.0), ax)
                    if batch_tuple:
                        loss_out = lax.psum(loss_out, batch_tuple)
                    return (loss_out, dx) + tuple(dacc) + tuple(dpacc)

                xspec = P(None, batch_axes,
                          *([None] * (xm.ndim - 2)))
                tspec = P(None, batch_axes,
                          *([None] * (tm.ndim - 2)))
                lspec = tuple(P(ax) for _ in lvs_in)
                pspec = tuple(P() for _ in post_in)
                outs = _shard_map(
                    local, mesh=jmesh,
                    in_specs=(xspec, tspec) + lspec + pspec,
                    out_specs=(P(), xspec) + lspec + pspec)(
                        xmv, tmv, *lvs_in, *post_in)
                loss, dx = outs[0], outs[1]
                dls = outs[2:2 + n_leaves]
                dps = outs[2 + n_leaves:]
                return loss / (M * dp_n), dx, dls, dps

            @jax.custom_vjp
            def op(xmv, *rest):
                return run(xmv, tm, *rest)[0]

            def op_fwd(xmv, *rest):
                loss, dx, dls, dps = run(xmv, tm, *rest)
                return loss, (dx, dls, dps)

            def op_bwd(res, g):
                dx, dls, dps = res  # dx already has xm's shape
                return ((g * dx,) + tuple(g * dl for dl in dls)
                        + tuple(g * dp_ for dp_ in dps))

            op.defvjp(op_fwd, op_bwd)
            return op(xm, *leaves, *post_vals_in)

        # span over the 1F1B dispatch (forward+backward hops inside);
        # see the pp.forward note — hops are in-program, the span is
        # the host-observable bracket around them (the collective
        # watchdog arms the same bracket, ISSUE 15)
        from ...observability import tracing as _tracing
        from ...observability import watchdog as _watchdog
        with _tracing.span("pp.train_batch", stages=pp, microbatches=M,
                           overlap_p2p=_overlap_p2p()), \
                _watchdog.arm_collective("pp.train_batch",
                                         key=self.pp_axis):
            self._audit_impl("pipeline_1f1b", impl,
                             (x, target, *leaf_tensors, *post_params))
            return apply("pipeline_1f1b", impl, x, target,
                         *leaf_tensors, *post_params)


def _as_param(t: Tensor):
    from ...core.tensor import Parameter
    if isinstance(t, Parameter):
        return t
    return Parameter(t._read(), trainable=True)


class LayerDesc:
    """Reference ``pp_layers.py`` LayerDesc parity: a deferred layer
    constructor (so each pipeline instantiation builds fresh params)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class PipelineLayer(Layer):
    """Reference ``PipelineLayer`` parity for HOMOGENEOUS descs: every
    ``LayerDesc`` must build the same block structure (the transformer
    case pipeline parallelism exists for). Heterogeneous pre/post layers
    (embedding, head) belong OUTSIDE — run them unsharded around this
    stack, as ``GPTForCausalLMPipe`` does (reference keeps them in
    first/last stages; with GSPMD they simply stay on their own sharding).
    """

    def __init__(self, layers, num_stages=None, mesh=None, pp_axis="pp",
                 num_microbatches=1, remat=True, interleave=1):
        super().__init__()
        descs = list(layers)
        if not descs:
            raise ValueError("PipelineLayer needs at least one LayerDesc")
        if not all(isinstance(d, LayerDesc) for d in descs):
            raise TypeError("PipelineLayer(layers=...) takes LayerDesc "
                            "items (wrap eager layers in LayerDesc)")
        first = descs[0]
        if any(d.layer_cls is not first.layer_cls or d.args != first.args
               or d.kwargs != first.kwargs for d in descs[1:]):
            raise NotImplementedError(
                "SPMD pipelining requires structurally identical blocks; "
                "move heterogeneous prologue/epilogue layers outside the "
                "PipelineLayer")
        self.blocks = PipelinedBlocks(first.build_layer, len(descs),
                                      mesh=mesh, pp_axis=pp_axis,
                                      num_microbatches=num_microbatches,
                                      remat=remat, interleave=interleave)

    def forward(self, x, batch_axes=None):
        return self.blocks(x, batch_axes=batch_axes)

    def train_batch(self, x, target, loss_fn, batch_axes=None,
                    post_params=None):
        """Fused 1F1B step (see ``PipelinedBlocks.train_batch``)."""
        return self.blocks.train_batch(x, target, loss_fn,
                                       batch_axes=batch_axes,
                                       post_params=post_params)

"""Pipeline parallelism — SPMD GPipe over a ``pp`` mesh axis.

Capability analog of the reference's pipeline stack (SURVEY D15-D17):
``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
(schedules, 1F1B :663), ``parallel_layers/pp_layers.py`` (PipelineLayer /
LayerDesc), ``pp_utils/p2p_communication.py`` (stage P2P). The reference
runs one process per stage and hand-schedules NCCL send/recv; here the
whole pipeline is ONE SPMD program:

- the repeated block stack's parameters are stacked into ``[L, ...]``
  arrays sharded ``Shard(0)`` over the ``pp`` axis — stage assignment IS
  the sharding;
- a ``jax.shard_map`` + ``lax.scan`` runs the classic fill-drain (GPipe)
  schedule: at tick ``t`` stage ``i`` computes microbatch ``t - i`` and
  hands its activation to stage ``i+1`` via ``lax.ppermute`` (ICI
  neighbor hop — the p2p_communication analog);
- backward is JAX's transpose of the scan: activations flow backward
  through reversed ppermutes, giving the mirrored drain-fill schedule
  without a hand-written 1F1B engine. ``jax.checkpoint`` on the per-layer
  body keeps the live set to O(microbatch) per stage.

Bubble fraction is the textbook ``(pp-1)/(M+pp-1)`` — raise
``num_microbatches`` to amortize, exactly as with the reference's GPipe
mode.
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ...core import state
from ...core import tensor as tensor_mod
from ...core.dispatch import apply
from ...core.tensor import Tensor
from ...nn.layer import Layer


def functional_call(layer: Layer, param_vals: dict, *args):
    """Run ``layer.forward`` as a PURE function of ``param_vals``
    (name -> raw array), torch.func.functional_call-style.

    Used to trace a Layer's computation with externally-managed (stacked /
    sliced / traced) parameter values: the layer's parameter tensors are
    temporarily re-pointed at ``param_vals``, the tape and any jit-capture
    tracker are disabled (the caller owns differentiation — usually the
    dispatch funnel's ``jax.vjp`` around the enclosing composite op), and
    the original buffers are restored afterwards."""
    params = dict(layer.named_parameters())
    missing = set(params) - set(param_vals)
    if missing:
        raise ValueError(f"functional_call missing values for {missing}")
    originals = {n: p._data for n, p in params.items()}

    def wrap(a):
        if isinstance(a, (tuple, list)):
            return type(a)(wrap(e) for e in a)
        return a if isinstance(a, Tensor) else Tensor(a)

    def unwrap(o):
        if isinstance(o, (tuple, list)):
            return type(o)(unwrap(e) for e in o)
        return o._data if isinstance(o, Tensor) else o

    old_tracker = tensor_mod.set_tracker(None)
    old_grad = state.set_grad_enabled(False)
    try:
        for n, p in params.items():
            p._data = param_vals[n]
        out = layer(*[wrap(a) for a in args])
    finally:
        state.set_grad_enabled(old_grad)
        tensor_mod.set_tracker(old_tracker)
        for n, p in params.items():
            p._data = originals[n]
    return unwrap(out)


from ...core.meshutil import pvary as _pvary


class PipelinedBlocks(Layer):
    """A stack of ``num_layers`` structurally-identical blocks executed as
    an SPMD pipeline (see module docstring). The per-leaf parameters are
    stored STACKED (``[L, *shape]``) so ``Shard(0)`` over the pp axis
    assigns ``L/pp`` consecutive layers to each stage — the analog of the
    reference PipelineLayer's segment allocation (``pp_layers.py``
    ``_segment_network``).

    ``block_factory()`` must build one block Layer; blocks may not carry
    buffers or active dropout (single-program pipelining threads only
    parameters; RNG-bearing blocks would constant-fold their keys).
    """

    def __init__(self, block_factory: Callable[[], Layer], num_layers: int,
                 mesh=None, pp_axis: str = "pp", num_microbatches: int = 1,
                 remat: bool = True):
        super().__init__()
        self.num_layers = num_layers
        self.pp_axis = pp_axis
        self.num_microbatches = num_microbatches
        self.remat = remat
        self._mesh = None
        self.template = block_factory()
        if any(True for _ in self.template.named_buffers()):
            raise ValueError("PipelinedBlocks: blocks must be buffer-free "
                             "(running stats can't thread the pipeline)")
        # stack L independent initializations leaf-wise -> [L, *shape]
        inits = [self.template] + [block_factory()
                                   for _ in range(num_layers - 1)]
        self._names = [n for n, _ in self.template.named_parameters()]
        for n in self._names:
            leaves = [dict(b.named_parameters())[n]._read() for b in inits]
            stacked = Tensor(jnp.stack(leaves, axis=0), stop_gradient=False)
            self.add_parameter(self._mangle(n), _as_param(stacked))
        if mesh is not None:
            self.shard(mesh, pp_axis)

    @staticmethod
    def _mangle(name: str) -> str:
        return "stacked__" + name.replace(".", "__")

    def stacked_parameter(self, name: str):
        return self._parameters[self._mangle(name)]

    def shard(self, mesh, pp_axis: str = "pp"):
        """Pin Shard(0) over ``pp_axis`` on every stacked leaf."""
        from ..auto_parallel.api import Replicate, Shard, shard_parameter
        self._mesh = mesh
        self.pp_axis = pp_axis
        dim = mesh.dim_names.index(pp_axis)
        pl = [Replicate()] * mesh.ndim
        pl[dim] = Shard(0)
        for n in self._names:
            shard_parameter(self.stacked_parameter(n), mesh, pl)
        return self

    # -- the schedule --------------------------------------------------
    def forward(self, x, batch_axes=None):
        if self._mesh is None:
            raise RuntimeError("call .shard(mesh, pp_axis) first")
        mesh = self._mesh
        jmesh = getattr(mesh, "jmesh", mesh)
        pp = dict(zip(jmesh.axis_names, jmesh.devices.shape))[self.pp_axis]
        M = self.num_microbatches
        L, ax = self.num_layers, self.pp_axis
        if L % pp:
            raise ValueError(f"num_layers {L} not divisible by pp {pp}")
        template, names = self.template, self._names
        remat = self.remat
        if isinstance(batch_axes, str):
            batch_tuple = (batch_axes,)
        else:
            batch_tuple = tuple(batch_axes or ())
        vary_axes = (ax,) + batch_tuple

        leaf_tensors = [self.stacked_parameter(n) for n in names]

        def impl(xv, *leaves):
            b = xv.shape[0]
            if b % M:
                raise ValueError(f"batch {b} not divisible by "
                                 f"num_microbatches {M}")
            xm = xv.reshape((M, b // M) + xv.shape[1:])

            def block_apply(h, layer_leaves):
                vals = dict(zip(names, layer_leaves))
                y = functional_call(template, vals, h)
                return y, None

            if remat:
                block_apply = jax.checkpoint(block_apply)

            def local(xloc, *lvs):
                i = lax.axis_index(ax)
                mb_shape = xloc.shape[1:]

                def tick(carry, t):
                    h_in, outputs = carry
                    inject = xloc[jnp.clip(t, 0, M - 1)]
                    h = jnp.where(i == 0, inject, h_in)
                    y, _ = lax.scan(block_apply, h, lvs)
                    m_out = t - (pp - 1)
                    idx = jnp.clip(m_out, 0, M - 1)
                    valid = (i == pp - 1) & (m_out >= 0)
                    cur = lax.dynamic_index_in_dim(outputs, idx, 0,
                                                   keepdims=False)
                    outputs = lax.dynamic_update_index_in_dim(
                        outputs, jnp.where(valid, y, cur), idx, 0)
                    nxt = lax.ppermute(y, ax,
                                       [(r, (r + 1) % pp)
                                        for r in range(pp)])
                    return (nxt, outputs), None

                h0 = jnp.zeros(mb_shape, xloc.dtype)
                out0 = jnp.zeros((M,) + mb_shape, xloc.dtype)
                h0, out0 = _pvary((h0, out0), vary_axes)
                (_, outputs), _ = lax.scan(tick, (h0, out0),
                                           jnp.arange(M + pp - 1))
                # results live on the last stage; replicate over pp
                outputs = lax.psum(
                    jnp.where(i == pp - 1, outputs, 0), ax)
                return outputs

            xspec = P(None, batch_axes, *([None] * (xv.ndim - 1)))
            lspec = tuple(P(ax) for _ in leaves)
            out = jax.shard_map(local, mesh=jmesh,
                                in_specs=(xspec,) + lspec,
                                out_specs=xspec)(xm, *leaves)
            return out.reshape((b,) + xv.shape[1:])

        return apply("pipelined_blocks", impl, x, *leaf_tensors)


def _as_param(t: Tensor):
    from ...core.tensor import Parameter
    if isinstance(t, Parameter):
        return t
    return Parameter(t._read(), trainable=True)


class LayerDesc:
    """Reference ``pp_layers.py`` LayerDesc parity: a deferred layer
    constructor (so each pipeline instantiation builds fresh params)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class PipelineLayer(Layer):
    """Reference ``PipelineLayer`` parity for HOMOGENEOUS descs: every
    ``LayerDesc`` must build the same block structure (the transformer
    case pipeline parallelism exists for). Heterogeneous pre/post layers
    (embedding, head) belong OUTSIDE — run them unsharded around this
    stack, as ``GPTForCausalLMPipe`` does (reference keeps them in
    first/last stages; with GSPMD they simply stay on their own sharding).
    """

    def __init__(self, layers, num_stages=None, mesh=None, pp_axis="pp",
                 num_microbatches=1, remat=True):
        super().__init__()
        descs = list(layers)
        if not descs:
            raise ValueError("PipelineLayer needs at least one LayerDesc")
        if not all(isinstance(d, LayerDesc) for d in descs):
            raise TypeError("PipelineLayer(layers=...) takes LayerDesc "
                            "items (wrap eager layers in LayerDesc)")
        first = descs[0]
        if any(d.layer_cls is not first.layer_cls or d.args != first.args
               or d.kwargs != first.kwargs for d in descs[1:]):
            raise NotImplementedError(
                "SPMD pipelining requires structurally identical blocks; "
                "move heterogeneous prologue/epilogue layers outside the "
                "PipelineLayer")
        self.blocks = PipelinedBlocks(first.build_layer, len(descs),
                                      mesh=mesh, pp_axis=pp_axis,
                                      num_microbatches=num_microbatches,
                                      remat=remat)

    def forward(self, x, batch_axes=None):
        return self.blocks(x, batch_axes=batch_axes)

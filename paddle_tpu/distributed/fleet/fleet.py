"""Fleet facade: init / distributed_model / distributed_optimizer.

Capability analog of ``python/paddle/distributed/fleet/fleet.py`` (SURVEY
D13; ``Fleet`` ``:100``, hybrid_configs ``:605-610``, ``distributed_model``
``model.py:32``). The reference wraps the model per-strategy with NCCL
group plumbing; here ``init`` builds the hybrid mesh and
``distributed_model`` pins GSPMD shardings (batch over dp×sharding,
parameters replicated unless a TP layer already sharded them).
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...nn.layer import Layer
from .topology import HybridCommunicateGroup

_hcg: Optional[HybridCommunicateGroup] = None
_strategy = None


class DistributedStrategy:
    """Reference ``distributed_strategy.py`` DistributedStrategy proto —
    the hybrid_configs subset that matters on TPU plus pass-through dicts
    for the rest."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.find_unused_parameters = False


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    """Reference ``fleet.py:167`` fleet.init."""
    global _hcg, _strategy
    strategy = strategy or DistributedStrategy()
    cfg = strategy.hybrid_configs
    _strategy = strategy
    _hcg = HybridCommunicateGroup(
        dp_degree=cfg.get("dp_degree", 1),
        mp_degree=cfg.get("mp_degree", 1),
        pp_degree=cfg.get("pp_degree", 1),
        sharding_degree=cfg.get("sharding_degree", 1),
        sep_degree=cfg.get("sep_degree", 1))
    from .. import collective as _coll
    _coll._ensure_world()
    return _hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg


def worker_index():
    return 0


def worker_num():
    return len(jax.devices())


class HybridParallelModel(Layer):
    """Wraps a model for hybrid execution: shards batch inputs over the
    dp×sharding axes; TP layers inside carry their own weight shardings.
    Analog of the meta_parallel wrappers (reference ``model.py:141-160``)."""

    def __init__(self, layers: Layer, hcg: HybridCommunicateGroup):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        mesh = hcg.mesh
        repl = NamedSharding(mesh, P())
        for p in layers.parameters():
            v = p._read()
            if not isinstance(v, jax.core.Tracer) and not p.is_dist():
                p._write(jax.device_put(v, repl))

    def forward(self, *inputs, **kwargs):
        mesh = self._hcg.mesh
        dpdeg = (self._hcg.get_data_parallel_world_size() *
                 self._hcg.get_sharding_parallel_world_size())
        sh = NamedSharding(mesh, P(("dp", "sharding")))

        def shard_batch(x):
            if isinstance(x, Tensor):
                v = x._read()
                if (not isinstance(v, jax.core.Tracer) and v.ndim > 0
                        and v.shape[0] % dpdeg == 0):
                    return Tensor(jax.device_put(v, sh),
                                  stop_gradient=x.stop_gradient)
            return x

        inputs = tuple(shard_batch(x) for x in inputs)
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


def distributed_model(model: Layer) -> Layer:
    """Reference ``fleet/base/distributed_strategy`` + ``model.py:32``."""
    if _hcg is None:
        init()
    return HybridParallelModel(model, _hcg)


def distributed_optimizer(optimizer, strategy=None):
    """Reference ``fleet.py`` distributed_optimizer: wraps with the
    HybridParallelOptimizer behavior. Under GSPMD gradients are globally
    correct by construction, so the wrapper only adds sharding-stage
    handling when sharding_degree > 1."""
    if _hcg is not None and _hcg.get_sharding_parallel_world_size() > 1:
        from .sharding_optimizer import DygraphShardingOptimizer
        return DygraphShardingOptimizer(optimizer, _hcg)
    return optimizer

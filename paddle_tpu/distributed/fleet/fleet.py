"""Fleet facade: init / distributed_model / distributed_optimizer.

Capability analog of ``python/paddle/distributed/fleet/fleet.py`` (SURVEY
D13; ``Fleet`` ``:100``, hybrid_configs ``:605-610``, ``distributed_model``
``model.py:32``). The reference wraps the model per-strategy with NCCL
group plumbing; here ``init`` builds the hybrid mesh and
``distributed_model`` pins GSPMD shardings (batch over dp×sharding,
parameters replicated unless a TP layer already sharded them).
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...nn.layer import Layer
from .topology import HybridCommunicateGroup

_hcg: Optional[HybridCommunicateGroup] = None
_strategy = None


class DistributedStrategy:
    """Reference ``distributed_strategy.py`` DistributedStrategy proto —
    the hybrid_configs subset that matters on TPU plus pass-through dicts
    for the rest."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.find_unused_parameters = False


class _PsRole:
    """PS-mode role state from the reference env contract
    (``fleet/base/role_maker.py:854-909``): ``TRAINING_ROLE`` =
    PSERVER | TRAINER, ``PADDLE_PSERVERS_IP_PORT_LIST``,
    ``PADDLE_TRAINERS_NUM``, ``PADDLE_TRAINER_ID``/``PADDLE_PORT``."""

    def __init__(self):
        import os
        self.role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self.server_endpoints = [e for e in eps.split(",") if e]
        self.n_workers = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        self.worker_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self.port = os.environ.get("PADDLE_PORT")
        self.pod_ip = os.environ.get("POD_IP")
        sid = os.environ.get("PADDLE_PSERVER_ID")
        self.server_id = None if sid is None else int(sid)
        self.server = None
        self.client = None

    def my_server_endpoint(self):
        """This pserver's own endpoint (reference role_maker derives it
        from POD_IP + PADDLE_PORT; PADDLE_PSERVER_ID also works here)."""
        if self.server_id is not None:
            return self.server_endpoints[self.server_id]
        if self.pod_ip and self.port:
            want = f"{self.pod_ip}:{self.port}"
            if want in self.server_endpoints:
                return want
        if self.port:
            return f"0.0.0.0:{self.port}"
        if len(self.server_endpoints) == 1:
            return self.server_endpoints[0]
        raise RuntimeError(
            "cannot identify this pserver among "
            f"{self.server_endpoints}: set PADDLE_PSERVER_ID or "
            "POD_IP + PADDLE_PORT")


_ps_role: Optional[_PsRole] = None


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    """Reference ``fleet.py:167`` fleet.init. ``is_collective=False``
    enters PS mode and reads the role env contract."""
    global _hcg, _strategy, _ps_role
    strategy = strategy or DistributedStrategy()
    _strategy = strategy
    if not is_collective:
        _ps_role = _PsRole()
        return _ps_role
    cfg = strategy.hybrid_configs
    _hcg = HybridCommunicateGroup(
        dp_degree=cfg.get("dp_degree", 1),
        mp_degree=cfg.get("mp_degree", 1),
        pp_degree=cfg.get("pp_degree", 1),
        sharding_degree=cfg.get("sharding_degree", 1),
        sep_degree=cfg.get("sep_degree", 1))
    from .. import collective as _coll
    _coll._ensure_world()
    return _hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg


def worker_index():
    return _ps_role.worker_id if _ps_role is not None else 0


def worker_num():
    return (_ps_role.n_workers if _ps_role is not None
            else len(jax.devices()))


# -- PS-mode role flow (reference fleet.is_server/run_server/init_worker) --

def _require_ps():
    if _ps_role is None:
        raise RuntimeError("PS mode: call fleet.init(is_collective=False) "
                           "with the TRAINING_ROLE env contract first")
    return _ps_role


def is_server():
    return _require_ps().role == "PSERVER"


def is_worker():
    return _require_ps().role == "TRAINER"


def server_num():
    return len(_require_ps().server_endpoints)


def server_endpoints():
    return list(_require_ps().server_endpoints)


def run_server(sync=False):
    """Host this node's PS shard; blocks until a worker sends stop
    (reference fleet.run_server)."""
    role = _require_ps()
    from ..ps import PsServer
    role.server = PsServer(role.my_server_endpoint(),
                           n_workers=role.n_workers, sync=sync)
    role.server.run()


def init_worker():
    """Connect this trainer to every PS node (reference
    fleet.init_worker)."""
    role = _require_ps()
    from ..ps import PsClient
    role.client = PsClient(role.server_endpoints)
    return role.client


def barrier_worker():
    role = _require_ps()
    if role.client is not None:
        role.client.barrier("worker_barrier", role.n_workers)


def stop_worker():
    """Last worker out stops the servers (reference fleet.stop_worker)."""
    role = _require_ps()
    if role.client is not None:
        role.client.stop_servers()
        role.client.close()
        role.client = None


class HybridParallelModel(Layer):
    """Wraps a model for hybrid execution: shards batch inputs over the
    dp×sharding axes; TP layers inside carry their own weight shardings.
    Analog of the meta_parallel wrappers (reference ``model.py:141-160``)."""

    def __init__(self, layers: Layer, hcg: HybridCommunicateGroup):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        mesh = hcg.mesh
        repl = NamedSharding(mesh, P())
        for p in layers.parameters():
            v = p._read()
            if not isinstance(v, jax.core.Tracer) and not p.is_dist():
                p._write(jax.device_put(v, repl))

    def forward(self, *inputs, **kwargs):
        mesh = self._hcg.mesh
        dpdeg = (self._hcg.get_data_parallel_world_size() *
                 self._hcg.get_sharding_parallel_world_size())
        sh = NamedSharding(mesh, P(("dp", "sharding")))

        def shard_batch(x):
            if isinstance(x, Tensor):
                v = x._read()
                if (not isinstance(v, jax.core.Tracer) and v.ndim > 0
                        and v.shape[0] % dpdeg == 0):
                    return Tensor(jax.device_put(v, sh),
                                  stop_gradient=x.stop_gradient)
            return x

        inputs = tuple(shard_batch(x) for x in inputs)
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


def distributed_model(model: Layer) -> Layer:
    """Reference ``fleet/base/distributed_strategy`` + ``model.py:32``."""
    if _hcg is None:
        init()
    return HybridParallelModel(model, _hcg)


def distributed_optimizer(optimizer, strategy=None):
    """Reference ``fleet.py`` distributed_optimizer: wraps with the
    HybridParallelOptimizer behavior. Under GSPMD gradients are globally
    correct by construction, so the wrapper only adds sharding-stage
    handling when sharding_degree > 1."""
    if _hcg is not None and _hcg.get_sharding_parallel_world_size() > 1:
        from .sharding_optimizer import DygraphShardingOptimizer
        return DygraphShardingOptimizer(optimizer, _hcg)
    return optimizer

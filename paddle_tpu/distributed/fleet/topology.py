"""Hybrid-parallel topology over the TPU mesh.

Capability analog of ``python/paddle/distributed/fleet/base/topology.py``
(SURVEY D13; ``CommunicateTopology`` ``:65``, ``HybridCommunicateGroup``
``:178``). The reference builds one NCCL group per axis-combination; here
the topology IS a single N-D ``jax.sharding.Mesh`` with axes in the
reference's canonical order ``[dp, pp, sharding, sep, mp]`` — XLA
collectives target mesh axes directly, so per-combination groups are
unnecessary. Axis order puts ``mp`` innermost (fastest-varying device
index) so tensor-parallel collectives ride the shortest ICI hops, matching
the reference's ordering rationale.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# canonical axis order (reference fleet.py:605 hybrid_configs order)
AXES = ("dp", "pp", "sharding", "sep", "mp")


class CommunicateTopology:
    """Reference ``topology.py:65``: named dims + coordinate arithmetic."""

    def __init__(self,
                 hybrid_group_names: Sequence[str] = ("data", "pipe",
                                                      "sharding", "sep",
                                                      "model"),
                 dims: Sequence[int] = (1, 1, 1, 1, 1)):
        self._names = list(hybrid_group_names)
        self._dims = list(dims)

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, name):
        return self._dims[self._names.index(name)]

    def world_size(self):
        return int(np.prod(self._dims))

    def get_dim_size(self, name):
        return self.get_dim(name)

    def get_comm_list(self, axis_name):
        """Rank lists of every group along ``axis_name`` (reference shape)."""
        names = self._names
        dims = self._dims
        idx = names.index(axis_name)
        ranks = np.arange(self.world_size()).reshape(dims)
        moved = np.moveaxis(ranks, idx, -1).reshape(-1, dims[idx])
        return moved.tolist()

    def get_rank(self, **coords):
        idx = [coords[n] for n in self._names]
        return int(np.ravel_multi_index(idx, self._dims))

    def get_coord(self, rank):
        return tuple(int(c) for c in np.unravel_index(rank, self._dims))


class AxisGroup:
    """A mesh-axis view usable by collectives: (mesh, axis_name). The
    analog of one reference comm group, except it simultaneously denotes
    *all* groups along the axis (XLA partitions by coordinate)."""

    def __init__(self, mesh: Mesh, axis: str):
        self.mesh = mesh
        self.axis = axis
        self.nranks = mesh.shape[axis]
        self.ranks = list(range(self.nranks))

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank: int) -> int:
        return rank if 0 <= rank < self.nranks else -1

    def __repr__(self):
        return f"AxisGroup(axis={self.axis}, nranks={self.nranks})"


class HybridCommunicateGroup:
    """Reference ``topology.py:178``: the 5-D hybrid view.

    Single-controller: rank-dependent getters return the coordinate of this
    controller's first device (0 on a fresh mesh) — model code should be
    written against the mesh axes, not ranks.
    """

    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 dp_degree=1, mp_degree=1, pp_degree=1, sharding_degree=1,
                 sep_degree=1, devices=None):
        if topology is not None:
            dims = [topology.get_dim(n) for n in
                    topology.get_hybrid_group_names()]
            dp_degree, pp_degree, sharding_degree, sep_degree, mp_degree = \
                dims
        self._topo = CommunicateTopology(
            ("data", "pipe", "sharding", "sep", "model"),
            (dp_degree, pp_degree, sharding_degree, sep_degree, mp_degree))
        devices = list(jax.devices()) if devices is None else list(devices)
        need = dp_degree * pp_degree * sharding_degree * sep_degree * mp_degree
        if need > len(devices):
            raise ValueError(
                f"hybrid topology needs {need} devices, have {len(devices)}")
        dev = np.array(devices[:need], dtype=object).reshape(
            (dp_degree, pp_degree, sharding_degree, sep_degree, mp_degree))
        self.mesh = Mesh(dev, AXES)
        self.nranks = need
        self.global_rank = 0

    # --- degree/rank getters (reference API names) ---------------------
    def get_parallel_mode(self):
        if self._topo.get_dim("model") > 1:
            return "tensor_parallel"
        if self._topo.get_dim("pipe") > 1:
            return "pipeline_parallel"
        if self._topo.get_dim("sharding") > 1:
            return "sharding_parallel"
        return "data_parallel"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    def _axis(self, name) -> AxisGroup:
        return AxisGroup(self.mesh, name)

    # data parallel
    def get_data_parallel_world_size(self):
        return self._topo.get_dim("data")

    def get_data_parallel_rank(self):
        return 0

    def get_data_parallel_group(self):
        return self._axis("dp")

    def get_data_parallel_group_src_rank(self):
        return 0

    # model (tensor) parallel
    def get_model_parallel_world_size(self):
        return self._topo.get_dim("model")

    def get_model_parallel_rank(self):
        return 0

    def get_model_parallel_group(self):
        return self._axis("mp")

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline
    def get_pipe_parallel_world_size(self):
        return self._topo.get_dim("pipe")

    def get_stage_id(self):
        return 0

    def get_pipe_parallel_group(self):
        return self._axis("pp")

    # sharding
    def get_sharding_parallel_world_size(self):
        return self._topo.get_dim("sharding")

    def get_sharding_parallel_rank(self):
        return 0

    def get_sharding_parallel_group(self):
        return self._axis("sharding")

    # sep
    def get_sep_parallel_world_size(self):
        return self._topo.get_dim("sep")

    def get_sep_parallel_rank(self):
        return 0

    def get_sep_parallel_group(self):
        return self._axis("sep")

    def get_check_parallel_group(self, *a, **k):
        return self._axis("mp")

    def get_rank_from_stage(self, stage_id, **kwargs):
        return stage_id

    # --- hybrid-training bridge (ISSUE 11) -----------------------------
    def process_mesh(self, axes: Optional[Sequence[str]] = None):
        """The auto_parallel :class:`ProcessMesh` over this topology's
        device grid — the object ``PipelinedBlocks.shard`` /
        ``shard_parameter`` consume, so the hybrid topology can drive
        the SPMD pipeline directly::

            hcg = HybridCommunicateGroup(dp_degree=2, pp_degree=2,
                                         mp_degree=2)
            pipe = GPTForCausalLMPipe(cfg, hcg.process_mesh(),
                                      pp_axis="pp", dp_axis="dp",
                                      tp_axis="mp")

        ``axes``: keep only these mesh dims (size-1 dims dropped by
        default keep PartitionSpecs readable); None keeps every dim
        whose degree > 1, or ``dp`` alone on a fully-degenerate
        topology.
        """
        from ..auto_parallel.api import ProcessMesh
        dims = [self._topo.get_dim(n) for n in
                ("data", "pipe", "sharding", "sep", "model")]
        ranks = np.arange(self.nranks).reshape(dims)
        keep = [i for i, (name, deg) in enumerate(zip(AXES, dims))
                if (axes is not None and name in axes)
                or (axes is None and deg > 1)]
        if not keep:
            keep = [0]  # degenerate 1-device topology: a dp-only mesh
        drop = [i for i in range(len(AXES)) if i not in keep]
        ranks = ranks.transpose(keep + drop).reshape(
            [dims[i] for i in keep])
        return ProcessMesh(ranks, [AXES[i] for i in keep])

    def get_data_parallel_comm_group(self):
        """A ``collective.Group`` over the dp-axis devices at this
        controller's coordinate (mp/pp/... fixed at 0) — what
        ``DataParallel``/the overlap grad-sync scheduler take when the
        replicated-eager DP path runs alongside the in-program pp/mp
        axes."""
        from .. import collective as _coll
        dims = [self._topo.get_dim(n) for n in
                ("data", "pipe", "sharding", "sep", "model")]
        ranks = np.arange(self.nranks).reshape(dims)[:, 0, 0, 0, 0]
        return _coll.new_group([int(r) for r in ranks])

"""Activation recompute (gradient checkpointing).

Capability analog of ``python/paddle/distributed/fleet/recompute/
recompute.py:404`` (SURVEY D19): trade FLOPs for activation memory by
re-running a block's forward during backward. TPU-native mechanism: the
reference re-executes the Python block under a preserved RNG state; here the
block is lifted into one ``jax.checkpoint``-wrapped pure function over
(tensor args + the block's parameters), so XLA itself rematerializes inside
the compiled program — in eager it shortens the tape's saved residuals to
just the block inputs.

Limitation: stateful side effects inside the block (BatchNorm running
stats, RNG-consuming dropout) are not threaded out of the checkpointed
region — matching LLM-pretrain usage (dropout=0). Use ``jit.to_static``
around the full step for peak effect.
"""
from __future__ import annotations

import jax

from ...core import tensor as tensor_mod
from ...core.autograd import no_grad
from ...core.dispatch import apply
from ...core.tensor import Tensor


class _SubstituteTracker:
    """Maps a chosen set of tensors to trace-time values; everything else
    chains to the enclosing tracker (a jit capture, or none)."""

    def __init__(self, mapping, outer):
        self.map = mapping
        self.outer = outer
        self.writes: dict[int, object] = {}

    def on_create(self, t):
        if self.outer is not None:
            self.outer.on_create(t)

    def on_read(self, t):
        tid = id(t)
        if tid in self.map:
            return self.map[tid]
        if tid in self.writes:
            return self.writes[tid]
        if self.outer is not None:
            return self.outer.on_read(t)
        return t._data

    def on_write(self, t, val):
        # swallowed: values born inside jax.checkpoint must not escape the
        # trace through framework state (they would be leaked tracers)
        self.writes[id(t)] = val

    def on_grad_write(self, t):
        pass

    def add_host_sync(self, fn):
        if self.outer is not None:
            self.outer.add_host_sync(fn)


class _ReadRecorder:
    """Records which pre-existing Tensors a callable reads (to discover the
    parameters of a plain function/lambda passed to ``recompute``); writes
    are swallowed exactly like the substitute tracker so the probe run has
    no side effects on framework state."""

    def __init__(self, outer):
        self.outer = outer
        self.reads: list[Tensor] = []
        self._seen: set[int] = set()
        self._fresh: set[int] = set()
        self.writes: dict[int, object] = {}

    def on_create(self, t):
        self._fresh.add(id(t))
        if self.outer is not None:
            self.outer.on_create(t)

    def on_read(self, t):
        tid = id(t)
        if tid in self.writes:
            return self.writes[tid]
        if tid not in self._fresh and tid not in self._seen:
            self._seen.add(tid)
            self.reads.append(t)
        if self.outer is not None:
            return self.outer.on_read(t)
        return t._data

    def on_write(self, t, val):
        self.writes[id(t)] = val

    def on_grad_write(self, t):
        pass

    def add_host_sync(self, fn):
        pass


def _discover_params(function, args, kwargs):
    """Differentiable parameters read by ``function``: from the owning
    Layer when bound, else from a side-effect-free probe run (its outputs
    are unused, so under jit the probe is dead code XLA removes)."""
    owner = getattr(function, "__self__", None)
    if hasattr(owner, "parameters"):
        return [p for p in owner.parameters() if not p.stop_gradient]
    if hasattr(function, "parameters"):  # a Layer passed directly
        return [p for p in function.parameters() if not p.stop_gradient]
    cached = getattr(function, "_pdtpu_recompute_params", None)
    if cached is not None:
        return cached
    rec = _ReadRecorder(tensor_mod._tracker)
    old = tensor_mod.set_tracker(rec)
    try:
        with no_grad():
            function(*args, **kwargs)
    finally:
        tensor_mod.set_tracker(old)
    params = [t for t in rec.reads if not t.stop_gradient]
    # Cache on the function object: a reused callable probes only once.
    # (A lambda recreated every step re-probes — under jit.to_static the
    # probe is dead code XLA removes, but in pure-eager loops prefer a bound
    # Layer method, which skips probing entirely.)
    try:
        function._pdtpu_recompute_params = params
    except AttributeError:
        pass
    return params


def _dots_and_kernels_saveable(prim, *_, **__):
    """dots_saveable + custom (Pallas) kernel calls: ``dots_saveable``
    matches only dot_general, so a flash-attention forward inside a
    checkpointed block gets RE-RUN during backward (~0.4 ms x layers per
    step on the GPT bench). Marking custom/pallas calls saveable keeps
    their outputs as residuals instead; the extra HBM is one [B,S,H,D]
    activation per layer."""
    import jax as _jax
    if _jax.checkpoint_policies.dots_saveable(prim, *_, **__):
        return True
    return prim.name in ("pallas_call", "custom_vjp_call",
                         "custom_vjp_call_jaxpr")


def _named_saveable():
    import jax as _jax
    return _jax.checkpoint_policies.save_only_these_names(
        "ln_out", "act_out")


_NAMED_SAVEABLE = None


def _transformer_saveable(prim, *a, **k):
    """dots + kernels + the named transformer activations (ln_out /
    act_out, tagged via ``jax.ad_checkpoint.checkpoint_name`` in
    F.layer_norm and F.gelu): the backward reads the saved normed
    activations and GELU outputs instead of re-running the reductions
    and transcendentals. MEASURED SLOWER than dots_and_kernels on the
    GPT-124M bench (97.96 vs ~94 ms/step, r5 anatomy — the saved GELU
    residuals cost more HBM than their recompute) — this is a memory/
    recompute KNOB, not a default. Called once per jaxpr eqn, so the
    underlying policy object is built once."""
    global _NAMED_SAVEABLE
    if _NAMED_SAVEABLE is None:
        _NAMED_SAVEABLE = _named_saveable()
    if _NAMED_SAVEABLE(prim, *a, **k):
        return True
    return _dots_and_kernels_saveable(prim, *a, **k)


_POLICIES = {
    None: None,
    "full": None,  # rematerialize everything (reference behavior)
    # save EVERY residual — zero recompute work in backward. The
    # checkpoint region still exists, which makes this the remat-OFF
    # anchor for bitwise A/B: policies differ only in which residuals
    # the backward reads saved vs recomputes, never in the math, so
    # grads across the whole spectrum (everything_saveable .. full)
    # are bitwise-identical (tests/test_train_perf.py). The eager
    # per-op tape sits OUTSIDE this family: its cotangent accumulation
    # order differs from a region vjp by ~1e-10 ulps (test_models.py
    # compares it at tolerance for that reason).
    "everything_saveable": "everything_saveable",
    # save MXU matmul outputs, recompute only elementwise ops — trades a
    # little HBM for skipping the expensive half of the re-forward
    "dots_saveable": "dots_saveable",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims_saveable",
    # dots + Pallas custom calls (flash attention) saveable: skips the
    # in-backward re-run of the attention forward kernel
    "dots_and_kernels_saveable": _dots_and_kernels_saveable,
    # + named ln/gelu activations (see _transformer_saveable)
    "transformer_saveable": _transformer_saveable,
}


def recompute(function, *args, use_reentrant=True, preserve_rng_state=True,
              policy=None, **kwargs):
    """Run ``function(*args)`` with its activations rematerialized in
    backward. ``function`` may be a bound ``Layer`` method (parameters come
    from the owning layer), a ``Layer``, or any callable (parameters are
    discovered by a probe run); they are threaded as explicit
    differentiable inputs of the checkpointed region.

    ``policy`` (TPU extension over the reference signature): a
    ``jax.checkpoint_policies`` name — "full" (default, the reference's
    recompute-everything), or "dots_saveable" to keep matmul outputs and
    recompute only the cheap elementwise ops."""
    params = _discover_params(function, args, kwargs)
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    arg_ids = {id(a) for a in tensor_args}
    params = [p for p in params if id(p) not in arg_ids]
    all_inputs = tensor_args + params

    def run_block(*vals):
        mapping = {id(t): v for t, v in zip(all_inputs, vals)}
        sub = _SubstituteTracker(mapping, tensor_mod._tracker)
        old = tensor_mod.set_tracker(sub)
        try:
            with no_grad():
                out = function(*args, **kwargs)
        finally:
            tensor_mod.set_tracker(old)
        if isinstance(out, Tensor):
            return sub.writes.get(id(out), out._data)
        return tuple(sub.writes.get(id(o), o._data)
                     for o in out if isinstance(o, Tensor))

    if policy not in _POLICIES:
        raise ValueError(f"unknown recompute policy {policy!r}; "
                         f"one of {sorted(k for k in _POLICIES if k)}")
    pol_name = _POLICIES[policy]
    if callable(pol_name):
        pol = pol_name
    else:
        pol = (getattr(jax.checkpoint_policies, pol_name) if pol_name
               else None)
    ckpt = jax.checkpoint(run_block, policy=pol)
    return apply("recompute", lambda *vals: ckpt(*vals), *all_inputs)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Reference ``recompute_sequential``: checkpoint a Sequential in
    segments. ``ctx`` = {"segments": k}."""
    segments = int(ctx.get("segments", 1)) if isinstance(ctx, dict) else 1
    layers = list(functions)
    if segments <= 1:
        chunks = [layers]
    else:
        per = max(1, len(layers) // segments)
        chunks = [layers[i:i + per] for i in range(0, len(layers), per)]

    out = args[0] if len(args) == 1 else args

    class _Seg:
        """Bound-method shim so recompute() can discover the chunk params."""

        def __init__(self, seg_layers):
            self._layers = seg_layers

        def parameters(self):
            ps = []
            for l in self._layers:
                ps.extend(l.parameters())
            return ps

        def __call__(self, x):
            for l in self._layers:
                x = l(x)
            return x

    for chunk in chunks:
        seg = _Seg(chunk)
        fn = seg.__call__  # bound: __self__ is seg (has .parameters())
        out = recompute(fn, out, **kwargs)
    return out


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Reference ``recompute_hybrid.py:250`` (PP-aware offload variant);
    offload knobs are no-ops on TPU (XLA owns residual placement)."""
    return recompute(function, *args, **kwargs)

"""Sharding (ZeRO) optimizer stages — GSPMD mechanism.

Capability analog of ``python/paddle/distributed/fleet/meta_optimizers/
dygraph_optimizer/dygraph_sharding_optimizer.py:49`` (stage 1) and
``group_sharded_stage2/3`` (SURVEY D16). The reference partitions the
parameter list rank-by-rank and hand-codes reduce-scatter + broadcast; on
TPU the same memory win comes from *sharding annotations*: optimizer
moments (stage 1), gradients (stage 2), and parameters (stage 3/FSDP) are
pinned sharded along the ``sharding`` mesh axis, and XLA emits the
reduce-scatter/all-gather pairs inside the compiled step — the
"weight-update sharding" transform that is the published GSPMD recipe for
ZeRO on TPU.

Stage semantics:
- stage 1: accumulators sharded over the sharding axis (on the first
  free divisible dim, COMPOSED with any sharding the state already
  carries — a pipeline-stacked weight keeps its pp dim, TP weights
  their mp dim). Under jit capture the sharding is applied as
  ``with_sharding_constraint`` inside the compiled step.
- stage 2: + gradients resharded before the update.
- stage 3: + parameters stored sharded; all-gather happens inside forward
  (XLA inserts it where the full weight is consumed).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .topology import HybridCommunicateGroup


def _spec_names(spec):
    names = set()
    for s in spec:
        if s is None:
            continue
        names.update(s if isinstance(s, (tuple, list)) else (s,))
    return names


def _compose_parts(shape, cur, own_mesh, fallback_mesh, axis_name):
    """Core of the compose: given an existing partial spec ``cur`` over
    ``own_mesh``, pick the first free divisible dim for ``axis_name``.
    None = leave as is."""
    cur = tuple(cur) + (None,) * (len(shape) - len(cur))
    names = _spec_names(cur)
    if axis_name in names:
        return None                       # already ZeRO-sharded
    if names:
        mesh = (own_mesh if own_mesh is not None
                and axis_name in getattr(own_mesh, "axis_names", ())
                else fallback_mesh)
        if (axis_name not in mesh.axis_names
                or not names <= set(mesh.axis_names)):
            return None                   # cannot express the compose
    else:
        mesh = fallback_mesh
        if axis_name not in mesh.axis_names:
            return None
    size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    if size <= 1:
        return None
    for d in range(len(shape)):
        if cur[d] is None and shape[d] % size == 0 and shape[d] >= size:
            new = list(cur)
            new[d] = axis_name
            return mesh, P(*new)
    return None


def _compose_target(v, fallback_mesh, axis_name):
    """(mesh, spec) pinning ``v`` Shard over ``axis_name`` COMPOSED with
    any sharding it already carries (a pipeline-stacked weight is
    Shard('pp') on dim 0 and TP-sharded elsewhere — ZeRO must take a
    remaining dim, not fight those axes). None = leave as is."""
    sh = getattr(v, "sharding", None)
    return _compose_parts(v.shape, getattr(sh, "spec", None) or (),
                          getattr(sh, "mesh", None), fallback_mesh,
                          axis_name)


def _param_spec_parts(p):
    """(spec, mesh) annotated on a parameter — readable even when its
    value is a tracer (jit capture) via the ``_dist`` annotation."""
    dist = getattr(p, "_dist", None) if p is not None else None
    if not dist:
        return (), None
    mesh, placements = dist
    try:
        from ..auto_parallel.api import (ProcessMesh, _to_partition_spec)
        jmesh = mesh.jmesh if isinstance(mesh, ProcessMesh) else mesh
        if isinstance(placements, P):
            return tuple(placements), jmesh
        spec = _to_partition_spec(mesh, placements)
        return tuple(spec), jmesh
    except Exception:
        return (), None


class DygraphShardingOptimizer:
    """Wraps an inner optimizer; shards its state over the sharding axis."""

    def __init__(self, optimizer, hcg: HybridCommunicateGroup = None,
                 stage: int = 1):
        self._inner = optimizer
        # ZeRO shards per-param state over the sharding axis via GSPMD
        # constraint propagation; the fused flat-bucket path would fold
        # the moments into one unsharded buffer and defeat the sharding
        # — pin the inner optimizer to the per-param path
        if hasattr(optimizer, "_fused_off"):
            optimizer._fused_off = True
        if hcg is None:
            from .fleet import get_hybrid_communicate_group, init
            hcg = get_hybrid_communicate_group() or init()
        self._hcg = hcg
        self._mesh = hcg.mesh
        self._axis = "sharding"
        self._n = hcg.get_sharding_parallel_world_size()
        self.stage = stage

    # reference API: the inner optimizer's interface is preserved
    @property
    def _parameter_list(self):
        return getattr(self._inner, "_parameters", [])

    def _reshard_grads(self):
        for p in self._parameter_list:
            g = p.grad
            if g is None:
                continue
            v = g._read()
            if isinstance(v, jax.core.Tracer):
                cur, own = _param_spec_parts(p)
                tgt = _compose_parts(v.shape, cur, own, self._mesh,
                                     self._axis)
                if tgt is not None:
                    mesh, spec = tgt
                    g._write(jax.lax.with_sharding_constraint(
                        v, NamedSharding(mesh, spec)))
                continue
            tgt = _compose_target(v, self._mesh, self._axis)
            if tgt is not None:
                mesh, spec = tgt
                g._write(jax.device_put(v, NamedSharding(mesh, spec)))

    def _shard_accumulators(self):
        for _pid, acc in self._state_items():
            v = acc._read()
            if isinstance(v, jax.core.Tracer) or acc.is_dist():
                continue
            tgt = _compose_target(v, self._mesh, self._axis)
            if tgt is not None:
                mesh, spec = tgt
                acc._write(jax.device_put(
                    v, NamedSharding(mesh, spec)))
                acc._dist = (mesh, spec)

    def _state_items(self):
        items = []
        for store in self._inner._accumulators.values():
            items.extend(store.items())
        items.extend(getattr(self._inner, "_master_weights", {}).items())
        return items

    def _constrain_state_in_trace(self):
        """Under jit capture the accumulators / master weights hold
        tracers: apply ZeRO as ``with_sharding_constraint`` so the
        sharding lives INSIDE the compiled step (the GSPMD
        weight-update-sharding recipe). The compose base comes from the
        owning parameter's ``_dist`` annotation (a tracer carries no
        sharding to read)."""
        by_id = {id(p): p for p in self._parameter_list}
        for pid, acc in self._state_items():
            v = acc._read()
            if not isinstance(v, jax.core.Tracer):
                continue
            cur, own = _param_spec_parts(by_id.get(pid))
            tgt = _compose_parts(v.shape, cur, own, self._mesh,
                                 self._axis)
            if tgt is not None:
                mesh, spec = tgt
                acc._write(jax.lax.with_sharding_constraint(
                    v, NamedSharding(mesh, spec)))

    def step(self):
        if self._n > 1 and self.stage >= 2:
            self._reshard_grads()
        self._inner.step()
        if self._n > 1:
            # discovery/eager values are real (device_put path); the
            # replay and AOT traces see tracers (constraint path) —
            # each helper skips the other's case
            self._constrain_state_in_trace()
            self._shard_accumulators()

    def minimize(self, loss, *a, **k):
        if self._n > 1 and self.stage >= 2:
            self._reshard_grads()
        out = self._inner.minimize(loss, *a, **k)
        if self._n > 1:
            self._shard_accumulators()
        return out

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, sd):
        return self._inner.set_state_dict(sd)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, **kwargs):
    """Reference ``python/paddle/distributed/sharding/group_sharded.py``:
    level 'os' = stage 1, 'os_g' = stage 2, 'p_g_os' = stage 3. Stage 3
    additionally pins the parameters themselves sharded (FSDP layout)."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    from .fleet import get_hybrid_communicate_group, init
    hcg = get_hybrid_communicate_group() or init()
    opt = DygraphShardingOptimizer(optimizer, hcg, stage=stage)
    if stage >= 3:
        for p in model.parameters():
            v = p._read()
            if isinstance(v, jax.core.Tracer) or p.is_dist():
                continue
            tgt = _compose_target(v, hcg.mesh, "sharding")
            if tgt is not None:
                mesh, spec = tgt
                p._write(jax.device_put(v, NamedSharding(mesh, spec)))
                p._dist = (mesh, spec)
    return model, opt, scaler

"""Sharding (ZeRO) optimizer stages — GSPMD mechanism.

Capability analog of ``python/paddle/distributed/fleet/meta_optimizers/
dygraph_optimizer/dygraph_sharding_optimizer.py:49`` (stage 1) and
``group_sharded_stage2/3`` (SURVEY D16). The reference partitions the
parameter list rank-by-rank and hand-codes reduce-scatter + broadcast; on
TPU the same memory win comes from *sharding annotations*: optimizer
moments (stage 1), gradients (stage 2), and parameters (stage 3/FSDP) are
pinned sharded along the ``sharding`` mesh axis, and XLA emits the
reduce-scatter/all-gather pairs inside the compiled step — the
"weight-update sharding" transform that is the published GSPMD recipe for
ZeRO on TPU.

Stage semantics:
- stage 1: accumulators sharded (dim-0) over the sharding axis.
- stage 2: + gradients resharded before the update.
- stage 3: + parameters stored sharded; all-gather happens inside forward
  (XLA inserts it where the full weight is consumed).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .topology import HybridCommunicateGroup


def _shard0_spec(shape, axis_name, axis_size):
    """Shard along dim 0 when divisible; replicate otherwise (the reference
    likewise keeps non-divisible small params unsharded)."""
    if len(shape) > 0 and shape[0] % axis_size == 0 and shape[0] >= axis_size:
        return P(axis_name)
    return P()


class DygraphShardingOptimizer:
    """Wraps an inner optimizer; shards its state over the sharding axis."""

    def __init__(self, optimizer, hcg: HybridCommunicateGroup = None,
                 stage: int = 1):
        self._inner = optimizer
        if hcg is None:
            from .fleet import get_hybrid_communicate_group, init
            hcg = get_hybrid_communicate_group() or init()
        self._hcg = hcg
        self._mesh = hcg.mesh
        self._axis = "sharding"
        self._n = hcg.get_sharding_parallel_world_size()
        self.stage = stage

    # reference API: the inner optimizer's interface is preserved
    @property
    def _parameter_list(self):
        return getattr(self._inner, "_parameters", [])

    def _reshard_grads(self):
        for p in self._parameter_list:
            g = p.grad
            if g is None:
                continue
            v = g._read()
            if isinstance(v, jax.core.Tracer):
                continue
            spec = _shard0_spec(v.shape, self._axis, self._n)
            g._write(jax.device_put(v, NamedSharding(self._mesh, spec)))

    def _shard_accumulators(self):
        for store in self._inner._accumulators.values():
            for acc in store.values():
                v = acc._read()
                if isinstance(v, jax.core.Tracer) or acc.is_dist():
                    continue
                spec = _shard0_spec(v.shape, self._axis, self._n)
                if spec != P():
                    acc._write(jax.device_put(
                        v, NamedSharding(self._mesh, spec)))
                    acc._dist = (self._mesh, spec)

    def step(self):
        if self._n > 1 and self.stage >= 2:
            self._reshard_grads()
        self._inner.step()
        if self._n > 1:
            self._shard_accumulators()

    def minimize(self, loss, *a, **k):
        if self._n > 1 and self.stage >= 2:
            self._reshard_grads()
        out = self._inner.minimize(loss, *a, **k)
        if self._n > 1:
            self._shard_accumulators()
        return out

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, sd):
        return self._inner.set_state_dict(sd)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, **kwargs):
    """Reference ``python/paddle/distributed/sharding/group_sharded.py``:
    level 'os' = stage 1, 'os_g' = stage 2, 'p_g_os' = stage 3. Stage 3
    additionally pins the parameters themselves sharded (FSDP layout)."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    from .fleet import get_hybrid_communicate_group, init
    hcg = get_hybrid_communicate_group() or init()
    opt = DygraphShardingOptimizer(optimizer, hcg, stage=stage)
    if stage >= 3:
        mesh, n = hcg.mesh, hcg.get_sharding_parallel_world_size()
        for p in model.parameters():
            v = p._read()
            if isinstance(v, jax.core.Tracer) or p.is_dist():
                continue
            spec = _shard0_spec(v.shape, "sharding", n)
            if spec != P():
                p._write(jax.device_put(v, NamedSharding(mesh, spec)))
                p._dist = (mesh, spec)
    return model, opt, scaler

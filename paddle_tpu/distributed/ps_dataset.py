"""PS-mode datasets (reference ``python/paddle/distributed/fleet/dataset/``
InMemoryDataset / QueueDataset + the C++ DataFeed of SURVEY C26).

The reference streams slot-format text files through a C++ pipeline into
PS trainers. Here the same surface wraps the framework's IO stack: a
``parse_fn`` (the data_generator analog) maps each text line to a sample;
``InMemoryDataset`` materializes + shuffles, ``QueueDataset`` streams
through the thread-backed reader.
"""
from __future__ import annotations

import random

__all__ = ["InMemoryDataset", "QueueDataset", "multi_slot_parser"]


class _DatasetBase:
    def __init__(self):
        self._filelist = []
        self._batch_size = 1
        self._thread_num = 1
        self._parse_fn = lambda line: line

    def init(self, batch_size=1, thread_num=1, parse_fn=None, use_var=None,
             pipe_command=None, **kwargs):
        """Reference ``dataset.init``: configure batching/threads and the
        line parser (``parse_fn(line) -> sample``; the data_generator).
        With ``use_var`` (slot declarations) and no explicit parse_fn,
        lines parse as the reference's MultiSlotDataFeed format
        (``multi_slot_parser``)."""
        self._batch_size = batch_size
        self._thread_num = thread_num
        if parse_fn is None and use_var:
            parse_fn = multi_slot_parser(use_var)
        self._parse_fn = parse_fn or (lambda line: line)
        return self

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def _lines(self):
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    yield line.rstrip("\n")

    def _batches(self, samples):
        batch = []
        for s in samples:
            batch.append(s)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            yield batch


class InMemoryDataset(_DatasetBase):
    """Reference InMemoryDataset: load, shuffle in memory, iterate."""

    def __init__(self):
        super().__init__()
        self._data = None

    def load_into_memory(self):
        self._data = [self._parse_fn(ln) for ln in self._lines()]

    def local_shuffle(self, seed=None):
        if self._data is None:
            raise RuntimeError("call load_into_memory first")
        random.Random(seed).shuffle(self._data)

    def global_shuffle(self, fleet=None, thread_num=None, seed=None):
        # single-controller: every worker sees the same store-backed list;
        # a seeded shuffle is globally consistent
        self.local_shuffle(seed if seed is not None else 0)

    def get_memory_data_size(self, fleet=None):
        return len(self._data or [])

    def release_memory(self):
        self._data = None

    def __iter__(self):
        if self._data is None:
            raise RuntimeError("call load_into_memory first")
        return self._batches(iter(self._data))


class QueueDataset(_DatasetBase):
    """Reference QueueDataset: stream files through a bounded queue
    (thread-backed, like paddle_tpu.io's loader) without materializing."""

    def __iter__(self):
        from .. import reader as reader_mod

        def creator():
            for ln in self._lines():
                yield self._parse_fn(ln)

        buffered = reader_mod.buffered(creator, max(self._thread_num, 1) * 64)
        return self._batches(buffered())


def multi_slot_parser(slots):
    """Reference ``MultiSlotDataFeed`` line format
    (``paddle/fluid/framework/data_feed.cc`` MultiSlotDataFeed): each
    line holds, per slot in declared order, ``<count> v_1 ... v_count``.
    ``slots`` is a list of (name, dtype) pairs (or dicts with
    name/dtype); returns ``parse_fn(line) -> {name: np.ndarray}``."""
    import numpy as np

    spec = []
    for s in slots:
        if isinstance(s, dict):
            spec.append((s["name"], s.get("dtype", "int64")))
        elif isinstance(s, (tuple, list)):
            spec.append((s[0], s[1] if len(s) > 1 else "int64"))
        else:  # bare name -> sparse id slot
            spec.append((str(s), "int64"))

    def parse(line):
        toks = line.split()
        out = {}
        i = 0
        for name, dtype in spec:
            if i >= len(toks):
                raise ValueError(
                    f"multi_slot line ended before slot {name!r}: "
                    f"{line!r}")
            n = int(toks[i])
            i += 1
            vals = toks[i:i + n]
            if len(vals) != n:
                raise ValueError(
                    f"slot {name!r} declares {n} values, line has "
                    f"{len(vals)}: {line!r}")
            i += n
            out[name] = np.asarray(vals).astype(dtype)
        if i != len(toks):
            raise ValueError(
                f"trailing tokens after last slot: {line!r}")
        return out

    return parse

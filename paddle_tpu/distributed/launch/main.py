"""Launcher implementation (see package docstring).

Elastic mode (``--elastic 1``) adds the reference ElasticManager's
capabilities (``fleet/elastic/manager.py:126``): TCPStore-based heartbeat
membership, scale-up/down with rank re-map, and automatic worker respawn
on a membership change. ``--progress_timeout`` adds the hang watchdog
(the TPU analog of ``comm_task_manager.h:37``): workers heartbeat a
progress file every compiled step; a stalled worker (e.g. a desynced
collective hanging all ranks) is killed and restarted.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="multi-host launcher (one controller per host)")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")),
                   help="number of hosts")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER", ""),
                   help="coordinator host:port (required when nnodes > 1)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="controllers per host (1 on TPU: PJRT owns chips)")
    p.add_argument("--max_restart_times", type=int, default=0,
                   help="elastic: restart a failed child up to N times")
    p.add_argument("--elastic", type=int, default=0,
                   help="1 = heartbeat membership + re-rendezvous on "
                        "scale-up/down (requires --master for the store). "
                        "NOTE: the membership store lives in node-rank-0's "
                        "launcher — losing that node ends rendezvous for "
                        "the job (the reference's external etcd survives "
                        "its clients); host the store externally or use a "
                        "standby master to remove the SPOF")
    p.add_argument("--heartbeat_interval", type=float, default=1.0)
    p.add_argument("--heartbeat_timeout", type=float, default=5.0)
    p.add_argument("--progress_timeout", type=float, default=0.0,
                   help="seconds without worker progress before the "
                        "watchdog kills/restarts it (0 = off)")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--run_mode", default="collective")  # parity: accepted
    p.add_argument("--devices", default=None)           # parity: accepted
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _child_env(args, local_rank, nnodes=None, node_rank=None):
    env = dict(os.environ)
    nnodes = args.nnodes if nnodes is None else nnodes
    node_rank = args.node_rank if node_rank is None else node_rank
    world = nnodes * args.nproc_per_node
    rank = node_rank * args.nproc_per_node + local_rank
    env.update({
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_NNODES": str(nnodes),
        "PADDLE_NODE_RANK": str(node_rank),
    })
    if args.master:
        env["PADDLE_MASTER"] = args.master
        # the jax coordination-service contract consumed by
        # init_parallel_env on multi-host pods
        env.setdefault("JAX_COORDINATOR_ADDRESS", args.master)
        env.setdefault("JAX_NUM_PROCESSES", str(world))
        env.setdefault("JAX_PROCESS_ID", str(rank))
    return env


class _Worker:
    """One child process + its restart budget and progress file."""

    def __init__(self, args, local_rank, nnodes, node_rank):
        self.args = args
        self.lr = local_rank
        self.restarts = 0
        self.stdout = None
        if args.log_dir:
            self.stdout = open(os.path.join(
                args.log_dir, f"worker.{node_rank}.{local_rank}.log"), "ab")
        self.progress = None
        if args.progress_timeout > 0:
            base = args.log_dir or "/tmp"
            self.progress = os.path.join(
                base, f".progress.{os.getpid()}.{local_rank}")
        self.proc = None
        self.spawn(nnodes, node_rank)

    def spawn(self, nnodes, node_rank):
        env = _child_env(self.args, self.lr, nnodes, node_rank)
        if self.progress:
            env["PADDLE_PROGRESS_FILE"] = self.progress
            with open(self.progress, "w"):  # clock starts at spawn
                pass
        cmd = [sys.executable, self.args.script] + self.args.script_args
        self.proc = subprocess.Popen(cmd, env=env, stdout=self.stdout,
                                     stderr=self.stdout)

    def stalled(self, timeout):
        if not self.progress or self.proc.poll() is not None:
            return False
        try:
            return time.time() - os.path.getmtime(self.progress) > timeout
        except OSError:
            return False

    def terminate(self, grace=10.0):
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            deadline = time.time() + grace
            while self.proc.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            if self.proc.poll() is None:
                # last resort (note: can wedge a held TPU claim; the
                # lease times out server-side)
                self.proc.kill()
                self.proc.wait()  # reap: no zombie across generations

    def close(self):
        self.terminate()
        if self.stdout is not None:
            try:
                self.stdout.close()
            except OSError:
                pass


def _watch(args, workers, nnodes, node_rank, em=None, gen=0):
    """Supervise one generation. Returns ('done', code) or
    ('regen', new_gen, members)."""
    while True:
        alive = False
        for w in workers:
            code = w.proc.poll()
            if code is None:
                if args.progress_timeout > 0 and \
                        w.stalled(args.progress_timeout):
                    print(f"[launch] worker {w.lr} made no progress for "
                          f"{args.progress_timeout}s: killing "
                          f"(hang watchdog)", file=sys.stderr)
                    w.terminate()
                    code = w.proc.poll() or 1
                else:
                    alive = True
                    continue
            if code != 0:
                if w.restarts < args.max_restart_times:
                    w.restarts += 1
                    print(f"[launch] worker {w.lr} exited {code}; restart "
                          f"{w.restarts}/{args.max_restart_times}",
                          file=sys.stderr)
                    w.spawn(nnodes, node_rank)
                    alive = True
                else:
                    for other in workers:
                        other.terminate()
                    return ("done", code)
        if not alive:
            return ("done", 0)
        if em is not None:
            new_gen, members = em.wait_generation(gen, timeout=0.0)
            if new_gen > gen:
                print(f"[launch] membership changed (gen {gen} -> "
                      f"{new_gen}, {len(members)} nodes): re-rendezvous",
                      file=sys.stderr)
                for w in workers:
                    w.terminate()
                return ("regen", new_gen, members)
        time.sleep(0.2)


def _launch_static(args):
    workers = [_Worker(args, lr, args.nnodes, args.node_rank)
               for lr in range(args.nproc_per_node)]
    try:
        res = _watch(args, workers, args.nnodes, args.node_rank)
        return res[1]
    except KeyboardInterrupt:
        for w in workers:
            w.terminate()
        return 130


def _launch_elastic(args):
    from ..elastic import ElasticManager
    from ..store import TCPStore

    host, port = args.master.rsplit(":", 1)
    is_master = args.node_rank == 0
    store = TCPStore(host, int(port), is_master=is_master)
    node_id = os.environ.get(
        "PADDLE_ELASTIC_NODE_ID",
        f"{socket.gethostname()}-{args.node_rank}-{os.getpid()}")
    em = ElasticManager(store, node_id, is_master,
                        heartbeat_interval=args.heartbeat_interval,
                        heartbeat_timeout=args.heartbeat_timeout,
                        min_nodes=args.nnodes)
    gen, members = em.start()
    workers = []
    try:
        while True:
            nnodes, node_rank = len(members), em.rank_of(members)
            print(f"[launch] gen {gen}: {nnodes} nodes, this node rank "
                  f"{node_rank}", file=sys.stderr)
            workers = [_Worker(args, lr, nnodes, node_rank)
                       for lr in range(args.nproc_per_node)]
            res = _watch(args, workers, nnodes, node_rank, em, gen)
            if res[0] == "done":
                return res[1]
            for w in workers:  # old generation: reap + release log fds
                w.close()
            workers = []
            gen, members = res[1], res[2]
            while node_id not in members:  # dropped: wait to be re-seen
                gen, members = em.wait_generation(gen, timeout=None)
    except KeyboardInterrupt:
        return 130
    finally:
        for w in workers:
            w.close()
        em.stop()
        store.close()


def launch(argv=None):
    args = _parse(argv if argv is not None else sys.argv[1:])
    if args.nnodes > 1 and not args.master:
        raise SystemExit("--master host:port is required for nnodes > 1")
    if args.elastic and not args.master:
        raise SystemExit("--elastic requires --master host:port "
                         "(the membership store)")
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    if args.elastic:
        return _launch_elastic(args)
    return _launch_static(args)


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()

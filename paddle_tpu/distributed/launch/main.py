"""Launcher implementation (see package docstring)."""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="multi-host launcher (one controller per host)")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")),
                   help="number of hosts")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER", ""),
                   help="coordinator host:port (required when nnodes > 1)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="controllers per host (1 on TPU: PJRT owns chips)")
    p.add_argument("--max_restart_times", type=int, default=0,
                   help="elastic: restart a failed child up to N times")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--run_mode", default="collective")  # parity: accepted
    p.add_argument("--devices", default=None)           # parity: accepted
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _child_env(args, local_rank):
    env = dict(os.environ)
    world = args.nnodes * args.nproc_per_node
    rank = args.node_rank * args.nproc_per_node + local_rank
    env.update({
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_NNODES": str(args.nnodes),
        "PADDLE_NODE_RANK": str(args.node_rank),
    })
    if args.master:
        env["PADDLE_MASTER"] = args.master
        # the jax coordination-service contract consumed by
        # init_parallel_env on multi-host pods
        env.setdefault("JAX_COORDINATOR_ADDRESS", args.master)
        env.setdefault("JAX_NUM_PROCESSES", str(world))
        env.setdefault("JAX_PROCESS_ID", str(rank))
    return env


def launch(argv=None):
    args = _parse(argv if argv is not None else sys.argv[1:])
    if args.nnodes > 1 and not args.master:
        raise SystemExit("--master host:port is required for nnodes > 1")
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    procs = []
    for lr in range(args.nproc_per_node):
        cmd = [sys.executable, args.script] + args.script_args
        stdout = None
        if args.log_dir:
            stdout = open(os.path.join(
                args.log_dir, f"worker.{args.node_rank}.{lr}.log"), "ab")
        procs.append([subprocess.Popen(cmd, env=_child_env(args, lr),
                                       stdout=stdout, stderr=stdout),
                      0, stdout, lr])

    def terminate_all():
        for rec in procs:
            if rec[0].poll() is None:
                rec[0].send_signal(signal.SIGTERM)

    exit_code = 0
    try:
        while True:
            alive = False
            for rec in procs:
                proc, restarts, stdout, lr = rec
                code = proc.poll()
                if code is None:
                    alive = True
                elif code != 0:
                    if restarts < args.max_restart_times:
                        # elastic restart path (reference fleet/elastic
                        # manager watchdog)
                        rec[1] += 1
                        print(f"[launch] worker {lr} exited {code}; "
                              f"restart {rec[1]}/{args.max_restart_times}",
                              file=sys.stderr)
                        rec[0] = subprocess.Popen(
                            [sys.executable, args.script]
                            + args.script_args,
                            env=_child_env(args, lr), stdout=stdout,
                            stderr=stdout)
                        alive = True
                    else:
                        exit_code = code
                        terminate_all()
                        return exit_code
            if not alive:
                return exit_code
            time.sleep(0.2)
    except KeyboardInterrupt:
        terminate_all()
        return 130


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()

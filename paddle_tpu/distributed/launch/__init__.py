"""``paddle.distributed.launch`` parity — the multi-host launcher.

Capability analog of SURVEY D19-D20 (``python/paddle/distributed/launch/``
main.py/controllers, fleetrun) and the elastic controller
(``distributed/fleet/elastic/``). TPU-native topology: ONE controller
process per host (PJRT owns the local chips), federated by JAX's
coordination service — ``jax.distributed.initialize(coordinator, n, id)``
replaces the reference's TCPStore rendezvous + per-GPU worker spawn.

``python -m paddle_tpu.distributed.launch --nnodes N --node_rank I
--master host:port train.py`` sets the env contract
(``PADDLE_TRAINERS_NUM``/``PADDLE_TRAINER_ID``/``PADDLE_MASTER``), brings
the child up, and — the failure-detection half — watches it, restarting
up to ``--max_restart_times`` on nonzero exit (the elastic manager's
restart path; scale-out elasticity is a coordinator-service capability,
not a launcher one, on TPU pods).
"""
from .main import launch, main  # noqa: F401

__all__ = ["launch", "main"]

"""Custom-device plugin registry (SURVEY C5).

Reference: ``paddle/phi/backends/custom/custom_device.cc`` +
``paddle/phi/backends/device_manager.cc`` load vendor ``.so`` plugins
implementing the CustomDevice ABI and surface them through
``python/paddle/device/__init__.py`` (``is_compiled_with_custom_device``,
``core.CustomPlace``, ``set_device("npu:0")``).

TPU-native shape: the plugin ABI of the jax/XLA world is **PJRT** — a
vendor chip ships a PJRT plugin shared object, and jax can load it at
runtime. This registry is the paddle-flavored front door:

* ``register_custom_device(type, library_path=...)`` hands the plugin to
  jax's PJRT plugin loader (the analog of DeviceManager::LoadCustomRuntimeLib);
* ``register_custom_device(type, alias_of=...)`` names an
  already-initialized jax platform as a paddle custom-device type (the
  common case for backends that self-register via the ``jax_plugins``
  entry-point namespace before we are imported);
* ``CustomPlace("mychip", 0)``, ``paddle.device.set_device("mychip:0")``,
  ``is_compiled_with_custom_device("mychip")`` then work against the
  registered type exactly as the reference's surface does for ``npu``.
"""
from __future__ import annotations

from typing import Optional

import jax

from ..core.dtype import Place

# device_type -> jax platform name it resolves to
_registry: dict[str, str] = {}


def register_custom_device(device_type: str, *,
                           library_path: Optional[str] = None,
                           alias_of: Optional[str] = None,
                           options: Optional[dict] = None) -> None:
    """Register ``device_type`` as a paddle custom device.

    ``library_path``: path to a PJRT plugin shared object; it is handed
    to jax's plugin loader and the platform it announces is bound to
    ``device_type``. ``alias_of``: bind ``device_type`` to an existing
    jax platform instead (no loading). Exactly one must be given.
    """
    if (library_path is None) == (alias_of is None):
        raise ValueError(
            "register_custom_device: pass exactly one of library_path "
            "(load a PJRT plugin) or alias_of (bind an existing platform)")
    if alias_of is not None:
        plats = {d.platform for d in jax.devices()}
        if alias_of not in plats:
            raise ValueError(
                f"register_custom_device: platform {alias_of!r} is not "
                f"initialized (have {sorted(plats)})")
        _registry[device_type.lower()] = alias_of
        return
    # PJRT plugin load path. jax's loader registers the plugin under the
    # name we give it. jax caches its backend set on first use; when the
    # plugin does not surface, the ONLY recovery is dropping that cache —
    # which invalidates the device buffers of every already-created
    # array. Rather than silently breaking live tensors, refuse in that
    # case unless the caller opts in: register plugins BEFORE first
    # device/tensor use (import time) and none of this applies.
    from jax._src import xla_bridge as xb
    t = device_type.lower()
    # reinitialize_backends is OUR control flag, not a plugin create-
    # option: strip it before the options dict reaches the PJRT plugin
    plugin_options = {k: v for k, v in (options or {}).items()
                      if k != "reinitialize_backends"} or None
    xb.register_plugin(t, library_path=library_path, options=plugin_options)
    if not any(d.platform == t for d in jax.devices()):
        if (options or {}).get("reinitialize_backends"):
            jax.clear_backends()
        if not any(d.platform == t for d in jax.devices()):
            raise RuntimeError(
                f"register_custom_device: PJRT plugin {library_path!r} "
                f"was registered but platform {t!r} did not initialize. "
                f"Backends were already cached: register custom devices "
                f"BEFORE first device/tensor use, or pass "
                f"options={{'reinitialize_backends': True}} to force a "
                f"backend reset (this INVALIDATES every live tensor)")
    _registry[t] = t


def resolve_type(device_type: str) -> Optional[str]:
    """The jax platform a (possibly custom) device type maps to, or None
    when the type is neither registered nor a live platform."""
    t = device_type.lower()
    if t in _registry:
        return _registry[t]
    if any(d.platform == t for d in jax.devices()):
        return t
    return None


def registered_types() -> list[str]:
    return sorted(_registry)


def is_compiled_with_custom_device(device_type: str) -> bool:
    """Reference ``device/__init__.py:62`` — whether ``device_type`` is
    usable as a custom device in this process."""
    return resolve_type(device_type) is not None


class CustomPlace(Place):
    """Reference ``core.CustomPlace(type, id)`` over a registered type."""

    def __init__(self, device_type: str, device_id: int = 0):
        plat = resolve_type(device_type)
        if plat is None:
            raise ValueError(
                f"CustomPlace: unknown custom device type "
                f"{device_type!r}; register_custom_device first")
        devs = [d for d in jax.devices() if d.platform == plat]
        if not devs:
            raise ValueError(f"CustomPlace: no devices for {device_type!r}")
        if not 0 <= device_id < len(devs):
            raise ValueError(
                f"CustomPlace: device_id {device_id} out of range for "
                f"{device_type!r} ({len(devs)} device(s))")
        super().__init__(devs[device_id])
        self._custom_type = device_type
        self._custom_id = device_id

    def get_device_type(self) -> str:
        return self._custom_type

    def get_device_id(self) -> int:
        return self._custom_id

    def __repr__(self):
        return f"CustomPlace({self._custom_type}:{self._custom_id})"


__all__ = ["register_custom_device", "is_compiled_with_custom_device",
           "CustomPlace", "registered_types", "resolve_type"]

"""``paddle.device`` parity — device control, synchronization, memory stats.

Capability analog of SURVEY C4 (DeviceContext pool -> PJRT owns
streams/contexts; this is the user-facing surface), C7 (allocator stats ->
PJRT ``memory_stats``), C30 (DeviceEvent -> PJRT futures +
``block_until_ready``). Reference ``python/paddle/device/__init__.py``
(set_device/get_device/synchronize), ``device/cuda/__init__.py``
(memory stats, Event/Stream).

TPU-native notes: XLA/PJRT dispatches asynchronously on its own streams —
``synchronize`` drains by blocking on a sentinel transfer; Stream objects
are accepted for API compatibility but scheduling is PJRT's (the analog of
the reference's stream-safe allocator is buffer donation, already used by
the jit executor).
"""
from __future__ import annotations

import time
from typing import Optional

import jax
import numpy as np

_current = None


def _backend_devices():
    return jax.devices()


def get_all_device_type():
    kinds = []
    for d in jax.devices():
        if d.platform not in kinds:
            kinds.append(d.platform)
    return kinds


def get_all_custom_device_type():
    from .custom import registered_types
    native = [p for p in get_all_device_type() if p not in ("cpu",)]
    return native + [t for t in registered_types() if t not in native]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [d for d in get_available_device() if not d.startswith("cpu")]


def device_count(device_type: Optional[str] = None) -> int:
    if device_type is None:
        return len(jax.devices())
    from .custom import resolve_type
    plat = resolve_type(device_type) or device_type
    return len([d for d in jax.devices() if d.platform == plat])


def set_device(device: str):
    """Reference ``device/__init__.py set_device`` — pins the default
    placement for new tensors. Accepts "cpu", "tpu", "tpu:0", ...; the
    reference's "gpu:N" spelling maps to the accelerator backend."""
    global _current
    from .custom import resolve_type
    plat, _, idx = device.partition(":")
    if plat in ("gpu", "cuda", "xpu"):  # reference accelerator spellings
        plat = _accel_platform()
    resolved = resolve_type(plat)
    if resolved is None and plat not in ("cpu", "tpu"):
        raise ValueError(
            f"set_device: unknown device type {plat!r} (live platforms: "
            f"{get_all_device_type()}; custom types register via "
            f"device.register_custom_device)")
    plat = resolved or plat
    devs = [d for d in jax.devices() if d.platform == plat] or jax.devices()
    dev = devs[int(idx)] if idx else devs[0]
    jax.config.update("jax_default_device", dev)
    _current = f"{dev.platform}:{dev.id}"
    return _current


def _accel_platform():
    for d in jax.devices():
        if d.platform != "cpu":
            return d.platform
    return "cpu"


def get_device() -> str:
    if _current is not None:
        return _current
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def _resolve(device=None):
    from .custom import resolve_type
    if device is None:
        plat, _, idx = get_device().partition(":")
    else:
        plat, _, idx = str(device).partition(":")
    plat = resolve_type(plat) or plat
    devs = [d for d in jax.devices() if d.platform == plat] or jax.devices()
    return devs[int(idx)] if idx else devs[0]


def synchronize(device=None):
    """Drain outstanding device work: block on a sentinel transfer queued
    behind everything PJRT has in flight."""
    dev = _resolve(device)
    jax.block_until_ready(jax.device_put(np.zeros(()), dev))


# --- memory stats (C7; reference device/cuda memory APIs) ------------------

def _mem_stats(device=None) -> dict:
    dev = _resolve(device)
    stats = getattr(dev, "memory_stats", lambda: None)()
    return stats or {}


def memory_allocated(device=None) -> int:
    """Reference ``cuda.memory_allocated`` analog (HBM bytes in use)."""
    return int(_mem_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    return int(_mem_stats(device).get("peak_bytes_in_use",
                                      memory_allocated(device)))


def memory_reserved(device=None) -> int:
    s = _mem_stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    return max_memory_allocated(device)


def empty_cache():
    """PJRT owns the allocator; live buffers are freed by GC. Provided for
    API parity (reference ``cuda.empty_cache``)."""
    import gc
    gc.collect()


# --- events/streams (C30) --------------------------------------------------

class Event:
    """Reference ``device.Event``. PJRT has no user event objects; record
    drains the queue and timestamps — correct elapsed_time semantics for
    the common bench pattern, at the cost of a sync per record."""

    def __init__(self, device=None, enable_timing=True, blocking=False):
        self.device = device
        self._ts: Optional[float] = None

    def record(self, stream=None):
        synchronize(self.device)
        self._ts = time.perf_counter()

    def query(self) -> bool:
        return True

    def synchronize(self):
        synchronize(self.device)

    def elapsed_time(self, end_event: "Event") -> float:
        if self._ts is None or end_event._ts is None:
            raise RuntimeError("both events must be recorded")
        return (end_event._ts - self._ts) * 1000.0


class Stream:
    """API-parity shim: XLA/PJRT schedules its own streams; work items
    submitted 'to' this stream run on the default queue."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize(self.device)

    def query(self) -> bool:
        return True

    def record_event(self, event: Optional[Event] = None) -> Event:
        event = event or Event(self.device)
        event.record(self)
        return event

    def wait_event(self, event: Event):
        event.synchronize()

    def wait_stream(self, stream: "Stream"):
        stream.synchronize()


def current_stream(device=None) -> Stream:
    return Stream(device)


def set_stream(stream: Stream):
    return stream


class cuda:  # namespace parity: paddle.device.cuda.*
    Event = Event
    Stream = Stream
    synchronize = staticmethod(synchronize)
    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    max_memory_reserved = staticmethod(max_memory_reserved)
    empty_cache = staticmethod(empty_cache)

    @staticmethod
    def device_count():
        return device_count(_accel_platform())

    @staticmethod
    def current_stream(device=None):
        return Stream(device)


__all__ = [
    "set_device", "get_device", "get_all_device_type",
    "get_all_custom_device_type", "get_available_device",
    "get_available_custom_device", "device_count", "synchronize",
    "register_custom_device", "is_compiled_with_custom_device",
    "CustomPlace",
    "memory_allocated", "max_memory_allocated", "memory_reserved",
    "max_memory_reserved", "empty_cache", "Event", "Stream",
    "current_stream", "set_stream", "cuda",
]

from .custom import (CustomPlace, is_compiled_with_custom_device,  # noqa: E402
                     register_custom_device)

"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
PaddlePaddle (reference surveyed in SURVEY.md), built on jax/XLA/pallas/pjit.

Top-level namespace mirrors ``import paddle``: tensor factories and ops live
here, subpackages ``nn``, ``optimizer``, ``amp``, ``io``, ``jit``,
``distributed``, ``static`` mirror paddle's.
"""
from __future__ import annotations

from .core import state as _state
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.dtype import (  # noqa: F401
    Place, TPUPlace, CPUPlace, set_default_dtype, get_default_dtype,
    float64, float32, float16, bfloat16, int64, int32, int16, int8, uint8,
    bool_, complex64, complex128,
)
from .core.autograd import no_grad, enable_grad, set_grad_enabled, grad  # noqa: F401
from .core import autograd as _autograd_mod
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation

# framework-level helpers (paddle.* parity)
from .core.state import seed, get_flags, set_flags  # noqa: F401
from .core.lazy import LazyGuard  # noqa: F401

from . import ops  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import autograd  # noqa: F401
from . import distributed  # noqa: F401
from . import incubate  # noqa: F401
from . import static  # noqa: F401
from . import metric  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import distribution  # noqa: F401
from . import observability  # noqa: F401
from . import profiler  # noqa: F401
from . import device  # noqa: F401
from .device import set_device, get_device  # noqa: F401
from .device.custom import CustomPlace  # noqa: F401
from . import quantization  # noqa: F401
from . import text  # noqa: F401
from . import audio  # noqa: F401
from . import sparse  # noqa: F401
from .core import errors  # noqa: F401
from . import inference  # noqa: F401
from . import utils  # noqa: F401
from . import regularizer  # noqa: F401
from . import version  # noqa: F401
from . import vision  # noqa: F401
from . import hapi  # noqa: F401
from . import analysis  # noqa: F401
from . import resilience  # noqa: F401
from .hapi import Model  # noqa: F401
from .hapi import callbacks  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401
from . import framework  # noqa: F401
from .framework import save, load  # noqa: F401
from .jit import to_static  # noqa: F401
from . import geometric  # noqa: F401
from . import sysconfig  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import onnx  # noqa: F401
from . import cost_model  # noqa: F401
from .hapi import hub  # noqa: F401
from . import tensor  # noqa: F401  (compat: paddle.tensor op namespace)
from . import base  # noqa: F401

import numpy as _np


def is_grad_enabled():
    return _state.is_grad_enabled()


def in_dynamic_mode():
    return True


def device_count():
    import jax
    return len(jax.devices())


def get_device():
    from .device import get_device as _gd
    return _gd()


def set_device(device):
    # route through device.set_device: it resolves registered custom
    # device types and raises on unknown ones (a bare Place(str) would
    # silently map them to cpu); reference returns the Place — a
    # CustomPlace (keeping the registered type name) for custom types
    from .device import set_device as _sd
    from .device.custom import CustomPlace, registered_types
    resolved = _sd(device)
    dtype_name = str(device).split(":", 1)[0].lower()
    if dtype_name in registered_types():
        idx = int(str(device).split(":", 1)[1]) if ":" in str(device) else 0
        return CustomPlace(dtype_name, idx)
    return Place(resolved)


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_tpu():
    import jax
    return jax.devices()[0].platform in ("tpu", "axon")


def iinfo(dtype):
    """Reference ``paddle.iinfo``."""
    from .core.dtype import convert_dtype
    return _np.iinfo(_np.dtype(convert_dtype(dtype)))


def finfo(dtype):
    """Reference ``paddle.finfo`` (works for bfloat16 via ml_dtypes)."""
    import ml_dtypes
    from .core.dtype import convert_dtype
    d = convert_dtype(dtype)
    try:
        return _np.finfo(_np.dtype(d))
    except Exception:
        return ml_dtypes.finfo(d)


def batch(reader, batch_size, drop_last=False):
    """Reference ``paddle.batch`` (legacy reader combinator)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def summary(layer, input_size=None, dtypes=None):
    n_params = sum(p.size for p in layer.parameters())
    trainable = sum(p.size for p in layer.parameters() if not p.stop_gradient)
    print(f"Total params: {n_params}\nTrainable params: {trainable}")
    return {"total_params": n_params, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Reference ``paddle.flops`` (``hapi/dynamic_flops.py``) — but exact,
    not per-layer-formula: the forward is lowered and XLA's cost analysis
    reports the compiled program's FLOPs (fusion-aware, the number the MXU
    will actually execute)."""
    import jax

    import numpy as _np

    x = to_tensor(_np.zeros(input_size, _np.float32))

    def fwd(v):
        from .core import tensor as _tm
        old = _tm.set_tracker(None)
        try:
            with no_grad():
                out = net(Tensor(v))
        finally:
            _tm.set_tracker(old)
        return out._data if isinstance(out, Tensor) else out

    compiled = jax.jit(fwd).lower(x._read()).compile()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, list):  # some backends return [dict]
        cost = cost[0] if cost else {}
    total = int(cost.get("flops", 0))
    if print_detail:
        print(f"FLOPs (XLA cost analysis): {total:,}")
    return total


__version__ = "0.1.0"

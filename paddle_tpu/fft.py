"""``paddle.fft`` parity — discrete Fourier transforms.

Capability analog of ``python/paddle/fft.py`` (reference
``fft_c2c/fft_r2c/fft_c2r`` kernels, ``paddle/phi/kernels/funcs/fft.h``;
SURVEY C11 fft family). TPU-native: every transform lowers to the XLA FFT
HLO via ``jnp.fft`` behind the dispatch funnel, so transforms join the
autograd tape and fuse under jit like any other primitive.

``norm`` semantics match the reference: "backward" (default), "ortho",
"forward".
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import primitive

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            f"norm must be one of {_NORMS}, got {norm!r}")
    return norm


def _mk1(name, jfn):
    @primitive(name)
    def op(x, n=None, axis=-1, norm="backward"):
        return jfn(x, n=n, axis=axis, norm=_check_norm(norm))
    return op


def _mk2(name, jfn):
    @primitive(name)
    def op(x, s=None, axes=(-2, -1), norm="backward"):
        return jfn(x, s=s, axes=axes, norm=_check_norm(norm))
    return op


def _mkn(name, jfn):
    @primitive(name)
    def op(x, s=None, axes=None, norm="backward"):
        return jfn(x, s=s, axes=axes, norm=_check_norm(norm))
    return op


fft = _mk1("fft", jnp.fft.fft)
ifft = _mk1("ifft", jnp.fft.ifft)
rfft = _mk1("rfft", jnp.fft.rfft)
irfft = _mk1("irfft", jnp.fft.irfft)
hfft = _mk1("hfft", jnp.fft.hfft)
ihfft = _mk1("ihfft", jnp.fft.ihfft)

fft2 = _mk2("fft2", jnp.fft.fft2)
ifft2 = _mk2("ifft2", jnp.fft.ifft2)
rfft2 = _mk2("rfft2", jnp.fft.rfft2)
irfft2 = _mk2("irfft2", jnp.fft.irfft2)

fftn = _mkn("fftn", jnp.fft.fftn)
ifftn = _mkn("ifftn", jnp.fft.ifftn)
rfftn = _mkn("rfftn", jnp.fft.rfftn)
irfftn = _mkn("irfftn", jnp.fft.irfftn)


@primitive("hfft2")
def hfft2(x, s=None, axes=(-2, -1), norm="backward"):
    # reference hfftn decomposition: c2c over the leading axes, then the
    # hermitian c2r transform over the last axis
    _check_norm(norm)
    y = jnp.fft.fft(x, n=(s[0] if s else None), axis=axes[0], norm=norm)
    return jnp.fft.hfft(y, n=(s[1] if s else None), axis=axes[1],
                        norm=norm)


@primitive("ihfft2")
def ihfft2(x, s=None, axes=(-2, -1), norm="backward"):
    _check_norm(norm)
    y = jnp.fft.ihfft(x, n=(s[1] if s else None), axis=axes[1], norm=norm)
    return jnp.fft.ifft(y, n=(s[0] if s else None), axis=axes[0],
                        norm=norm)


@primitive("fftshift")
def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


@primitive("ifftshift")
def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    out = jnp.fft.fftfreq(n, d=d)
    if dtype is not None:
        from .core.dtype import convert_dtype
        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    out = jnp.fft.rfftfreq(n, d=d)
    if dtype is not None:
        from .core.dtype import convert_dtype
        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftshift", "ifftshift", "fftfreq", "rfftfreq",
]

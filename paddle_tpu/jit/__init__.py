"""paddle_tpu.jit — whole-step compilation of eager code.

Capability analog of the reference dy2static stack (SURVEY L9:
``paddle.jit.to_static`` ``python/paddle/jit/api.py:135``; the SOT bytecode
tracer ``jit/sot/``; compile cache ``symbolic/compile_cache.py``) — but
TPU-native in mechanism: instead of bytecode simulation producing a
StatementIR that feeds a ProgramDesc executor, we *capture* the eager
tape-level reads/writes of framework state while re-running the function
under ``jax.jit`` tracing, producing one fused XLA program per input
signature. Graph breaks (data-dependent Python control flow) fall back to
eager, mirroring SOT's fallback semantics.

How it works (see also ``core/tensor.py`` ``_tracker``):
1. Discovery pass — the function runs eagerly once (this *is* step 0) while
   a tracker records: which pre-existing Tensors are read (program inputs:
   params, optimizer state, RNG key, batch args), which are written
   (state outputs: updated params/moments/BN stats/RNG), and which tensors
   the function returns.
2. A pure function over (input values) -> (explicit outputs + state outputs)
   is wrapped in ``jax.jit`` with state inputs donated (in-place update on
   TPU HBM, the analog of the reference's inplace address reuse in
   ``inplace_pass.cc``).
3. Cached invocations read the current values of the captured input tensors,
   run the compiled program, and write state outputs back — no Python op
   dispatch at all in steady state.

Every capture is audited ONCE by the whole-program jaxpr analyzer
(``analysis/program.py``: collective-schedule consistency, donation/
live-range HBM with a static peak estimate surfaced as the
``hbm.static_peak_bytes{fn}`` gauge, recompile risk — the cache also
reports PDT242 when >= 3 variants differ only in input shapes) before
the jaxpr is released; gated by ``PDTPU_ANALYSIS``, zero per-dispatch
work.
"""
from __future__ import annotations

import logging
import os
import time
import warnings
import weakref
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..core import state
from ..core import tensor as tensor_mod
from ..core.tensor import Tensor

logger = logging.getLogger("paddle_tpu.jit")


# --- compile/HBM observability (ISSUE 12) ----------------------------------
# Every built _Executable registers here (weak: programs die with their
# StaticFunction cache) so lazy gauges can answer "how many bytes of
# captured state do the live compiled programs pin" without any work on
# the hot path — the gauges read at snapshot/render time only.
_live_executables: "weakref.WeakSet" = weakref.WeakSet()


def _program_state_bytes(fn_name=None) -> int:
    """Captured-state bytes (params/opt state/RNG the program holds
    strong refs to) across live executables — per ``fn_name`` when
    given, process-total otherwise."""
    total = 0
    for exe in list(_live_executables):
        if fn_name is not None \
                and getattr(exe, "_fn_name", None) != fn_name:
            continue
        for t in exe.capt_state:
            v = getattr(t, "_data", None)
            nb = getattr(v, "nbytes", None)
            if nb:
                total += int(nb)
    return total


def _jax_live_bytes():
    """Process-total bytes of live jax arrays (HBM residency on a real
    device; host memory on CPU).  Read LAZILY at snapshot time."""
    return int(sum(int(getattr(a, "nbytes", 0) or 0)
                   for a in jax.live_arrays()))


def _static_peak_bytes(fn_name):
    """Largest static peak-HBM estimate among live executables of
    ``fn_name`` (stamped by ``analysis.audit_executable`` at capture;
    max, not sum — each executable is one program's peak, and shape
    variants of one function share buffers across dispatches)."""
    peak = 0
    for exe in list(_live_executables):
        if getattr(exe, "_fn_name", None) != fn_name:
            continue
        peak = max(peak, int(getattr(exe, "static_peak_bytes", 0) or 0))
    return peak


def _register_hbm_gauges(fn_name):
    """Lazy HBM-accounting gauges in the default registry: one
    ``hbm.program_state_bytes`` series per compiled-function name (the
    process total is the sum over the ``fn`` label — a same-name
    unlabeled twin would collide in ``snapshot()``'s nesting) plus the
    ``hbm.live_bytes`` process total (the ISSUE 12 blind spot — pool
    bytes were visible, program residency was not)."""
    from ..observability import metrics as _obs
    reg = _obs.registry()
    reg.gauge("hbm.program_state_bytes", labels={"fn": str(fn_name)},
              help="captured-state bytes pinned by live compiled "
                   "programs (lazy; sum over fn = process total)"
              ).set_function(lambda n=str(fn_name):
                             _program_state_bytes(n))
    reg.gauge("hbm.live_bytes",
              "process-total live jax array bytes (lazy)"
              ).set_function(_jax_live_bytes)
    reg.gauge("hbm.static_peak_bytes", labels={"fn": str(fn_name)},
              help="static peak-HBM estimate from the whole-program "
                   "audit's live-range sweep (analysis/program.py; "
                   "compare against the measured program_state_bytes)"
              ).set_function(lambda n=str(fn_name):
                             _static_peak_bytes(n))


def _note_retrace(exe, sig):
    """Emit a ``compile.retrace`` ring event with a best-effort CAUSE:
    which input positions changed signature since the first trace, or
    — when the signature is identical — the cache-miss/scan-re-trace
    class the jit guards warn about.  A steady-state stream of these
    is the retrace regression ``train.retraces`` counts."""
    from ..observability import events as _events
    from ..observability import metrics as _obs
    if not _obs.enabled():
        return
    base = getattr(exe, "_sig0", None)
    if base is None or len(base) != len(sig):
        cause = "input arity changed"
    else:
        diffs = [i for i, (a, b) in enumerate(zip(base, sig))
                 if a != b]
        if diffs:
            changed = ", ".join(
                f"arg{i}: {base[i][0]}/{base[i][1]} -> "
                f"{sig[i][0]}/{sig[i][1]}" for i in diffs[:3])
            cause = f"input signature changed ({changed})"
        else:
            cause = ("same signature (jit cache miss/eviction or "
                     "scan/window re-trace)")
    _events.emit("compile.retrace",
                 fn=getattr(exe, "_fn_name", "step"),
                 count=int(exe.trace_count), cause=cause)


def _tree_signature(obj):
    """Cache key component for one argument."""
    if isinstance(obj, Tensor):
        d = obj._data
        return ("T", tuple(d.shape), str(d.dtype))
    from ..nn import Layer
    if isinstance(obj, Layer):
        # train/eval flips change the traced program (dropout, BN): guard on
        # the mode vector (the analog of SOT's guard system)
        return ("L", id(obj), obj.training,
                tuple(l.training for l in obj.sublayers()))
    if isinstance(obj, (list, tuple)):
        return (type(obj).__name__,
                tuple(_tree_signature(o) for o in obj))
    if isinstance(obj, dict):
        return ("d", tuple(sorted(
            (k, _tree_signature(v)) for k, v in obj.items())))
    if isinstance(obj, (np.ndarray, jax.Array)):
        return ("A", tuple(obj.shape), str(obj.dtype))
    return ("c", obj if isinstance(obj, (int, float, str, bool,
                                         type(None))) else str(obj))


def _flatten_tensors(obj, out):
    if isinstance(obj, Tensor):
        out.append(obj)
    elif isinstance(obj, (list, tuple)):
        for o in obj:
            _flatten_tensors(o, out)
    elif isinstance(obj, dict):
        for k in sorted(obj):
            _flatten_tensors(obj[k], out)
    return out


class GraphBreak(Exception):
    pass


def _flat_member(t, touched):
    """True for per-param views into a NON-grad flat optimizer bucket
    whose storage participates in this capture — their state lives in
    the bucket storage, so the program must not thread them. A view
    whose bucket the program never touched (e.g. params still bound to
    an old optimizer's bucket while a new one runs per-param) is acting
    as a plain tensor and stays threaded."""
    fv = t._flat_view
    return (fv is not None and fv[1] >= 0 and fv[0].kind != "grad"
            and id(fv[0].storage) in touched)


def _state_write(t, val):
    """Post-execution state write-back: direct for plain tensors, via
    the funnel for flat-bucket views (records the local override so
    later reads see the new value instead of a stale bucket slice)."""
    if t._flat_view is not None:
        t._write(val)
    else:
        t._data = val
    t._node = None


def _scrub_leaked_tracers(discovery):
    """Replay re-executes the function, so the tape may assign tracer-backed
    grad Tensors onto real (pre-existing) tensors. Drop any such leftovers —
    the compiled program returns grads explicitly via grad_out_owners."""
    seen = list(discovery.inputs) + list(discovery.written.values()) + \
        list(discovery.grad_owners.values())
    for t in seen:
        g = t._grad
        if g is not None and isinstance(g._data, jax.core.Tracer):
            t._grad = None
        if t._node is not None:
            t._node = None


class _DiscoveryTracker:
    """Concrete-value pass: classifies tensors into inputs/state/fresh while
    the function executes for real (step 0)."""

    is_discovery = True  # flat-bucket host state may mutate (flat.py)

    def __init__(self):
        self.inputs: list[Tensor] = []      # pre-existing, read
        self.input_ids: set[int] = set()
        self.written: dict[int, Tensor] = {}  # pre-existing, written
        self.fresh: set[int] = set()        # created during capture
        self.grad_owners: dict[int, Tensor] = {}
        self.host_syncs: list[Callable] = []

    def on_create(self, t):
        self.fresh.add(id(t))

    def on_read(self, t):
        tid = id(t)
        if tid not in self.fresh and tid not in self.input_ids:
            self.input_ids.add(tid)
            self.inputs.append(t)
        return t._data

    def on_write(self, t, val):
        tid = id(t)
        if tid in self.fresh:
            # A tensor created during capture but mutated through the state
            # funnel is persistent state born lazily on step 0 (e.g.
            # optimizer accumulators): promote it to a real program
            # input/output so later steps thread it instead of re-creating.
            self.fresh.discard(tid)
            self.input_ids.add(tid)
            self.inputs.append(t)
        self.written[tid] = t
        t._data = val

    def on_grad_write(self, t):
        if id(t) not in self.fresh:
            self.grad_owners[id(t)] = t

    def add_host_sync(self, fn):
        self.host_syncs.append(fn)


class _ReplayTracker:
    """Tracing pass: substitutes jax tracers for the discovered inputs."""

    is_discovery = False  # flat-bucket host state frozen (flat.py)

    def __init__(self, input_ids_to_pos, vals):
        self.pos = input_ids_to_pos
        self.vals = vals
        self.env: dict[int, Any] = {}
        self.fresh: set[int] = set()
        self.grad_owners: dict[int, Tensor] = {}

    def on_create(self, t):
        self.fresh.add(id(t))

    def on_read(self, t):
        tid = id(t)
        if tid in self.env:
            return self.env[tid]
        if tid in self.pos:
            return self.vals[self.pos[tid]]
        if tid in self.fresh:
            return t._data
        # Tensor not seen during discovery (nondeterministic structure)
        raise GraphBreak(
            "tensor read not seen during discovery (op structure is "
            "nondeterministic across calls)")

    def on_write(self, t, val):
        self.env[id(t)] = val

    def on_grad_write(self, t):
        if id(t) not in self.fresh:
            self.grad_owners[id(t)] = t

    def add_host_sync(self, fn):
        pass  # collected once, during discovery


class _Executable:
    """One compiled specialization (per input signature). Holds strong refs
    to the captured state tensors (params/opt state/RNG) — the analog of the
    reference partial program's persistable-var scope."""

    def __init__(self, fn, discovery, ret_rebuild, n_ret):
        self.fn = fn
        self.discovery = discovery
        self.compiled = None
        self.capt_state: list[Tensor] = []
        self.state_out_tensors: list[Tensor] = []
        self.grad_out_owners: list[Tensor] = []
        self.ret_rebuild = ret_rebuild
        self.n_ret = n_ret
        self.arg_out_pos: list[int] = []
        self.trace_count = 0  # XLA (re)traces; guards retrace regressions
        self.jaxpr = None            # ClosedJaxpr, kept for the IR lint
        self.donate_idx: tuple = ()  # donated invar positions
        self.n_explicit_args = 0     # leading caller-owned inputs

    def state_split(self):
        """(carry_idx, const_idx) into ``capt_state``: which captured
        tensors the step WRITES (must thread through a scan carry) vs
        reads only (scan constants). Shared by ``jit.multi_step`` and
        the decode-window scan (``models/generation.py``)."""
        pos = {id(t): i for i, t in enumerate(self.capt_state)}
        carry_idx = [pos[id(t)] for t in self.state_out_tensors]
        carry_set = set(carry_idx)
        const_idx = [i for i in range(len(self.capt_state))
                     if i not in carry_set]
        return carry_idx, const_idx

    def build(self, arg_tensors, call_args, call_kwargs):
        d = self.discovery
        arg_pos = {id(t): i for i, t in enumerate(arg_tensors)}
        # tensors that became flat-bucket member views during discovery
        # (the fused optimizer binding params/moments at its first step)
        # are dropped: the flat storage is the program input/output and
        # their traced reads route there. GRAD views stay — under a
        # tracker they read/write as plain tensors (optimizer/flat.py),
        # so gradient accumulation threads per-param exactly as before.
        touched = {id(t) for t in d.inputs}
        touched.update(d.written)
        self.capt_state = [t for t in d.inputs
                           if id(t) not in arg_pos
                           and not _flat_member(t, touched)]
        ordered = list(arg_tensors) + self.capt_state
        pos = {id(t): i for i, t in enumerate(ordered)}

        # mutated explicit-arg tensors are written back BY POSITION to the
        # tensors of the *current* call, not the step-0 objects
        written = [t for t in d.written.values() if id(t) not in arg_pos
                   and not _flat_member(t, touched)]
        self.arg_out_pos = [arg_pos[id(t)] for t in d.written.values()
                            if id(t) in arg_pos]
        written_args = [t for t in d.written.values() if id(t) in arg_pos]
        grad_owners = list(d.grad_owners.values())
        self.state_out_tensors = written
        self.grad_out_owners = grad_owners
        fn = self.fn

        def pure(*vals):
            self.trace_count += 1
            # signature of this trace's inputs: the retrace-cause diff
            # (compile.retrace event) compares against the first one
            sig = tuple((tuple(jnp.shape(v)),
                         str(getattr(v, "dtype", type(v).__name__)))
                        for v in vals)
            if self.trace_count == 1:
                self._sig0 = sig
            else:
                _note_retrace(self, sig)
            tr = _ReplayTracker(pos, vals)
            old = tensor_mod.set_tracker(tr)
            try:
                out = fn(*call_args, **call_kwargs)
            finally:
                tensor_mod.set_tracker(old)
            ret_vals = []
            for t in _flatten_tensors(out, []):
                ret_vals.append(tr.env.get(id(t), t._data))
            state_vals = [tr.env.get(id(t), t._data) for t in written]
            arg_vals = [tr.env.get(id(t), t._data) for t in written_args]
            grad_vals = []
            for t in grad_owners:
                g = t._grad
                if g is None:
                    grad_vals.append(jnp.zeros_like(t._data))
                else:
                    # in-place accumulated grads live in the replay env
                    # (object identity stable); fresh grads hold tracers
                    grad_vals.append(tr.env.get(id(g), g._data))
            return (tuple(ret_vals) + tuple(state_vals) + tuple(arg_vals) +
                    tuple(grad_vals))

        # donate captured-state inputs that are also outputs (HBM buffer
        # reuse — the analog of the reference inplace_pass). Explicit args
        # are never donated: the caller still owns those buffers.
        written_ids = {id(t) for t in written}
        n_args = len(arg_tensors)
        donate = tuple(i for i, t in enumerate(ordered)
                       if i >= n_args and id(t) in written_ids)
        self._pure = pure  # re-used by jit.multi_step's scanned window
        self.compiled = jax.jit(pure, donate_argnums=donate)
        self.donate_idx = donate
        self.n_explicit_args = n_args
        # force tracing now so failures surface at capture time. The replay
        # re-executes the function body, so host-side grad slots can be
        # clobbered (clear_grad() + backward() replaces a concrete step-0
        # grad with a tracer-backed Tensor): snapshot and restore them.
        # The trace+lower runs under a "compile" tracing span (ISSUE 12)
        # carrying the program geometry, and its wall time backs the
        # train.compile_ms histogram — the single-process blind spot
        # that made recompiles invisible in step timelines.
        from ..observability import metrics as _obs_metrics
        from ..observability import tracing as _obs_tracing
        self._fn_name = getattr(self.fn, "__name__", "step")
        saved_grads = [(t, t._grad) for t in grad_owners]
        t0 = time.perf_counter()
        try:
            with _obs_tracing.span("compile", fn=self._fn_name,
                                   n_inputs=len(ordered),
                                   n_state=len(written),
                                   n_donated=len(donate)):
                traced = self.compiled.trace(*[t._data for t in ordered])
                self.jaxpr = traced.jaxpr
                traced.lower()
        finally:
            _scrub_leaked_tracers(d)
            for t, g in saved_grads:
                if t._grad is not g:
                    t._grad = g
        _live_executables.add(self)
        if _obs_metrics.enabled():
            _obs_metrics.registry().histogram(
                "train.compile_ms",
                "trace+lower wall time of captured programs",
                _obs_metrics.LATENCY_BUCKETS_MS).observe(
                    (time.perf_counter() - t0) * 1e3)
            _register_hbm_gauges(self._fn_name)

    def __call__(self, arg_tensors):
        for sync in self.discovery.host_syncs:
            sync()
        vals = [t._read() for t in arg_tensors] + \
            [t._read() for t in self.capt_state]
        outs = self.compiled(*vals)
        n_ret = self.n_ret
        n_state = len(self.state_out_tensors)
        n_arg_out = len(self.arg_out_pos)
        ret_vals = outs[:n_ret]
        state_vals = outs[n_ret:n_ret + n_state]
        arg_vals = outs[n_ret + n_state:n_ret + n_state + n_arg_out]
        grad_vals = outs[n_ret + n_state + n_arg_out:]
        for t, v in zip(self.state_out_tensors, state_vals):
            _state_write(t, v)
        # mutated explicit-arg tensors: write back positionally onto the
        # tensors of THIS call (not the step-0 objects)
        for pos, v in zip(self.arg_out_pos, arg_vals):
            _state_write(arg_tensors[pos], v)
        for t, v in zip(self.grad_out_owners, grad_vals):
            if t._grad is not None:
                # mutate in place so the object identity the trace captured
                # stays valid across XLA retraces (sharding changes);
                # funnel for flat-bucket grad views
                _state_write(t._grad, v)
            else:
                t._grad = Tensor(v, stop_gradient=True)
        if "PADDLE_PROGRESS_FILE" in os.environ:
            # hang-watchdog heartbeat: every completed compiled step
            # (see distributed/elastic.py)
            from ..distributed.elastic import report_progress
            report_progress()
        return self.ret_rebuild([Tensor(v) for v in ret_vals])


def _make_rebuilder(out):
    """fn(list_of_ret_tensors) -> structure shaped like ``out``."""
    if isinstance(out, Tensor):
        return lambda ts: ts[0]
    if isinstance(out, (list, tuple)):
        typ = type(out)

        def rebuild(ts, _out=out, _typ=typ):
            res, i = [], 0
            for o in _out:
                if isinstance(o, Tensor):
                    res.append(ts[i])
                    i += 1
                else:
                    res.append(o)
            return _typ(res)
        return rebuild
    if isinstance(out, dict):
        def rebuild_d(ts, _out=out):
            # sorted: must mirror _flatten_tensors' dict walk order
            res, i = {}, 0
            for k in sorted(_out):
                if isinstance(_out[k], Tensor):
                    res[k] = ts[i]
                    i += 1
                else:
                    res[k] = _out[k]
            return res
        return rebuild_d
    return lambda ts, _out=out: _out


_fallback_retry_limit = 3


def set_fallback_retry_limit(n: int) -> None:
    """How many failed trace attempts before a cache key is pinned to eager
    (the retry policy the reference's SOT gets from guard invalidation;
    a transient failure — OOM, flaky host callback — no longer poisons the
    key forever). Default 3."""
    global _fallback_retry_limit
    _fallback_retry_limit = max(1, int(n))


def get_fallback_retry_limit() -> int:
    return _fallback_retry_limit


class StaticFunction:
    """Analog of ``SymbolicStaticFunction``
    (reference ``jit/dy2static/program_translator.py:708``)."""

    def __init__(self, fn, build_strategy=None, backend=None,
                 full_graph=False, remat=None):
        self.fn = fn
        self._cache: dict[Any, _Executable] = {}
        self._fallback_keys: set = set()
        self._fallback_counts: dict[Any, int] = {}
        self._full_graph = full_graph
        self.__name__ = getattr(fn, "__name__", "static_fn")
        self._conv_fn = None
        self._conv_tried = False
        # resolved 1-tuple (policy,) from to_static(remat=...), or None.
        # Applied AFTER dy2static conversion (see _converted): wrapping
        # before it would hand dy2static a wrapper whose source/closure
        # don't match the user function.
        self._remat = remat
        self._remat_fn = None

    def _converted(self):
        """The dy2static AST-converted function (plain Python if/while/for
        on tensor predicates lowered to cond/while_loop — see
        ``jit/dy2static.py``), or the original when conversion found
        nothing to do or declined. Converted lazily on first call so
        closure cells are populated."""
        if not self._conv_tried:
            # pre-conversion tracer-safety lint (PDT1xx); a no-op when
            # PDTPU_ANALYSIS=off, raises StaticAnalysisError under
            # =error. Runs BEFORE _conv_tried is set: a blocked call
            # must not burn the one conversion attempt, so a later
            # suppressed/fixed call still converts.
            from .. import analysis as _analysis
            _analysis.lint_callable(self.fn, where=self.__name__)
            self._conv_tried = True
            try:
                from .dy2static import convert_function
                self._conv_fn = convert_function(self.fn)
            except Exception as e:
                from ..core.errors import StaticAnalysisError
                if isinstance(e, StaticAnalysisError):
                    # the conversion-decline gate (PDTPU_ANALYSIS=error)
                    # must propagate, and the blocked call must not burn
                    # the one conversion attempt
                    self._conv_tried = False
                    raise
                warnings.warn(
                    f"to_static: dy2static conversion of {self.__name__} "
                    f"failed ({type(e).__name__}: {e}); using the "
                    "original function")
                self._conv_fn = None
        fn = self._conv_fn or self.fn
        if self._remat is None:
            return fn
        if self._remat_fn is None:
            from ..distributed.fleet.recompute import recompute
            pol = self._remat[0]

            def _remat_fn(*args, **kw):
                return recompute(fn, *args, policy=pol, **kw)
            self._remat_fn = _remat_fn
        return self._remat_fn

    def __get__(self, instance, owner):
        # bound-method support for @to_static on Layer methods
        import functools
        if instance is None:
            return self
        bound = functools.partial(self.__call__, instance)
        bound.__wrapped__ = self
        return bound

    def _cache_key(self, args, kwargs):
        from .. import amp
        a = amp.amp_state()
        return (tuple(_tree_signature(x) for x in args),
                tuple(sorted((k, _tree_signature(v))
                             for k, v in kwargs.items())),
                a.enabled, str(a.dtype), a.level,
                state.is_grad_enabled())

    def __call__(self, *args, **kwargs):
        if tensor_mod._tracker is not None:
            # nested to_static: inline into the outer capture
            return self._converted()(*args, **kwargs)
        try:
            key = self._cache_key(args, kwargs)
        except Exception:
            return self._converted()(*args, **kwargs)
        if key in self._fallback_keys:
            return self._converted()(*args, **kwargs)
        exe = self._cache.get(key)
        arg_tensors = _flatten_tensors((list(args), kwargs), [])
        if exe is not None:
            return exe(arg_tensors)
        return self._capture(key, args, kwargs, arg_tensors)

    def _capture(self, key, args, kwargs, arg_tensors):
        fn = self._converted()
        d = _DiscoveryTracker()
        old = tensor_mod.set_tracker(d)
        try:
            out = fn(*args, **kwargs)
        finally:
            tensor_mod.set_tracker(old)
        # a grad owner whose grad is None at function exit was cleared
        # in-function (opt.clear_grad): it is not a program output — and
        # writing a value back would desync eager state from the captured
        # program (stale grads then break later retraces)
        d.grad_owners = {k: t for k, t in d.grad_owners.items()
                         if t._grad is not None}
        ret_tensors = _flatten_tensors(out, [])
        exe = _Executable(fn, d, _make_rebuilder(out),
                          len(ret_tensors))
        try:
            exe.build(arg_tensors, args, kwargs)
        except Exception as e:  # trace failed -> eager, retry next call
            if self._full_graph:
                raise
            n = self._fallback_counts.get(key, 0) + 1
            self._fallback_counts[key] = n
            limit = _fallback_retry_limit
            if n >= limit:
                warnings.warn(
                    f"to_static: pinning {self.__name__} to eager after "
                    f"{n} failed traces ({type(e).__name__}: {e})")
                self._fallback_keys.add(key)
            else:
                warnings.warn(
                    f"to_static: eager fallback for {self.__name__}, "
                    f"trace retry {n}/{limit} on next call "
                    f"({type(e).__name__}: {e})")
            return out
        self._fallback_counts.pop(key, None)
        # post-capture whole-program audit (PDT2xx: collective
        # consistency, donation/HBM with the static peak estimate,
        # recompile risk) over the traced program. Runs BEFORE caching:
        # under PDTPU_ANALYSIS=error a blocking finding leaves the key
        # uncached, so every call re-captures and raises again until the
        # finding is fixed or suppressed. The jaxpr is only needed here
        # — release it so cached executables of large models don't pin
        # the whole trace for the process lifetime (the audit stashes
        # ``static_peak_bytes``/``schedule_hash`` on the exe first).
        from .. import analysis as _analysis
        _analysis.audit_executable(exe, where=self.__name__, fn=self.fn)
        exe.jaxpr = None
        self._cache[key] = exe
        self._check_shape_fork(key)
        return out  # discovery pass already produced step-0 results

    def _check_shape_fork(self, key):
        """PDT242: >= SHAPE_FORK_LIMIT cached variants differing ONLY in
        input shapes means a length/batch/table is baked as a static
        dim — every new size recompiles. Compile-time-only work (runs
        once per new cache entry) sharing the ``compile.retrace`` cause
        vocabulary with the runtime classifier."""
        from ..analysis import program as _program
        try:
            stripped = _program.strip_shapes(key)
            variants = sum(1 for k in self._cache
                           if _program.strip_shapes(k) == stripped)
        except Exception:
            return
        if variants < _program.SHAPE_FORK_LIMIT:
            return
        from .. import analysis as _analysis
        cause = (f"shape-as-data: {variants} compiled variants of "
                 f"{self.__name__} differ only in input shapes")
        _analysis.report_runtime(
            "PDT242",
            f"{cause} — a traced length/table is baked as a static dim "
            f"(every new size recompiles); pad to bucketed shapes or "
            f"pass the length as data", file=f"<jit:{self.__name__}>")
        from ..observability import events as _events
        from ..observability import metrics as _obs
        if _obs.enabled():
            _events.emit("compile.retrace", fn=self.__name__,
                         count=int(variants), cause=cause)

    def concrete_program(self, *args, **kwargs):
        return self._cache.get(self._cache_key(args, kwargs))

    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self.fn)
        except OSError:
            return "<source unavailable>"


def aot_lower(fn, *args, donate_state=True, **kwargs):
    """Ahead-of-time lower ``fn``'s captured train-step program WITHOUT
    executing it: the same discovery capture as ``to_static`` runs with
    abstract values, so LazyGuard-built models lower at scales whose
    real parameters exceed host memory (the 13B-on-32-virtual-devices
    proof runs the REAL ``GPTForCausalLM`` + ``shard_gpt`` capture, not
    a hand-written twin). Returns a ``jax.stages.Lowered``;
    ``.compile().memory_analysis()`` gives the per-device picture.

    Inputs = explicit ``args`` tensors + every live lazy tensor
    (shardings from their annotations). With ``donate_state`` the lazy
    state written by the step (parameters under an optimizer update) is
    donated, matching the executable path's buffer reuse. Tensors
    CREATED inside (optimizer moments on their first step) lower as
    outputs — same residency, but not yet aliased inputs as in the
    steady-state program."""
    import jax as _jax

    from ..core import lazy as _lazy

    if isinstance(fn, StaticFunction):
        fn = fn._converted()
    arg_tensors = _flatten_tensors((list(args), kwargs), [])
    arg_ids = {id(t) for t in arg_tensors}
    lazies = [t for t in _lazy.lazy_tensors() if id(t) not in arg_ids]
    tensors = list(arg_tensors) + lazies

    def spec_of(t):
        v = t._data
        if isinstance(v, _jax.ShapeDtypeStruct):
            return v
        sh = getattr(v, "sharding", None)
        from jax.sharding import NamedSharding
        return _jax.ShapeDtypeStruct(
            jnp.shape(v), v.dtype,
            sharding=sh if isinstance(sh, NamedSharding) else None)

    specs = [spec_of(t) for t in tensors]
    holder = {}

    def drive(*vals):
        saved = [(t, t._data, t._grad, t._node) for t in tensors]
        for t, v in zip(tensors, vals):
            t._data = v
        d = _DiscoveryTracker()
        old = tensor_mod.set_tracker(d)
        try:
            out = fn(*args, **kwargs)
            ret_vals = [t._data for t in _flatten_tensors(out, [])]
            written = [t for t in d.written.values()]
            state_vals = [t._data for t in written]
            holder["written_ids"] = {id(t) for t in written}
        finally:
            tensor_mod.set_tracker(old)
            _scrub_leaked_tracers(d)
            for t, v, g, n in saved:
                t._data = v
                t._grad = g
                t._node = n
        return tuple(ret_vals) + tuple(state_vals)

    if not donate_state:
        return _jax.jit(drive).lower(*specs)
    # trace once to learn which state the step writes, then lower with
    # those inputs donated (the _Executable donates the same way)
    _jax.eval_shape(drive, *specs)
    donate = tuple(i for i, t in enumerate(tensors)
                   if i >= len(arg_tensors)
                   and id(t) in holder["written_ids"])
    return _jax.jit(drive, donate_argnums=donate).lower(*specs)


def _resolve_remat(policy):
    """Validate ``to_static(remat=...)`` and return the
    ``fleet.recompute`` policy object (``None`` spells 'full': save
    nothing, recompute everything). The wrap itself happens after
    dy2static conversion (``StaticFunction._converted``): the whole
    call runs under ``fleet.recompute`` with this policy, so its
    backward recomputes the non-saveable intermediates instead of
    keeping them live — which is what moves the captured executable's
    ``static_peak_bytes``. Gradients are bitwise-identical either way.
    The wrapped function must be a pure forward (args -> outputs);
    train-step closures that call ``.backward()`` inside should use
    ``Model.prepare(remat=)`` instead, which remats the transformer
    blocks themselves."""
    from ..distributed.fleet.recompute import _POLICIES
    if policy is True or policy == "full":
        return None
    if policy is None or policy not in _POLICIES:
        raise ValueError(
            f"to_static(remat={policy!r}): unknown remat policy; "
            f"expected True, 'full', or one of "
            f"{sorted(k for k in _POLICIES if isinstance(k, str))}")
    return policy


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, remat=None, **kwargs):
    """``paddle.jit.to_static`` analog (reference ``jit/api.py:135``).

    ``remat`` (TPU extension, ISSUE 19): ``True``/'full' or a
    ``fleet.recompute`` policy name runs the converted function under
    selective activation recompute at capture — see
    :func:`_resolve_remat`."""
    def deco(fn):
        if isinstance(fn, StaticFunction):
            if input_spec is not None:
                fn._input_spec = input_spec
            return fn
        import functools
        sf = StaticFunction(fn, build_strategy, backend, full_graph,
                            remat=(_resolve_remat(remat),)
                            if remat else None)
        functools.update_wrapper(sf, fn, updated=[])
        sf._input_spec = input_spec
        return sf

    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    fn._pdtpu_not_to_static = True
    return fn


def ignore_module(modules):
    pass


def enable_to_static(flag):
    pass


class BuildStrategy:
    """Compatibility shim (reference CompiledProgram BuildStrategy); XLA owns
    all the fusion/inlining decisions these flags used to toggle."""

    def __init__(self):
        self.build_cinn_pass = False
        self.enable_inplace = True


# --- save / load (inference program export) --------------------------------
# ``paddle.jit.save`` analog (reference ``jit/api.py:744`` -> TranslatedLayer
# ``:1246``): the traced program is exported as serialized StableHLO via
# jax.export (the TPU-native ProgramDesc: SURVEY §7 maps ProgramDesc/PIR to
# StableHLO as the IR). Format:
#   {path}.pdmodel   pickle {stablehlo: bytes, param_names, out_struct, ...}
#   {path}.pdiparams the parameter/buffer state dict (framework.save format)
# ``jit.load`` rebuilds a TranslatedLayer that executes the program without
# the original Python class.

class _ExportTracker:
    """Substitutes traced values for the captured parameter tensors during
    program export; state writes are swallowed (the exported program is a
    pure inference function)."""

    def __init__(self, mapping):
        self.map = mapping
        self.env: dict[int, Any] = {}

    def on_create(self, t):
        pass

    def on_read(self, t):
        tid = id(t)
        if tid in self.map:
            return self.map[tid]
        if tid in self.env:
            return self.env[tid]
        return t._data

    def on_write(self, t, val):
        self.env[id(t)] = val

    def on_grad_write(self, t):
        pass

    def add_host_sync(self, fn):
        pass


def _encode_structure(out):
    """Picklable descriptor of the output pytree; Tensors become indices."""
    counter = [0]

    def enc(o):
        if isinstance(o, Tensor):
            i = counter[0]
            counter[0] += 1
            return ("t", i)
        if isinstance(o, (list, tuple)):
            return ("seq", type(o).__name__, [enc(x) for x in o])
        if isinstance(o, dict):
            # tensor indices MUST follow _flatten_tensors' walk order,
            # which visits dict keys sorted — insertion order here would
            # silently swap values between keys
            return ("d", {k: enc(o[k]) for k in sorted(o)})
        return ("c", o)
    return enc(out), counter[0]


def _decode_structure(desc, tensors):
    kind = desc[0]
    if kind == "t":
        return tensors[desc[1]]
    if kind == "seq":
        seq = [_decode_structure(x, tensors) for x in desc[2]]
        return tuple(seq) if desc[1] == "tuple" else seq
    if kind == "d":
        return {k: _decode_structure(v, tensors) for k, v in desc[1].items()}
    return desc[1]


def _spec_avals(specs):
    """InputSpecs -> jax avals; None dims become symbolic dimensions (one
    shared symbol per position index so equal batch dims stay equal)."""
    from jax import export as jexport
    has_dynamic = any(d is None for s in specs for d in s.shape)
    if not has_dynamic:
        return [jax.ShapeDtypeStruct(s.shape, jnp.dtype(s.dtype))
                for s in specs], False
    scope = jexport.SymbolicScope()
    avals = []
    for si, s in enumerate(specs):
        parts = []
        for di, d in enumerate(s.shape):
            parts.append(f"d{di}" if d is None else str(d))
        shape = jexport.symbolic_shape(",".join(parts) or "", scope=scope)
        avals.append(jax.ShapeDtypeStruct(shape, jnp.dtype(s.dtype)))
    return avals, True


def _resolve_input_spec(fn_or_layer, input_spec):
    from ..static import InputSpec
    if input_spec is None:
        target = fn_or_layer
        from ..nn import Layer
        if isinstance(fn_or_layer, Layer):
            target = getattr(type(fn_or_layer).forward, "__wrapped__",
                             fn_or_layer.forward)
        input_spec = getattr(target, "_input_spec", None)
    if input_spec is None:
        raise ValueError(
            "jit.save needs an input_spec: pass input_spec=[InputSpec(...)]"
            " to jit.save or to @to_static")
    specs = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            specs.append(s)
        elif isinstance(s, Tensor):
            specs.append(InputSpec.from_tensor(s))
        else:
            raise TypeError(f"input_spec entries must be InputSpec/Tensor, "
                            f"got {type(s).__name__}")
    return specs


def save(layer, path, input_spec=None, **config):
    """Export ``layer`` (or a ``@to_static`` function) as a standalone
    inference program + parameters (reference ``jit/api.py:744``)."""
    import pickle

    from .. import framework as fw
    from ..core.autograd import no_grad
    from ..nn import Layer
    from jax import export as jexport

    specs = _resolve_input_spec(layer, input_spec)

    if isinstance(layer, Layer):
        named = layer.state_dict()
        fn = layer
    else:
        fn = layer.fn if isinstance(layer, StaticFunction) else layer
        if not callable(fn):
            raise TypeError("jit.save expects a Layer or a callable")
        # discover captured state with a probe run on example inputs
        d = _DiscoveryTracker()
        ex_args = [Tensor(jnp.asarray(s._example())) for s in specs]
        old = tensor_mod.set_tracker(d)
        try:
            with no_grad():
                fn(*ex_args)
        finally:
            tensor_mod.set_tracker(old)
        named = {f"var_{i}": t for i, t in enumerate(
            t for t in d.inputs if not any(t is a for a in ex_args))}

    names = list(named)
    ptensors = [named[n] for n in names]

    def pure(param_vals, *input_vals):
        tr = _ExportTracker(
            {id(t): v for t, v in zip(ptensors, param_vals)})
        old = tensor_mod.set_tracker(tr)
        try:
            with no_grad():
                out = fn(*[Tensor(v) for v in input_vals])
        finally:
            tensor_mod.set_tracker(old)
        flat = _flatten_tensors(out, [])
        return [tr.env.get(id(t), t._data) for t in flat], out

    def pure_vals(param_vals, *input_vals):
        return pure(param_vals, *input_vals)[0]

    param_vals = [t._read() for t in ptensors]
    avals, symbolic = _spec_avals(specs)
    param_avals = [jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for v in param_vals]
    try:
        exported = jexport.export(jax.jit(pure_vals))(param_avals, *avals)
    except Exception:
        if not symbolic:
            raise
        # model not shape-polymorphic (static reshapes etc.): fall back to
        # the example's concrete shapes
        warnings.warn("jit.save: symbolic-shape export failed; exporting "
                      "with concrete example shapes instead")
        avals = [jax.ShapeDtypeStruct(
            tuple(2 if d is None else d for d in s.shape),
            jnp.dtype(s.dtype)) for s in specs]
        exported = jexport.export(jax.jit(pure_vals))(param_avals, *avals)

    # run once concretely to learn the output structure
    with no_grad():
        _, out_example = pure(param_vals,
                              *[jnp.zeros([2 if d is None else d
                                           for d in s.shape],
                                          jnp.dtype(s.dtype))
                                for s in specs])
    out_struct, n_out = _encode_structure(out_example)

    # output names for the inference Predictor (reference: fetch-var
    # names in the saved program): explicit ``output_names=[...]`` wins,
    # else dict keys / tensor .name along the flatten order, else out{i}
    out_names = []

    def _name_walk(o, path):
        if isinstance(o, Tensor):
            nm = getattr(o, "name", None)
            out_names.append(nm if nm else
                             (path or f"out{len(out_names)}"))
        elif isinstance(o, (list, tuple)):
            for i, v in enumerate(o):
                _name_walk(v, f"{path}.{i}" if path else str(i))
        elif isinstance(o, dict):
            for k in sorted(o):
                _name_walk(o[k], f"{path}.{k}" if path else str(k))

    explicit = config.get("output_names")
    if explicit:
        out_names = [str(n) for n in explicit]
    else:
        _name_walk(out_example, "")
        # all-positional fallback keeps the legacy out{i} names
        if all(n.isdigit() for n in out_names):
            out_names = [f"out{i}" for i in range(len(out_names))]
    if len(out_names) != n_out:
        out_names = [f"out{i}" for i in range(n_out)]

    meta = {
        "format": "pdtpu.jit.v1",
        "stablehlo": bytes(exported.serialize()),
        "param_names": names,
        "out_struct": out_struct,
        "n_out": n_out,
        "in_specs": [(s.shape, s.dtype, s.name) for s in specs],
        "out_names": out_names,
    }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f, protocol=4)
    fw.save(dict(zip(names, ptensors)), path + ".pdiparams")


class TranslatedLayer:
    """A loaded inference program (reference TranslatedLayer,
    ``jit/api.py:1246``): callable without the original model code."""

    def __init__(self, meta, params):
        # slim metadata for consumers (inference.Predictor IO names) —
        # everything except the serialized program, which would pin
        # potentially hundreds of MB alongside the deserialized Exported
        self._meta = {k: v for k, v in meta.items() if k != "stablehlo"}
        from jax import export as jexport
        self._exported = jexport.deserialize(bytearray(meta["stablehlo"]))
        self._names = meta["param_names"]
        self._out_struct = meta["out_struct"]
        self._params = params
        self._call = jax.jit(
            lambda pv, *xs: self._exported.call(pv, *xs))

    def __call__(self, *inputs):
        return self.forward(*inputs)

    def forward(self, *inputs):
        vals = [x._read() if isinstance(x, Tensor) else jnp.asarray(x)
                for x in inputs]
        pv = [self._params[n]._read() for n in self._names]
        outs = self._call(pv, *vals)
        tensors = [Tensor(o, stop_gradient=True) for o in outs]
        return _decode_structure(self._out_struct, tensors)

    def state_dict(self):
        return dict(self._params)

    def set_state_dict(self, sd):
        for k, v in sd.items():
            if k in self._params:
                self._params[k]._data = (v._read() if isinstance(v, Tensor)
                                         else jnp.asarray(v))

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is an inference program; "
                           "training requires the original model code")


def load(path, **config):
    """Load a ``jit.save``d program as a TranslatedLayer (reference
    ``jit/api.py:1246``)."""
    import pickle

    from .. import framework as fw
    with open(path + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    if meta.get("format") != "pdtpu.jit.v1":
        raise ValueError(f"{path}.pdmodel is not a pdtpu jit export")
    params = fw.load(path + ".pdiparams")
    return TranslatedLayer(meta, params)


from .multi_step import WindowRunner, multi_step  # noqa: E402,F401

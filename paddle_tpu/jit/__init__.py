"""paddle_tpu.jit — whole-step compilation of eager code.

Capability analog of the reference dy2static stack (SURVEY L9:
``paddle.jit.to_static`` ``python/paddle/jit/api.py:135``; the SOT bytecode
tracer ``jit/sot/``; compile cache ``symbolic/compile_cache.py``) — but
TPU-native in mechanism: instead of bytecode simulation producing a
StatementIR that feeds a ProgramDesc executor, we *capture* the eager
tape-level reads/writes of framework state while re-running the function
under ``jax.jit`` tracing, producing one fused XLA program per input
signature. Graph breaks (data-dependent Python control flow) fall back to
eager, mirroring SOT's fallback semantics.

How it works (see also ``core/tensor.py`` ``_tracker``):
1. Discovery pass — the function runs eagerly once (this *is* step 0) while
   a tracker records: which pre-existing Tensors are read (program inputs:
   params, optimizer state, RNG key, batch args), which are written
   (state outputs: updated params/moments/BN stats/RNG), and which tensors
   the function returns.
2. A pure function over (input values) -> (explicit outputs + state outputs)
   is wrapped in ``jax.jit`` with state inputs donated (in-place update on
   TPU HBM, the analog of the reference's inplace address reuse in
   ``inplace_pass.cc``).
3. Cached invocations read the current values of the captured input tensors,
   run the compiled program, and write state outputs back — no Python op
   dispatch at all in steady state.
"""
from __future__ import annotations

import logging
import warnings
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..core import state
from ..core import tensor as tensor_mod
from ..core.tensor import Tensor

logger = logging.getLogger("paddle_tpu.jit")


def _tree_signature(obj):
    """Cache key component for one argument."""
    if isinstance(obj, Tensor):
        d = obj._data
        return ("T", tuple(d.shape), str(d.dtype))
    from ..nn import Layer
    if isinstance(obj, Layer):
        # train/eval flips change the traced program (dropout, BN): guard on
        # the mode vector (the analog of SOT's guard system)
        return ("L", id(obj), obj.training,
                tuple(l.training for l in obj.sublayers()))
    if isinstance(obj, (list, tuple)):
        return (type(obj).__name__,
                tuple(_tree_signature(o) for o in obj))
    if isinstance(obj, dict):
        return ("d", tuple(sorted(
            (k, _tree_signature(v)) for k, v in obj.items())))
    if isinstance(obj, (np.ndarray, jax.Array)):
        return ("A", tuple(obj.shape), str(obj.dtype))
    return ("c", obj if isinstance(obj, (int, float, str, bool,
                                         type(None))) else str(obj))


def _flatten_tensors(obj, out):
    if isinstance(obj, Tensor):
        out.append(obj)
    elif isinstance(obj, (list, tuple)):
        for o in obj:
            _flatten_tensors(o, out)
    elif isinstance(obj, dict):
        for k in sorted(obj):
            _flatten_tensors(obj[k], out)
    return out


class GraphBreak(Exception):
    pass


def _scrub_leaked_tracers(discovery):
    """Replay re-executes the function, so the tape may assign tracer-backed
    grad Tensors onto real (pre-existing) tensors. Drop any such leftovers —
    the compiled program returns grads explicitly via grad_out_owners."""
    seen = list(discovery.inputs) + list(discovery.written.values()) + \
        list(discovery.grad_owners.values())
    for t in seen:
        g = t._grad
        if g is not None and isinstance(g._data, jax.core.Tracer):
            t._grad = None
        if t._node is not None:
            t._node = None


class _DiscoveryTracker:
    """Concrete-value pass: classifies tensors into inputs/state/fresh while
    the function executes for real (step 0)."""

    def __init__(self):
        self.inputs: list[Tensor] = []      # pre-existing, read
        self.input_ids: set[int] = set()
        self.written: dict[int, Tensor] = {}  # pre-existing, written
        self.fresh: set[int] = set()        # created during capture
        self.grad_owners: dict[int, Tensor] = {}
        self.host_syncs: list[Callable] = []

    def on_create(self, t):
        self.fresh.add(id(t))

    def on_read(self, t):
        tid = id(t)
        if tid not in self.fresh and tid not in self.input_ids:
            self.input_ids.add(tid)
            self.inputs.append(t)
        return t._data

    def on_write(self, t, val):
        tid = id(t)
        if tid in self.fresh:
            # A tensor created during capture but mutated through the state
            # funnel is persistent state born lazily on step 0 (e.g.
            # optimizer accumulators): promote it to a real program
            # input/output so later steps thread it instead of re-creating.
            self.fresh.discard(tid)
            self.input_ids.add(tid)
            self.inputs.append(t)
        self.written[tid] = t
        t._data = val

    def on_grad_write(self, t):
        if id(t) not in self.fresh:
            self.grad_owners[id(t)] = t

    def add_host_sync(self, fn):
        self.host_syncs.append(fn)


class _ReplayTracker:
    """Tracing pass: substitutes jax tracers for the discovered inputs."""

    def __init__(self, input_ids_to_pos, vals):
        self.pos = input_ids_to_pos
        self.vals = vals
        self.env: dict[int, Any] = {}
        self.fresh: set[int] = set()
        self.grad_owners: dict[int, Tensor] = {}

    def on_create(self, t):
        self.fresh.add(id(t))

    def on_read(self, t):
        tid = id(t)
        if tid in self.env:
            return self.env[tid]
        if tid in self.pos:
            return self.vals[self.pos[tid]]
        if tid in self.fresh:
            return t._data
        # Tensor not seen during discovery (nondeterministic structure)
        raise GraphBreak(
            "tensor read not seen during discovery (op structure is "
            "nondeterministic across calls)")

    def on_write(self, t, val):
        self.env[id(t)] = val

    def on_grad_write(self, t):
        if id(t) not in self.fresh:
            self.grad_owners[id(t)] = t

    def add_host_sync(self, fn):
        pass  # collected once, during discovery


class _Executable:
    """One compiled specialization (per input signature). Holds strong refs
    to the captured state tensors (params/opt state/RNG) — the analog of the
    reference partial program's persistable-var scope."""

    def __init__(self, fn, discovery, ret_rebuild, n_ret):
        self.fn = fn
        self.discovery = discovery
        self.compiled = None
        self.capt_state: list[Tensor] = []
        self.state_out_tensors: list[Tensor] = []
        self.grad_out_owners: list[Tensor] = []
        self.ret_rebuild = ret_rebuild
        self.n_ret = n_ret
        self.arg_out_pos: list[int] = []
        self.trace_count = 0  # XLA (re)traces; guards retrace regressions

    def build(self, arg_tensors, call_args, call_kwargs):
        d = self.discovery
        arg_pos = {id(t): i for i, t in enumerate(arg_tensors)}
        self.capt_state = [t for t in d.inputs if id(t) not in arg_pos]
        ordered = list(arg_tensors) + self.capt_state
        pos = {id(t): i for i, t in enumerate(ordered)}

        # mutated explicit-arg tensors are written back BY POSITION to the
        # tensors of the *current* call, not the step-0 objects
        written = [t for t in d.written.values() if id(t) not in arg_pos]
        self.arg_out_pos = [arg_pos[id(t)] for t in d.written.values()
                            if id(t) in arg_pos]
        written_args = [t for t in d.written.values() if id(t) in arg_pos]
        grad_owners = list(d.grad_owners.values())
        self.state_out_tensors = written
        self.grad_out_owners = grad_owners
        fn = self.fn

        def pure(*vals):
            self.trace_count += 1
            tr = _ReplayTracker(pos, vals)
            old = tensor_mod.set_tracker(tr)
            try:
                out = fn(*call_args, **call_kwargs)
            finally:
                tensor_mod.set_tracker(old)
            ret_vals = []
            for t in _flatten_tensors(out, []):
                ret_vals.append(tr.env.get(id(t), t._data))
            state_vals = [tr.env.get(id(t), t._data) for t in written]
            arg_vals = [tr.env.get(id(t), t._data) for t in written_args]
            grad_vals = []
            for t in grad_owners:
                g = t._grad
                grad_vals.append(g._data if g is not None
                                 else jnp.zeros_like(t._data))
            return (tuple(ret_vals) + tuple(state_vals) + tuple(arg_vals) +
                    tuple(grad_vals))

        # donate captured-state inputs that are also outputs (HBM buffer
        # reuse — the analog of the reference inplace_pass). Explicit args
        # are never donated: the caller still owns those buffers.
        written_ids = {id(t) for t in written}
        n_args = len(arg_tensors)
        donate = tuple(i for i, t in enumerate(ordered)
                       if i >= n_args and id(t) in written_ids)
        self.compiled = jax.jit(pure, donate_argnums=donate)
        # force tracing now so failures surface at capture time
        try:
            self.compiled.lower(*[t._data for t in ordered])
        finally:
            _scrub_leaked_tracers(d)

    def __call__(self, arg_tensors):
        for sync in self.discovery.host_syncs:
            sync()
        vals = [t._read() for t in arg_tensors] + \
            [t._read() for t in self.capt_state]
        outs = self.compiled(*vals)
        n_ret = self.n_ret
        n_state = len(self.state_out_tensors)
        n_arg_out = len(self.arg_out_pos)
        ret_vals = outs[:n_ret]
        state_vals = outs[n_ret:n_ret + n_state]
        arg_vals = outs[n_ret + n_state:n_ret + n_state + n_arg_out]
        grad_vals = outs[n_ret + n_state + n_arg_out:]
        for t, v in zip(self.state_out_tensors, state_vals):
            t._data = v
            t._node = None
        # mutated explicit-arg tensors: write back positionally onto the
        # tensors of THIS call (not the step-0 objects)
        for pos, v in zip(self.arg_out_pos, arg_vals):
            arg_tensors[pos]._data = v
            arg_tensors[pos]._node = None
        for t, v in zip(self.grad_out_owners, grad_vals):
            if t._grad is not None:
                # mutate in place so the object identity the trace captured
                # stays valid across XLA retraces (sharding changes)
                t._grad._data = v
                t._grad._node = None
            else:
                t._grad = Tensor(v, stop_gradient=True)
        return self.ret_rebuild([Tensor(v) for v in ret_vals])


def _make_rebuilder(out):
    """fn(list_of_ret_tensors) -> structure shaped like ``out``."""
    if isinstance(out, Tensor):
        return lambda ts: ts[0]
    if isinstance(out, (list, tuple)):
        typ = type(out)

        def rebuild(ts, _out=out, _typ=typ):
            res, i = [], 0
            for o in _out:
                if isinstance(o, Tensor):
                    res.append(ts[i])
                    i += 1
                else:
                    res.append(o)
            return _typ(res)
        return rebuild
    if isinstance(out, dict):
        def rebuild_d(ts, _out=out):
            res, i = {}, 0
            for k in _out:
                if isinstance(_out[k], Tensor):
                    res[k] = ts[i]
                    i += 1
                else:
                    res[k] = _out[k]
            return res
        return rebuild_d
    return lambda ts, _out=out: _out


class StaticFunction:
    """Analog of ``SymbolicStaticFunction``
    (reference ``jit/dy2static/program_translator.py:708``)."""

    def __init__(self, fn, build_strategy=None, backend=None,
                 full_graph=False):
        self.fn = fn
        self._cache: dict[Any, _Executable] = {}
        self._fallback_keys: set = set()
        self._full_graph = full_graph
        self.__name__ = getattr(fn, "__name__", "static_fn")

    def __get__(self, instance, owner):
        # bound-method support for @to_static on Layer methods
        import functools
        if instance is None:
            return self
        bound = functools.partial(self.__call__, instance)
        bound.__wrapped__ = self
        return bound

    def _cache_key(self, args, kwargs):
        from .. import amp
        a = amp.amp_state()
        return (tuple(_tree_signature(x) for x in args),
                tuple(sorted((k, _tree_signature(v))
                             for k, v in kwargs.items())),
                a.enabled, str(a.dtype), a.level,
                state.is_grad_enabled())

    def __call__(self, *args, **kwargs):
        if tensor_mod._tracker is not None:
            # nested to_static: inline into the outer capture
            return self.fn(*args, **kwargs)
        try:
            key = self._cache_key(args, kwargs)
        except Exception:
            return self.fn(*args, **kwargs)
        if key in self._fallback_keys:
            return self.fn(*args, **kwargs)
        exe = self._cache.get(key)
        arg_tensors = _flatten_tensors((list(args), kwargs), [])
        if exe is not None:
            return exe(arg_tensors)
        return self._capture(key, args, kwargs, arg_tensors)

    def _capture(self, key, args, kwargs, arg_tensors):
        d = _DiscoveryTracker()
        old = tensor_mod.set_tracker(d)
        try:
            out = self.fn(*args, **kwargs)
        finally:
            tensor_mod.set_tracker(old)
        # a grad owner whose grad is None at function exit was cleared
        # in-function (opt.clear_grad): it is not a program output — and
        # writing a value back would desync eager state from the captured
        # program (stale grads then break later retraces)
        d.grad_owners = {k: t for k, t in d.grad_owners.items()
                         if t._grad is not None}
        ret_tensors = _flatten_tensors(out, [])
        exe = _Executable(self.fn, d, _make_rebuilder(out),
                          len(ret_tensors))
        try:
            exe.build(arg_tensors, args, kwargs)
        except Exception as e:  # trace failed -> permanent eager fallback
            if self._full_graph:
                raise
            warnings.warn(
                f"to_static: eager fallback for {self.__name__} "
                f"({type(e).__name__}: {e})")
            self._fallback_keys.add(key)
            return out
        self._cache[key] = exe
        return out  # discovery pass already produced step-0 results

    def concrete_program(self, *args, **kwargs):
        return self._cache.get(self._cache_key(args, kwargs))

    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self.fn)
        except OSError:
            return "<source unavailable>"


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, **kwargs):
    """``paddle.jit.to_static`` analog (reference ``jit/api.py:135``)."""
    def deco(fn):
        if isinstance(fn, StaticFunction):
            return fn
        import functools
        sf = StaticFunction(fn, build_strategy, backend, full_graph)
        functools.update_wrapper(sf, fn, updated=[])
        return sf

    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    fn._pdtpu_not_to_static = True
    return fn


def ignore_module(modules):
    pass


def enable_to_static(flag):
    pass


class BuildStrategy:
    """Compatibility shim (reference CompiledProgram BuildStrategy); XLA owns
    all the fusion/inlining decisions these flags used to toggle."""

    def __init__(self):
        self.build_cinn_pass = False
        self.enable_inplace = True


# --- save / load (inference export) ---------------------------------------
def save(layer, path, input_spec=None, **config):
    """``paddle.jit.save`` analog (reference ``jit/api.py:744``): exports
    state dict now; StableHLO program export lands with the inference
    engine."""
    from .. import framework as fw
    from ..nn import Layer
    if isinstance(layer, Layer):
        fw.save(layer.state_dict(), path + ".pdparams")
    else:
        raise TypeError("jit.save expects a Layer")


def load(path, **config):
    from .. import framework as fw
    return fw.load(path + ".pdparams")

def to_static(fn=None, **kw):
    # placeholder; real trace-and-compile lands with the jit module
    if fn is None:
        return lambda f: f
    return fn

"""Automatic dynamic-to-static conversion: rewrite *natural Python*
control flow into the framework's compiled control-flow ops.

Capability analog of the reference's dy2static transformer stack
(``python/paddle/jit/dy2static/transformers/ifelse_transformer.py``,
``.../loop_transformer.py``, orchestrated from
``program_translator.py:780``) — TPU-shaped in mechanism: instead of
rewriting into ConditionalBlock/While ops over a ProgramDesc, the AST
pass rewrites ``if``/``while``/``for range(...)`` statements into calls
to :func:`run_if` / :func:`run_while`, which dispatch per site at
runtime:

- predicate is a **Tensor under jit capture** -> lower onto
  ``static.nn.cond`` / ``static.nn.while_loop`` (ultimately
  ``lax.cond`` / ``lax.while_loop`` / masked ``lax.scan``), keeping the
  branch *inside* the single compiled XLA program;
- predicate is a plain Python value (or we're eager) -> run the plain
  Python control flow, bit-for-bit the original semantics.

That per-site dispatch is the fallback granularity: a site the rewriter
cannot convert (``return``/``break`` inside the block, attribute or
subscript stores whose side effects a traced branch could not replay)
is simply left as plain Python — only *that* statement graph-breaks,
not the whole function.

State handoff uses the reference's get/set-args pattern
(``ifelse_transformer.py`` ``create_get_args_node``/
``create_set_args_node``): names assigned inside a converted block are
hoisted through closure get/set helpers with ``nonlocal`` declarations,
and names possibly unbound at entry are pre-bound to the UNDEF sentinel
(the reference's ``UndefinedVar``).
"""
from __future__ import annotations

import ast
import inspect
import textwrap
import types

__all__ = ["convert_function", "run_if", "run_while", "not_", "and_",
           "or_", "range_args", "range_cond", "UNDEF"]

_HELPER = "__pdtpu_d2s__"


# ==========================================================================
# runtime helpers (the rewritten code calls these)
# ==========================================================================

class _Undef:
    """Sentinel for names unbound at block entry (the reference's
    ``UndefinedVar``). Any use other than rebinding raises."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined local (paddle_tpu dy2static)>"

    def __bool__(self):
        raise NameError(
            "local variable used before assignment (it was only assigned "
            "inside a converted control-flow block that did not run)")


UNDEF = _Undef()


def _is_tensor(v):
    from ..core.tensor import Tensor
    return isinstance(v, Tensor)


def _under_capture():
    from ..core import tensor as tensor_mod
    return tensor_mod._tracker is not None


def _truthy(v):
    if v is UNDEF:
        raise NameError("control-flow predicate uses an unbound local")
    return bool(v)


def ret_value(v):
    """Final-return helper for eliminated early returns when every path
    provably returns: yields the flagged value (UNDEF can only mean the
    value genuinely was ``return None``-less fall-through dead code)."""
    return None if v is UNDEF else v


def ret_final(flag, v):
    """Final-return helper when fall-through is possible: the flag
    decides between the flagged value and ``None``. A TRACED flag makes
    the choice unrepresentable in one compiled program (tensor-vs-None);
    ``bool(flag)`` then raises, which to_static's retry machinery turns
    into an eager fallback — correct, per-call semantics (the reference
    declines these with RETURN_NO_VALUE sentinel checks)."""
    if flag is UNDEF or not flag:
        return None
    return None if v is UNDEF else v


def is_tensor_seq(v):
    """True when ``for x in v`` should iterate rows of a tensor (the
    reference's ``loop_transformer`` tensor-iteration contract)."""
    return _is_tensor(v) and len(getattr(v, "shape", ())) >= 1


def loop_index():
    """Row index for desugared tensor iteration: a traced int32 scalar
    under capture (so the loop lowers to lax control flow with dynamic
    row gathers), a plain int eagerly."""
    if _under_capture():
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        return Tensor(jnp.asarray(0, jnp.int32))
    return 0


def run_if(pred, true_fn, false_fn, get, set_):
    """Runtime dispatch for a rewritten ``if`` statement."""
    if _is_tensor(pred) and _under_capture():
        from ..static.control_flow import cond as static_cond
        init = get()

        # branch thunks restore the frame state they found: they re-run
        # at every (re)trace — probe, lax trace, backward-time vjp — and
        # their nonlocal writes must never outlive the trace (the final
        # set_ below owns the real result)
        def t():
            cur = get()
            try:
                set_(init)
                true_fn()
                return get()
            finally:
                set_(cur)

        def f():
            cur = get()
            try:
                set_(init)
                false_fn()
                return get()
            finally:
                set_(cur)

        out = static_cond(pred, t, f, _undef_fill=UNDEF)
        set_(tuple(out))
        return
    if _truthy(pred):
        true_fn()
    else:
        false_fn()


def run_while(cond_fn, body_fn, get, set_, max_trip_count=None):
    """Runtime dispatch for a rewritten ``while`` (or ``for range``).

    The predicate can TURN INTO a tensor mid-loop (a python-bound
    ``for range`` whose break flag becomes traced on the first
    iteration): iterations run eagerly (prefix-unrolled under capture)
    until the predicate is a tensor, then the REST of the loop lowers
    onto lax control flow with the current state as init.  Eager
    iterations count against ``max_trip_count``: the lowered remainder
    gets the leftover budget (ADVICE r5: the bound is a whole-loop
    bound, not a post-prefix one), floored at 1 — static_while treats
    a bound <= 0 as an explicit OPT-OUT of the scan lowering, so
    flooring at 0 would UNBOUND exactly the loop that exhausted its
    budget."""
    eager_trips = 0
    while True:
        first = cond_fn()
        if _is_tensor(first) and _under_capture():
            break
        if not _truthy(first):
            return
        body_fn()
        eager_trips += 1
    from ..static.control_flow import while_loop as static_while
    if max_trip_count is None:
        # the implicit budget is the flag static_while would read; pull
        # it here so eager trips count against THAT bound too
        from ..core import state as _state
        try:
            max_trip_count = int(
                _state.get_flag("while_grad_max_trip_count"))
        except Exception:
            max_trip_count = None
    if max_trip_count is not None:
        mtc = int(max_trip_count)
        if mtc > 0:  # <= 0 stays as-is: the documented scan opt-out
            max_trip_count = max(mtc - eager_trips, 1)
    init = get()

    def c(*vs):
        cur = get()
        try:
            set_(tuple(vs))
            return cond_fn()
        finally:
            set_(cur)

    def b(*vs):
        cur = get()
        try:
            set_(tuple(vs))
            body_fn()
            return get()
        finally:
            set_(cur)

    out = static_while(c, b, list(init),
                       max_trip_count=max_trip_count,
                       _undef_fill=UNDEF)
    set_(tuple(out))


def not_(v):
    if _is_tensor(v):
        from .. import ops
        return ops.logical_not(v)
    return not v


def and_(a, b_thunk):
    if _is_tensor(a):
        from .. import ops
        return ops.logical_and(a, b_thunk())
    return a and b_thunk()


def or_(a, b_thunk):
    if _is_tensor(a):
        from .. import ops
        return ops.logical_or(a, b_thunk())
    return a or b_thunk()


_SKIP_ROOTS = {"paddle_tpu", "jax", "jaxlib", "numpy", "torch", "flax",
               "optax", "orbax", "chex", "einops", "builtins", "math",
               "functools", "itertools", "typing"}
import weakref

# code-object-keyed caches. Values pin the code object so its id cannot
# be recycled; the per-function-object cache is weak so per-call-created
# closures do not accumulate.
_decline_codes: dict[int, object] = {}       # id(code) -> code
_conv_fns: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _skip_function(fn):
    mod = (getattr(fn, "__module__", "") or "")
    if mod.split(".")[0] in _SKIP_ROOTS:
        return True
    f = getattr(fn.__code__, "co_filename", "")
    return "site-packages" in f or "/lib/python" in f


def _convert_cached(fn):
    cid = id(fn.__code__)
    if cid in _decline_codes:
        return None
    try:
        conv = _conv_fns.get(fn)
    except TypeError:
        conv = None
    if conv is not None:
        return conv
    conv = convert_function(fn)
    if conv is None:
        _decline_codes[cid] = fn.__code__
        return None
    try:
        _conv_fns[fn] = conv
    except TypeError:
        pass
    return conv


def call(f):
    """Call-site wrapper (the reference's ``convert_call``,
    ``jit/dy2static/convert_call_func.py``): convert user callables
    recursively so control flow inside callees (e.g. a Layer's
    ``forward``) lowers too; framework/library functions pass through."""
    try:
        from ..nn import Layer
        if isinstance(f, Layer):
            fwd = getattr(type(f), "forward", None)
            if isinstance(fwd, types.FunctionType) \
                    and not _skip_function(fwd):
                conv = _convert_cached(fwd)
                if conv is not None:
                    return _LayerCallProxy(f, types.MethodType(conv, f))
            return f
        tgt = f.__func__ if isinstance(f, types.MethodType) else f
        if not isinstance(tgt, types.FunctionType) or _skip_function(tgt):
            return f
        conv = _convert_cached(tgt)
        if conv is None:
            return f
        if isinstance(f, types.MethodType):
            return types.MethodType(conv, f.__self__)
        return conv
    except Exception:
        return f


class _LayerCallProxy:
    """Invoke a Layer through its real ``__call__`` (pre/post hooks run)
    with the converted ``forward`` shadowed in the instance dict for the
    duration of the call."""

    __slots__ = ("_layer", "_fwd")

    def __init__(self, layer, fwd):
        self._layer = layer
        self._fwd = fwd

    def __call__(self, *args, **kwargs):
        layer = self._layer
        had = "forward" in layer.__dict__
        prev = layer.__dict__.get("forward")
        layer.__dict__["forward"] = self._fwd
        try:
            return layer(*args, **kwargs)
        finally:
            if had:
                layer.__dict__["forward"] = prev
            else:
                layer.__dict__.pop("forward", None)


def range_args(*a):
    if len(a) == 1:
        return (0, a[0], 1)
    if len(a) == 2:
        return (a[0], a[1], 1)
    if len(a) == 3:
        return tuple(a)
    raise TypeError(f"range expected 1-3 arguments, got {len(a)}")


def range_cond(i, stop, step):
    if isinstance(step, (int, float)):
        if step == 0:
            raise ValueError("range() arg 3 must not be zero")
        return i < stop if step > 0 else i > stop
    from .. import ops
    return ops.logical_or(ops.logical_and(step > 0, i < stop),
                          ops.logical_and(step < 0, i > stop))


# ==========================================================================
# AST analysis
# ==========================================================================

_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef, ast.ListComp, ast.SetComp, ast.DictComp,
                   ast.GeneratorExp)


def _walk_in_scope(node):
    """ast.walk that does not descend into nested scopes."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, _SCOPE_BARRIERS):
                stack.append(child)


def _target_names(t, out):
    if isinstance(t, ast.Name):
        out.add(t.id)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            _target_names(e, out)
    elif isinstance(t, ast.Starred):
        _target_names(t.value, out)
    # Attribute/Subscript targets are object mutations, not name binds


def _assigned_names(stmts):
    """Names bound by the statements (this scope only, ordered)."""
    names: set[str] = set()
    for s in stmts:
        for n in _walk_in_scope(s):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    _target_names(t, names)
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                _target_names(n.target, names)
            elif isinstance(n, ast.For):
                _target_names(n.target, names)
            elif isinstance(n, ast.NamedExpr):
                _target_names(n.target, names)
            elif isinstance(n, ast.withitem) and n.optional_vars:
                _target_names(n.optional_vars, names)
            elif isinstance(n, ast.Import):
                for al in n.names:
                    names.add((al.asname or al.name).split(".")[0])
            elif isinstance(n, ast.ImportFrom):
                for al in n.names:
                    names.add(al.asname or al.name)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.add(n.name)
    return sorted(names)


def _child_blocks(s, depth):
    """Child statement blocks of ``s`` with the loop depth they sit at
    (+1 inside a loop body — break/continue there bind to that loop).
    Nested defs are new scopes and are not yielded."""
    if isinstance(s, (ast.For, ast.While, ast.AsyncFor)):
        yield s.body, depth + 1
        yield s.orelse, depth
    elif isinstance(s, ast.If):
        yield s.body, depth
        yield s.orelse, depth
    elif isinstance(s, (ast.With, ast.AsyncWith)):
        yield s.body, depth
    elif isinstance(s, ast.Try):
        yield s.body, depth
        yield s.orelse, depth
        yield s.finalbody, depth
        for h in s.handlers:
            yield h.body, depth


def _has_escape(stmts, *, loop_ctx=False):
    """True if converting these statements into a nested function would
    change semantics: return/yield anywhere in this scope, or
    break/continue that binds to a loop OUTSIDE the statements
    (``loop_ctx``: the statements themselves are a loop body, so depth-0
    break/continue escapes), or ``del`` of a name."""

    def walk(ss, depth):
        for s in ss:
            if isinstance(s, (ast.Return, ast.Delete)):
                return True
            if isinstance(s, (ast.Break, ast.Continue)) and depth == 0:
                return True
            for child_list, d in _child_blocks(s, depth):
                if walk(child_list, d):
                    return True
            for n in _walk_in_scope(s):
                if isinstance(n, (ast.Yield, ast.YieldFrom, ast.Await)):
                    return True
        return False

    return walk(stmts, 0)


def _has_mangled_names(tree):
    for n in ast.walk(tree):
        if isinstance(n, ast.Attribute) and n.attr.startswith("__") \
                and not n.attr.endswith("__"):
            return True
    return False


class _DeclScanner(ast.NodeVisitor):
    def __init__(self):
        self.globals: set[str] = set()
        self.nonlocals: set[str] = set()

    def visit_Global(self, node):
        self.globals.update(node.names)

    def visit_Nonlocal(self, node):
        self.nonlocals.update(node.names)


# ==========================================================================
# AST rewriting
# ==========================================================================

class _PredRewriter(ast.NodeTransformer):
    """Convert ``not``/``and``/``or`` inside a predicate expression into
    tensor-aware helpers (reference ``logical_transformer.py``). Lazy
    evaluation of and/or tails is preserved via thunks."""

    def visit_UnaryOp(self, node):
        node = self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _call(_HELPER + ".not_", [node.operand])
        return node

    def visit_BoolOp(self, node):
        node = self.generic_visit(node)
        fn = ".and_" if isinstance(node.op, ast.And) else ".or_"
        out = node.values[0]
        for v in node.values[1:]:
            out = _call(_HELPER + fn, [out, _thunk(v)])
        return out

    # do not descend into new scopes inside the predicate
    def visit_Lambda(self, node):
        return node


def _call(dotted, args):
    mod, attr = dotted.rsplit(".", 1)
    return ast.Call(
        func=ast.Attribute(value=ast.Name(id=mod, ctx=ast.Load()),
                           attr=attr, ctx=ast.Load()),
        args=args, keywords=[])


def _thunk(expr):
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                           kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]),
        body=expr)


def _parse_stmts(src):
    return ast.parse(textwrap.dedent(src)).body


def _visit_body(transformer, fndef):
    """Apply a scope-barriered NodeTransformer to ``fndef``'s body
    statements (visiting the FunctionDef itself would hit the barrier)."""
    new = []
    for s in fndef.body:
        r = transformer.visit(s)
        new.extend(r if isinstance(r, list) else [r])
    fndef.body = new


def _is_range_for(node):
    """A ``for NAME in range(...)`` loop the desugar pass can handle."""
    return (not node.orelse
            and isinstance(node.target, ast.Name)
            and isinstance(node.iter, ast.Call)
            and isinstance(node.iter.func, ast.Name)
            and node.iter.func.id == "range"
            and not node.iter.keywords
            and not any(isinstance(a, ast.Starred)
                        for a in node.iter.args))


def _for_range_desugar(node, prefix):
    """``for t in range(...)`` -> (setup stmts, equivalent While node).
    The loop target is pre-bound to start so it is never UNDEF in the
    carry (documented divergence from CPython: an empty range leaves the
    target bound to start instead of unbound)."""
    r, i = f"{prefix}_range", f"{prefix}_i"
    setup = _parse_stmts(
        f"{r} = {_HELPER}.range_args({{args}})\n{i} = {r}[0]\n"
        f"{node.target.id} = {r}[0]")
    # splice real arg expressions into the range_args call
    setup[0].value.args = list(node.iter.args)
    incr = _parse_stmts(f"{i} = {i} + {r}[2]")
    # the increment must run even on `continue` (for-loop semantics):
    # the tag keeps it out of _BreakContinueEliminator's guards
    incr[0]._pdtpu_loop_incr = True
    while_node = ast.While(
        test=_call(_HELPER + ".range_cond", [
            ast.Name(id=i, ctx=ast.Load()),
            _sub(r, 1), _sub(r, 2)]),
        body=([ast.Assign(targets=[node.target],
                          value=ast.Name(id=i, ctx=ast.Load()))]
              + node.body
              + incr),
        orelse=[])
    for s in setup + [while_node]:
        ast.copy_location(s, node)
        ast.fix_missing_locations(s)
    return setup, while_node


# ==========================================================================
# escape elimination: return/break/continue -> flag form, tensor for-each
#
# Capability analog of the reference's
# ``jit/dy2static/transformers/return_transformer.py`` (early return ->
# return-value/flag pair), ``break_continue_transformer.py`` (break ->
# loop-condition flag + guards) and ``loop_transformer.py`` (iteration
# over a tensor's rows). Runs BEFORE the main rewriter so the resulting
# if/while sites are escape-free and convert normally; statements the
# passes cannot handle (escapes inside try/with, returns nested in
# python-iterable loops) are simply left as real escapes — the rewriter
# then declines just those sites (mixed flag/real form is safe: a real
# ``return`` still returns directly, flagged paths flow to the appended
# final return).
# ==========================================================================

_RETF, _RETV = "__pt_retf", "__pt_retv"


def _not_flags(names):
    expr = ast.Name(id=names[0], ctx=ast.Load())
    if len(names) > 1:
        expr = ast.BoolOp(op=ast.Or(), values=[
            ast.Name(id=n, ctx=ast.Load()) for n in names])
    return ast.UnaryOp(op=ast.Not(), operand=expr)


def _guard_if(flags, body):
    return ast.If(test=_not_flags(flags), body=body or [ast.Pass()],
                  orelse=[])


def _scope_has_return(stmts):
    def walk(ss, depth):
        for s in ss:
            if isinstance(s, ast.Return):
                return True
            for blk, d in _child_blocks(s, depth):
                if walk(blk, d):
                    return True
        return False
    return walk(stmts, 0)


class _ForEachDesugar(ast.NodeTransformer):
    """``for x in EXPR`` (non-range): runtime-dispatch between row
    iteration over a tensor's leading axis (convertible; lowers with a
    dynamic row gather under capture) and the original Python loop."""

    def __init__(self):
        self.n = 0

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def visit_For(self, node):
        self.generic_visit(node)
        if node.orelse or not isinstance(node.target, ast.Name) \
                or _is_range_for(node):
            return node
        # break/continue inside try/with: _BreakContinueEliminator will
        # decline them as fragile, and a REAL continue in the generated
        # while would skip the index increment (infinite loop) — keep
        # the original for (Tensor.__iter__ handles tensors eagerly)
        if _loop_escape_kinds(node.body)[2]:
            return node
        import copy
        k = self.n
        self.n += 1
        seq, n_, i = (f"__ptfe{k}_seq", f"__ptfe{k}_n", f"__ptfe{k}_i")
        stmts = _parse_stmts(
            f"{seq} = None\n"
            f"if {_HELPER}.is_tensor_seq({seq}):\n"
            f"    {n_} = {seq}.shape[0]\n"
            f"    {i} = {_HELPER}.loop_index()\n"
            f"    while {i} < {n_}:\n"
            f"        {node.target.id} = {seq}[{i}]\n"
            f"        pass\n"
            f"        {i} = {i} + 1\n"
            f"else:\n"
            f"    pass\n")
        stmts[0].value = node.iter
        ifn = stmts[1]
        wl = ifn.body[2]
        wl.body[-1]._pdtpu_loop_incr = True  # runs even on `continue`
        wl.body[1:2] = node.body
        ifn.orelse = [ast.For(target=node.target,
                              iter=ast.Name(id=seq, ctx=ast.Load()),
                              body=copy.deepcopy(node.body), orelse=[])]
        for s in stmts:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return stmts


def _eliminate_returns(fndef):
    """Early returns -> ``__pt_retf``/``__pt_retv`` flag form. Only
    returns reachable through convertible structure are transformed;
    anything else stays a real return (safe in mixed form)."""
    if not _scope_has_return(fndef.body):
        return False
    # nothing to do when every return already sits at function top level
    if not any(_scope_has_return([s]) for s in fndef.body
               if not isinstance(s, ast.Return)):
        return False
    changed = [0]
    counter = [0]

    def setret(s):
        val = s.value if s.value is not None else ast.Constant(value=None)
        a1 = ast.Assign(targets=[ast.Name(id=_RETV, ctx=ast.Store())],
                        value=val)
        out = [a1] + _parse_stmts(f"{_RETF} = True")
        for n in out:
            ast.copy_location(n, s)
            ast.fix_missing_locations(n)
        changed[0] += 1
        return out

    def xform(stmts):
        """-> (new_stmts, may_return, always_returns)."""
        out = []
        for idx, s in enumerate(stmts):
            rest_src = stmts[idx + 1:]
            if isinstance(s, ast.Return):
                out.extend(setret(s))
                return out, True, True          # rest is dead
            if isinstance(s, ast.If):
                b, mb, ab = xform(s.body)
                e, me, ae = xform(s.orelse)
                if not (mb or me):
                    out.append(s)
                    continue
                s.body, s.orelse = (b or [ast.Pass()]), e
                rest, _, ar = (xform(rest_src) if rest_src
                               else ([], False, False))
                if ab and ae:
                    out.append(s)               # both branches return
                    return out, True, True
                if ab and not s.orelse:
                    # continuation folding: `if c: return a; REST` ->
                    # `if c: <flags> else: REST` keeps retv bound on
                    # both sides (no dummy fill needed)
                    s.orelse = rest
                    out.append(s)
                    return out, True, ar
                if ae and not ab:
                    s.body = s.body + (rest if not mb
                                       else [_guard_if([_RETF], rest)])
                    out.append(s)
                    return out, True, ar
                out.append(s)
                if rest:
                    out.append(_guard_if([_RETF], rest))
                return out, True, False
            if isinstance(s, (ast.While, ast.For)) \
                    and _scope_has_return([s]):
                if isinstance(s, ast.For):
                    if not _is_range_for(s):
                        out.append(s)
                        if not _breakify_for(s, changed):
                            # only unguardable (deep, real) returns
                            # inside: nothing flagged, so trailing
                            # statements need no guard either
                            continue
                    else:
                        k = counter[0]
                        counter[0] += 1
                        setup, wl = _for_range_desugar(s, f"__ptr{k}")
                        nb, mb, _ = xform(wl.body[1:-1])
                        wl.body[1:-1] = nb
                        if mb:
                            wl.test = ast.BoolOp(op=ast.And(), values=[
                                _not_flags([_RETF]), wl.test])
                            ast.fix_missing_locations(wl)
                        out.extend(setup)
                        out.append(wl)
                else:
                    # trailing ``_pdtpu_loop_incr``-tagged statements (a
                    # desugared for-each's index increment) must STAY the
                    # loop tail: folding them into a return-If's orelse
                    # would hide the tag from _BreakContinueEliminator's
                    # tail scan, which then wraps the increment in the
                    # continue guard — the index stops advancing on
                    # continue iterations (ADVICE r5 high: infinite loop
                    # on continue + later return)
                    n_tail = 0
                    while n_tail < len(s.body) and getattr(
                            s.body[-1 - n_tail], "_pdtpu_loop_incr",
                            False):
                        n_tail += 1
                    cut = len(s.body) - n_tail
                    nb, mb, _ = xform(s.body[:cut])
                    if mb:
                        s.body = nb + s.body[cut:]
                        s.test = ast.BoolOp(op=ast.And(), values=[
                            _not_flags([_RETF]), s.test])
                        ast.fix_missing_locations(s)
                    out.append(s)
                rest, _, _ = (xform(rest_src) if rest_src
                              else ([], False, False))
                if rest:
                    out.append(_guard_if([_RETF], rest))
                return out, True, False
            # With/Try (and anything else): real returns inside stay real
            out.append(s)
        return out, False, False

    body2, _may, always = xform(fndef.body)
    if not changed[0]:
        return False
    prologue = _parse_stmts(
        f"{_RETF} = False\n{_RETV} = {_HELPER}.UNDEF")
    # fall-through possible -> the flag must decide value-vs-None (and a
    # traced flag correctly forces the eager fallback); all paths return
    # -> plain value extraction, stays compiled
    epilogue = _parse_stmts(
        f"return {_HELPER}.ret_value({_RETV})" if always else
        f"return {_HELPER}.ret_final({_RETF}, {_RETV})")
    for s in prologue + epilogue:
        ast.copy_location(s, fndef.body[0] if fndef.body else fndef)
        ast.fix_missing_locations(s)
    fndef.body = prologue + body2 + epilogue
    return True


def _breakify_for(node, changed):
    """Returns inside a python-iterable ``for``: flag + real ``break``
    (the loop itself stays plain Python). Only depth-0 returns directly
    in the body or under plain ``if`` are transformed; deeper ones stay
    real returns. Returns the number of returns transformed (also added
    to ``changed`` so the flag prologue/epilogue is guaranteed whenever
    the tree was mutated)."""
    n_repl = [0]

    def walk(stmts, depth):
        out = []
        for s in stmts:
            if isinstance(s, ast.Return) and depth == 0:
                val = (s.value if s.value is not None
                       else ast.Constant(value=None))
                a1 = ast.Assign(
                    targets=[ast.Name(id=_RETV, ctx=ast.Store())],
                    value=val)
                repl = [a1] + _parse_stmts(f"{_RETF} = True") \
                    + [ast.Break()]
                for n in repl:
                    ast.copy_location(n, s)
                    ast.fix_missing_locations(n)
                out.extend(repl)
                n_repl[0] += 1
                return out                      # rest of block is dead
            if isinstance(s, ast.If) and depth == 0:
                s.body = walk(s.body, depth)
                s.orelse = walk(s.orelse, depth)
            out.append(s)
        return out

    node.body = walk(node.body, 0)
    changed[0] += n_repl[0]
    return n_repl[0]


def _loop_escape_kinds(stmts):
    """(has_break, has_continue) binding to the loop whose body is
    ``stmts``; also True-third when any sits inside try/with (fragile —
    guard insertion there is out of scope)."""
    hb = hc = fragile = False

    def walk(ss, depth, frag):
        nonlocal hb, hc, fragile
        for s in ss:
            if isinstance(s, (ast.Break, ast.Continue)) and depth == 0:
                if isinstance(s, ast.Break):
                    hb = True
                else:
                    hc = True
                fragile = fragile or frag
            f2 = frag or isinstance(s, (ast.Try, ast.With, ast.AsyncWith))
            for blk, d in _child_blocks(s, depth):
                walk(blk, d, f2)

    walk(stmts, 0, False)
    return hb, hc, fragile


def _guard_break_continue(stmts, brk, cont, flags):
    """-> (new_stmts, may_escape): replace depth-0 break/continue with
    flag sets and guard trailing statements."""
    out = []
    for idx, s in enumerate(stmts):
        if isinstance(s, ast.Break):
            out.extend(_parse_stmts(f"{brk} = True"))
            return out, True                    # rest of block is dead
        if isinstance(s, ast.Continue):
            out.extend(_parse_stmts(f"{cont} = True"))
            return out, True
        if isinstance(s, ast.If):
            b, mb = _guard_break_continue(s.body, brk, cont, flags)
            e, me = _guard_break_continue(s.orelse, brk, cont, flags)
            if mb or me:
                s.body, s.orelse = (b or [ast.Pass()]), e
                ast.fix_missing_locations(s)
                out.append(s)
                rest = stmts[idx + 1:]
                if rest:
                    r, _ = _guard_break_continue(rest, brk, cont, flags)
                    g = _guard_if(flags, r)
                    ast.copy_location(g, s)
                    ast.fix_missing_locations(g)
                    out.append(g)
                return out, True
        # nested loops own their break/continue; try/with pre-screened
        out.append(s)
    return out, False


class _BreakContinueEliminator(ast.NodeTransformer):
    """break/continue in while / for-range bodies -> loop-condition
    flags + guards (innermost loops first)."""

    def __init__(self):
        self.n = 0
        self.changed = 0

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def _loop(self, node):
        """Transform one While node. Trailing statements tagged
        ``_pdtpu_loop_incr`` (a desugared for's index increment) must
        run even on continue, so they stay outside the guards."""
        if node.orelse:
            return [node]
        n_tail = 0
        while n_tail < len(node.body) and getattr(
                node.body[-1 - n_tail], "_pdtpu_loop_incr", False):
            n_tail += 1
        cut = len(node.body) - n_tail
        main = node.body[:cut]
        hb, hc, fragile = _loop_escape_kinds(main)
        if not (hb or hc) or fragile:
            return [node]
        k = self.n
        self.n += 1
        brk, cont = f"__ptbc{k}_brk", f"__ptbc{k}_cont"
        flags = ([brk] if hb else []) + ([cont] if hc else [])
        new_main, _ = _guard_break_continue(main, brk, cont, flags)
        reset = _parse_stmts(f"{cont} = False") if hc else []
        node.body = reset + new_main + node.body[cut:]
        if hb:
            node.test = ast.BoolOp(op=ast.And(), values=[
                _not_flags([brk]), node.test])
        pre = _parse_stmts(
            "\n".join(f"{f} = False" for f in flags))
        for s in pre + [node]:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        self.changed += 1
        return pre + [node]

    def visit_While(self, node):
        self.generic_visit(node)
        return self._loop(node)

    def visit_For(self, node):
        self.generic_visit(node)
        if not _is_range_for(node):
            return node                         # python-iterable: real
        hb, hc, fragile = _loop_escape_kinds(node.body)
        if not (hb or hc) or fragile:
            return node
        setup, wl = _for_range_desugar(node, f"__ptbc{self.n}f")
        return setup + self._loop(wl)


class _Rewriter(ast.NodeTransformer):
    def __init__(self, declared_globals, declared_nonlocals,
                 on_decline=None):
        self.globals = declared_globals
        self.nonlocals = declared_nonlocals
        self.n = 0
        self.converted_sites = 0
        self.wrapped_calls = 0
        # diagnostics hook: called with (node, reason) at every site the
        # rewriter leaves as plain Python (the silent graph breaks)
        self.on_decline = on_decline

    def _declined(self, node, reason):
        if self.on_decline is not None:
            self.on_decline(node, reason)

    # ---- scope barriers: transform only the target function's scope
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    # ---- recursive callee conversion (reference convert_call)
    _CALL_SKIP = frozenset({
        "range", "len", "print", "super", "isinstance", "issubclass",
        "type", "int", "float", "bool", "str", "tuple", "list", "dict",
        "set", "frozenset", "enumerate", "zip", "map", "filter", "getattr",
        "setattr", "hasattr", "repr", "id", "abs", "min", "max", "sum",
        "sorted", "reversed", "any", "all", "iter", "next", "vars",
        "locals", "globals",
    })

    def visit_Call(self, node):
        node = self.generic_visit(node)
        if isinstance(node.func, ast.Name) \
                and node.func.id in self._CALL_SKIP:
            return node
        node.func = _call(_HELPER + ".call", [node.func])
        self.wrapped_calls += 1
        return node

    # ---------------------------------------------------------------- util
    def _decls(self, names):
        """nonlocal/global declaration statements for generated fns."""
        g = [n for n in names if n in self.globals]
        nl = [n for n in names if n not in self.globals]
        out = []
        if nl:
            out.append(ast.Nonlocal(names=nl))
        if g:
            out.append(ast.Global(names=g))
        return out

    def _mkfn(self, name, body, state_names, args=None):
        body = self._decls(state_names) + (body or [ast.Pass()])
        if not body:
            body = [ast.Pass()]
        return ast.FunctionDef(
            name=name,
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=a) for a in (args or [])],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=body, decorator_list=[], returns=None)

    def _guards(self, names):
        """try/except pre-binding for every state name (makes the name a
        bound local so nonlocal chains resolve, and UNDEF-fills names
        unbound at entry)."""
        out = []
        for n in names:
            if n in self.globals:
                continue  # guards would shadow the global with a local
            out.extend(_parse_stmts(
                f"try:\n    {n}\n"
                f"except (NameError, UnboundLocalError):\n"
                f"    {n} = {_HELPER}.UNDEF"))
        return out

    def _getset(self, idx, names):
        tup = "(" + ", ".join(names) + ("," if names else "") + ")"
        get = self._mkfn(f"__pt{idx}_get",
                         _parse_stmts(f"return {tup}"), [])
        set_body = (_parse_stmts(f"{tup} = __pt_vals") if names
                    else [ast.Pass()])
        set_ = self._mkfn(f"__pt{idx}_set", set_body, names,
                          args=["__pt_vals"])
        return get, set_

    def _state_names(self, *stmt_lists):
        names = set()
        for ss in stmt_lists:
            names.update(_assigned_names(ss))
        # generated helper FUNCTIONS are always (re)defined before use in
        # their own scope — never cross-branch state. Generated loop
        # counters (__ptN_i) stay: they are genuine carry state.
        import re
        drop = re.compile(r"__pt\d+_(true|false|get|set|cond|body)$")
        return sorted(n for n in names if not drop.match(n))

    # ------------------------------------------------------------------ if
    def visit_If(self, node):
        node = self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            self._declined(node, "`if` block contains an escape "
                           "(return/break/del/yield) the elimination "
                           "passes could not rewrite")
            return node
        idx = self.n
        self.n += 1
        names = self._state_names(node.body, node.orelse)
        test = _PredRewriter().visit(node.test)
        tf = self._mkfn(f"__pt{idx}_true", node.body, names)
        ff = self._mkfn(f"__pt{idx}_false", node.orelse, names)
        get, set_ = self._getset(idx, names)
        call = ast.Expr(value=_call(_HELPER + ".run_if", [
            test,
            ast.Name(id=tf.name, ctx=ast.Load()),
            ast.Name(id=ff.name, ctx=ast.Load()),
            ast.Name(id=f"__pt{idx}_get", ctx=ast.Load()),
            ast.Name(id=f"__pt{idx}_set", ctx=ast.Load()),
        ]))
        out = self._guards(names) + [tf, ff, get, set_, call]
        for s in out:
            ast.copy_location(s, node)
        self.converted_sites += 1
        return out

    # --------------------------------------------------------------- while
    def visit_While(self, node):
        node = self.generic_visit(node)
        return self._convert_while(node)

    def _convert_while(self, node):
        if node.orelse or _has_escape(node.body, loop_ctx=True):
            self._declined(node, "`while` has an else clause or an "
                           "escape (return/break/del/yield) the "
                           "elimination passes could not rewrite")
            return node
        idx = self.n
        self.n += 1
        names = self._state_names(node.body)
        test = _PredRewriter().visit(node.test)
        cf = self._mkfn(f"__pt{idx}_cond",
                        [ast.Return(value=test)], [])
        bf = self._mkfn(f"__pt{idx}_body", node.body, names)
        get, set_ = self._getset(idx, names)
        call = ast.Expr(value=_call(_HELPER + ".run_while", [
            ast.Name(id=cf.name, ctx=ast.Load()),
            ast.Name(id=bf.name, ctx=ast.Load()),
            ast.Name(id=f"__pt{idx}_get", ctx=ast.Load()),
            ast.Name(id=f"__pt{idx}_set", ctx=ast.Load()),
        ]))
        out = self._guards(names) + [cf, bf, get, set_, call]
        for s in out:
            ast.copy_location(s, node)
        self.converted_sites += 1
        return out

    # ----------------------------------------------------------------- for
    def visit_For(self, node):
        node = self.generic_visit(node)
        if not _is_range_for(node) or _has_escape(node.body, loop_ctx=True):
            if _is_range_for(node):
                self._declined(node, "`for range(...)` body contains an "
                               "escape the elimination passes could not "
                               "rewrite")
            return node
        idx = self.n
        self.n += 1
        setup, while_node = _for_range_desugar(node, f"__pt{idx}")
        out = self._convert_while(while_node)
        if out is while_node:  # inner conversion declined; keep plain for
            return node
        return setup + out


def _sub(name, i):
    return ast.Subscript(value=ast.Name(id=name, ctx=ast.Load()),
                         slice=ast.Constant(value=i), ctx=ast.Load())


# ==========================================================================
# entry point
# ==========================================================================

def _emit_graph_break_diags(fn, items):
    """Report conversion-decline sites ((code, rel_line, message) with
    lines relative to the dedented source) through the analysis
    registry — the graph breaks that used to degrade silently. Honors
    the analysis mode flag, ``# pdtpu: noqa`` pragmas and
    ``@analysis.suppress`` tags; a broken analysis import never breaks
    conversion."""
    if not items:
        return
    try:
        from .. import analysis
        from ..analysis.registry import active_suppressions
        if analysis.mode() == "off":
            return
        sup = frozenset(getattr(fn, "__pdtpu_suppress__", ())) | \
            active_suppressions()
        try:
            lines, start = inspect.getsourcelines(fn)
        except (OSError, TypeError):
            lines, start = [], fn.__code__.co_firstlineno
        filename = getattr(fn.__code__, "co_filename", "<unknown>")
        diags = []
        for code, rel, msg in items:
            spec = analysis.REGISTRY.get(code)
            if spec is None or code in sup:
                continue
            src_line = lines[rel - 1] if 0 < rel <= len(lines) else ""
            if analysis.pragma_suppressed(src_line, code):
                continue
            diags.append(analysis.Diagnostic(
                code=code, severity=spec.severity, message=msg,
                file=filename, line=start - 1 + rel))
    except Exception:
        return
    # outside the guard: in error mode report() raises, and that must
    # propagate to the caller rather than be swallowed
    analysis.report(diags, where=getattr(fn, "__name__", ""))

# id(code) -> (code_exec, fndef_name, has_factory); pins the original
# code object (key stability) AND the compiled artifact, so fresh
# function objects sharing a code (per-call closures) skip the AST
# pipeline and only re-exec + rebind cells
_artifact_cache: dict[int, tuple] = {}


def _instantiate(fn, code, fndef_name, has_factory, gns):
    loc: dict = {}
    exec(code, gns, loc)
    if has_factory:
        inner_code = None
        for const in loc["__pt_factory"].__code__.co_consts:
            if isinstance(const, types.CodeType) \
                    and const.co_name == fndef_name:
                inner_code = const
                break
        if inner_code is None:
            return None
        cellmap = dict(zip(fn.__code__.co_freevars, fn.__closure__ or ()))
        try:
            closure = tuple(cellmap[v] for v in inner_code.co_freevars)
        except KeyError:
            return None
        new = types.FunctionType(inner_code, gns, fn.__name__,
                                 fn.__defaults__, closure)
    else:
        new = loc[fndef_name]
        new.__defaults__ = fn.__defaults__
    new.__kwdefaults__ = fn.__kwdefaults__
    new.__dict__.update(fn.__dict__)
    new.__wrapped_original__ = fn
    return new


def convert_function(fn):
    """AST-convert ``fn``; returns the converted function, or ``None``
    when nothing was (or could be) converted (caller keeps the
    original). Mirrors ``program_translator.py:780``'s convert-on-entry,
    collapsed to one pass since our per-site dispatch happens at
    runtime."""
    if not isinstance(fn, types.FunctionType):
        return None
    import sys
    cached = _artifact_cache.get(id(fn.__code__))
    if cached is not None:
        gns = fn.__globals__
        gns.setdefault(_HELPER, sys.modules[__name__])
        return _instantiate(fn, *cached[:3], gns)
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    if not tree.body or not isinstance(tree.body[0], ast.FunctionDef):
        return None
    fndef = tree.body[0]
    if fndef.name != fn.__name__:
        return None
    if _has_mangled_names(fndef):
        # source-level name mangling won't survive re-exec
        _emit_graph_break_diags(fn, [(
            "PDT107", fndef.lineno,
            "dy2static declined: __name-mangled attribute access does "
            "not survive re-exec; tensor control flow stays eager")])
        return None
    from ..analysis.registry import decorator_name
    for dec in fndef.decorator_list:
        # stripping an unknown decorator would change behavior (and a
        # wrapping decorator means ``fn`` isn't this source anyway);
        # analysis.suppress only tags the function, so it is safe
        name = decorator_name(dec)
        if name not in ("to_static", "suppress"):
            _emit_graph_break_diags(fn, [(
                "PDT107", dec.lineno,
                f"dy2static declined: decorator @{name or '<expr>'} "
                f"cannot be stripped; tensor control flow stays eager")])
            return None
    decls = _DeclScanner()
    decls.visit(fndef)
    if decls.nonlocals:
        # re-exec'd nonlocal writes would not share cells
        _emit_graph_break_diags(fn, [(
            "PDT107", fndef.lineno,
            f"dy2static declined: nonlocal "
            f"({', '.join(sorted(decls.nonlocals))}) writes cannot share "
            f"closure cells after re-exec; tensor control flow stays "
            f"eager")])
        return None

    # escape elimination first (reference transformer ordering:
    # loop_transformer's tensor iteration, return_transformer,
    # break_continue_transformer) so the rewriter sees escape-free
    # sites. The transformers barrier on nested defs, so they are
    # applied to the target function's body statements, not the
    # FunctionDef node itself.
    _visit_body(_ForEachDesugar(), fndef)
    _eliminate_returns(fndef)
    _visit_body(_BreakContinueEliminator(), fndef)
    ast.fix_missing_locations(fndef)

    declines: list[tuple] = []
    rw = _Rewriter(decls.globals, decls.nonlocals,
                   on_decline=lambda node, reason: declines.append(
                       ("PDT105", node.lineno,
                        f"graph break: {reason}; the site runs as plain "
                        f"Python (a tensor predicate here breaks the "
                        f"capture)")))
    new_body = []
    for s in fndef.body:
        r = rw.visit(s)
        new_body.extend(r if isinstance(r, list) else [r])
    fndef.body = new_body
    _emit_graph_break_diags(fn, declines)
    if not rw.converted_sites and not rw.wrapped_calls:
        return None
    fndef.decorator_list = []

    freevars = fn.__code__.co_freevars
    if freevars:
        # wrap in a factory that pre-binds the freevar names so the inner
        # def compiles them as free variables again; then rebuild the
        # function around the ORIGINAL closure cells (late rebinding in
        # the defining scope stays visible)
        factory = ast.FunctionDef(
            name="__pt_factory",
            args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=([ast.Assign(
                targets=[ast.Name(id=v, ctx=ast.Store())
                         for v in freevars],
                value=ast.Constant(value=None))]
                + [fndef,
                   ast.Return(value=ast.Name(id=fndef.name,
                                             ctx=ast.Load()))]),
            decorator_list=[], returns=None)
        mod = ast.Module(body=[factory], type_ignores=[])
    else:
        mod = ast.Module(body=[fndef], type_ignores=[])
    ast.fix_missing_locations(mod)

    filename = f"<dy2static {getattr(fn.__code__, 'co_filename', '?')}:" \
               f"{fn.__code__.co_firstlineno}>"
    code = compile(mod, filename, "exec")
    # 4th slot pins the original code object so the cache key id cannot
    # be recycled by a new code object at the same address
    _artifact_cache[id(fn.__code__)] = (code, fndef.name, bool(freevars),
                                        fn.__code__)

    gns = fn.__globals__
    gns.setdefault(_HELPER, sys.modules[__name__])
    return _instantiate(fn, code, fndef.name, bool(freevars), gns)

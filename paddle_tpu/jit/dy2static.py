"""Automatic dynamic-to-static conversion: rewrite *natural Python*
control flow into the framework's compiled control-flow ops.

Capability analog of the reference's dy2static transformer stack
(``python/paddle/jit/dy2static/transformers/ifelse_transformer.py``,
``.../loop_transformer.py``, orchestrated from
``program_translator.py:780``) — TPU-shaped in mechanism: instead of
rewriting into ConditionalBlock/While ops over a ProgramDesc, the AST
pass rewrites ``if``/``while``/``for range(...)`` statements into calls
to :func:`run_if` / :func:`run_while`, which dispatch per site at
runtime:

- predicate is a **Tensor under jit capture** -> lower onto
  ``static.nn.cond`` / ``static.nn.while_loop`` (ultimately
  ``lax.cond`` / ``lax.while_loop`` / masked ``lax.scan``), keeping the
  branch *inside* the single compiled XLA program;
- predicate is a plain Python value (or we're eager) -> run the plain
  Python control flow, bit-for-bit the original semantics.

That per-site dispatch is the fallback granularity: a site the rewriter
cannot convert (``return``/``break`` inside the block, attribute or
subscript stores whose side effects a traced branch could not replay)
is simply left as plain Python — only *that* statement graph-breaks,
not the whole function.

State handoff uses the reference's get/set-args pattern
(``ifelse_transformer.py`` ``create_get_args_node``/
``create_set_args_node``): names assigned inside a converted block are
hoisted through closure get/set helpers with ``nonlocal`` declarations,
and names possibly unbound at entry are pre-bound to the UNDEF sentinel
(the reference's ``UndefinedVar``).
"""
from __future__ import annotations

import ast
import inspect
import textwrap
import types

__all__ = ["convert_function", "run_if", "run_while", "not_", "and_",
           "or_", "range_args", "range_cond", "UNDEF"]

_HELPER = "__pdtpu_d2s__"


# ==========================================================================
# runtime helpers (the rewritten code calls these)
# ==========================================================================

class _Undef:
    """Sentinel for names unbound at block entry (the reference's
    ``UndefinedVar``). Any use other than rebinding raises."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined local (paddle_tpu dy2static)>"

    def __bool__(self):
        raise NameError(
            "local variable used before assignment (it was only assigned "
            "inside a converted control-flow block that did not run)")


UNDEF = _Undef()


def _is_tensor(v):
    from ..core.tensor import Tensor
    return isinstance(v, Tensor)


def _under_capture():
    from ..core import tensor as tensor_mod
    return tensor_mod._tracker is not None


def _truthy(v):
    if v is UNDEF:
        raise NameError("control-flow predicate uses an unbound local")
    return bool(v)


def run_if(pred, true_fn, false_fn, get, set_):
    """Runtime dispatch for a rewritten ``if`` statement."""
    if _is_tensor(pred) and _under_capture():
        from ..static.control_flow import cond as static_cond
        init = get()

        # branch thunks restore the frame state they found: they re-run
        # at every (re)trace — probe, lax trace, backward-time vjp — and
        # their nonlocal writes must never outlive the trace (the final
        # set_ below owns the real result)
        def t():
            cur = get()
            try:
                set_(init)
                true_fn()
                return get()
            finally:
                set_(cur)

        def f():
            cur = get()
            try:
                set_(init)
                false_fn()
                return get()
            finally:
                set_(cur)

        out = static_cond(pred, t, f)
        set_(tuple(out))
        return
    if _truthy(pred):
        true_fn()
    else:
        false_fn()


def run_while(cond_fn, body_fn, get, set_, max_trip_count=None):
    """Runtime dispatch for a rewritten ``while`` (or ``for range``)."""
    first = cond_fn()
    if _is_tensor(first) and _under_capture():
        from ..static.control_flow import while_loop as static_while
        init = get()

        def c(*vs):
            cur = get()
            try:
                set_(tuple(vs))
                return cond_fn()
            finally:
                set_(cur)

        def b(*vs):
            cur = get()
            try:
                set_(tuple(vs))
                body_fn()
                return get()
            finally:
                set_(cur)

        out = static_while(c, b, list(init),
                           max_trip_count=max_trip_count)
        set_(tuple(out))
        return
    if not _truthy(first):
        return
    body_fn()
    while _truthy(cond_fn()):
        body_fn()


def not_(v):
    if _is_tensor(v):
        from .. import ops
        return ops.logical_not(v)
    return not v


def and_(a, b_thunk):
    if _is_tensor(a):
        from .. import ops
        return ops.logical_and(a, b_thunk())
    return a and b_thunk()


def or_(a, b_thunk):
    if _is_tensor(a):
        from .. import ops
        return ops.logical_or(a, b_thunk())
    return a or b_thunk()


_SKIP_ROOTS = {"paddle_tpu", "jax", "jaxlib", "numpy", "torch", "flax",
               "optax", "orbax", "chex", "einops", "builtins", "math",
               "functools", "itertools", "typing"}
import weakref

# code-object-keyed caches. Values pin the code object so its id cannot
# be recycled; the per-function-object cache is weak so per-call-created
# closures do not accumulate.
_decline_codes: dict[int, object] = {}       # id(code) -> code
_conv_fns: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _skip_function(fn):
    mod = (getattr(fn, "__module__", "") or "")
    if mod.split(".")[0] in _SKIP_ROOTS:
        return True
    f = getattr(fn.__code__, "co_filename", "")
    return "site-packages" in f or "/lib/python" in f


def _convert_cached(fn):
    cid = id(fn.__code__)
    if cid in _decline_codes:
        return None
    try:
        conv = _conv_fns.get(fn)
    except TypeError:
        conv = None
    if conv is not None:
        return conv
    conv = convert_function(fn)
    if conv is None:
        _decline_codes[cid] = fn.__code__
        return None
    try:
        _conv_fns[fn] = conv
    except TypeError:
        pass
    return conv


def call(f):
    """Call-site wrapper (the reference's ``convert_call``,
    ``jit/dy2static/convert_call_func.py``): convert user callables
    recursively so control flow inside callees (e.g. a Layer's
    ``forward``) lowers too; framework/library functions pass through."""
    try:
        from ..nn import Layer
        if isinstance(f, Layer):
            fwd = getattr(type(f), "forward", None)
            if isinstance(fwd, types.FunctionType) \
                    and not _skip_function(fwd):
                conv = _convert_cached(fwd)
                if conv is not None:
                    return _LayerCallProxy(f, types.MethodType(conv, f))
            return f
        tgt = f.__func__ if isinstance(f, types.MethodType) else f
        if not isinstance(tgt, types.FunctionType) or _skip_function(tgt):
            return f
        conv = _convert_cached(tgt)
        if conv is None:
            return f
        if isinstance(f, types.MethodType):
            return types.MethodType(conv, f.__self__)
        return conv
    except Exception:
        return f


class _LayerCallProxy:
    """Invoke a Layer through its real ``__call__`` (pre/post hooks run)
    with the converted ``forward`` shadowed in the instance dict for the
    duration of the call."""

    __slots__ = ("_layer", "_fwd")

    def __init__(self, layer, fwd):
        self._layer = layer
        self._fwd = fwd

    def __call__(self, *args, **kwargs):
        layer = self._layer
        had = "forward" in layer.__dict__
        prev = layer.__dict__.get("forward")
        layer.__dict__["forward"] = self._fwd
        try:
            return layer(*args, **kwargs)
        finally:
            if had:
                layer.__dict__["forward"] = prev
            else:
                layer.__dict__.pop("forward", None)


def range_args(*a):
    if len(a) == 1:
        return (0, a[0], 1)
    if len(a) == 2:
        return (a[0], a[1], 1)
    if len(a) == 3:
        return tuple(a)
    raise TypeError(f"range expected 1-3 arguments, got {len(a)}")


def range_cond(i, stop, step):
    if isinstance(step, (int, float)):
        if step == 0:
            raise ValueError("range() arg 3 must not be zero")
        return i < stop if step > 0 else i > stop
    from .. import ops
    return ops.logical_or(ops.logical_and(step > 0, i < stop),
                          ops.logical_and(step < 0, i > stop))


# ==========================================================================
# AST analysis
# ==========================================================================

_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef, ast.ListComp, ast.SetComp, ast.DictComp,
                   ast.GeneratorExp)


def _walk_in_scope(node):
    """ast.walk that does not descend into nested scopes."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, _SCOPE_BARRIERS):
                stack.append(child)


def _target_names(t, out):
    if isinstance(t, ast.Name):
        out.add(t.id)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            _target_names(e, out)
    elif isinstance(t, ast.Starred):
        _target_names(t.value, out)
    # Attribute/Subscript targets are object mutations, not name binds


def _assigned_names(stmts):
    """Names bound by the statements (this scope only, ordered)."""
    names: set[str] = set()
    for s in stmts:
        for n in _walk_in_scope(s):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    _target_names(t, names)
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                _target_names(n.target, names)
            elif isinstance(n, ast.For):
                _target_names(n.target, names)
            elif isinstance(n, ast.NamedExpr):
                _target_names(n.target, names)
            elif isinstance(n, ast.withitem) and n.optional_vars:
                _target_names(n.optional_vars, names)
            elif isinstance(n, ast.Import):
                for al in n.names:
                    names.add((al.asname or al.name).split(".")[0])
            elif isinstance(n, ast.ImportFrom):
                for al in n.names:
                    names.add(al.asname or al.name)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.add(n.name)
    return sorted(names)


def _has_escape(stmts, *, loop_ctx=False):
    """True if converting these statements into a nested function would
    change semantics: return/yield anywhere in this scope, or
    break/continue that binds to a loop OUTSIDE the statements
    (``loop_ctx``: the statements themselves are a loop body, so depth-0
    break/continue escapes), or ``del`` of a name."""

    def walk(ss, depth):
        for s in ss:
            if isinstance(s, (ast.Return, ast.Delete)):
                return True
            if isinstance(s, (ast.Break, ast.Continue)) and depth == 0:
                return True
            for child_list, d in _child_blocks(s, depth):
                if walk(child_list, d):
                    return True
            for n in _walk_in_scope(s):
                if isinstance(n, (ast.Yield, ast.YieldFrom, ast.Await)):
                    return True
        return False

    def _child_blocks(s, depth):
        if isinstance(s, (ast.For, ast.While, ast.AsyncFor)):
            yield s.body, depth + 1
            yield s.orelse, depth
        elif isinstance(s, ast.If):
            yield s.body, depth
            yield s.orelse, depth
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            yield s.body, depth
        elif isinstance(s, ast.Try):
            yield s.body, depth
            yield s.orelse, depth
            yield s.finalbody, depth
            for h in s.handlers:
                yield h.body, depth
        # nested defs: new scope, their returns/breaks are fine

    return walk(stmts, 0)


def _has_mangled_names(tree):
    for n in ast.walk(tree):
        if isinstance(n, ast.Attribute) and n.attr.startswith("__") \
                and not n.attr.endswith("__"):
            return True
    return False


class _DeclScanner(ast.NodeVisitor):
    def __init__(self):
        self.globals: set[str] = set()
        self.nonlocals: set[str] = set()

    def visit_Global(self, node):
        self.globals.update(node.names)

    def visit_Nonlocal(self, node):
        self.nonlocals.update(node.names)


# ==========================================================================
# AST rewriting
# ==========================================================================

class _PredRewriter(ast.NodeTransformer):
    """Convert ``not``/``and``/``or`` inside a predicate expression into
    tensor-aware helpers (reference ``logical_transformer.py``). Lazy
    evaluation of and/or tails is preserved via thunks."""

    def visit_UnaryOp(self, node):
        node = self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _call(_HELPER + ".not_", [node.operand])
        return node

    def visit_BoolOp(self, node):
        node = self.generic_visit(node)
        fn = ".and_" if isinstance(node.op, ast.And) else ".or_"
        out = node.values[0]
        for v in node.values[1:]:
            out = _call(_HELPER + fn, [out, _thunk(v)])
        return out

    # do not descend into new scopes inside the predicate
    def visit_Lambda(self, node):
        return node


def _call(dotted, args):
    mod, attr = dotted.rsplit(".", 1)
    return ast.Call(
        func=ast.Attribute(value=ast.Name(id=mod, ctx=ast.Load()),
                           attr=attr, ctx=ast.Load()),
        args=args, keywords=[])


def _thunk(expr):
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                           kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]),
        body=expr)


def _parse_stmts(src):
    return ast.parse(textwrap.dedent(src)).body


class _Rewriter(ast.NodeTransformer):
    def __init__(self, declared_globals, declared_nonlocals):
        self.globals = declared_globals
        self.nonlocals = declared_nonlocals
        self.n = 0
        self.converted_sites = 0
        self.wrapped_calls = 0

    # ---- scope barriers: transform only the target function's scope
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    # ---- recursive callee conversion (reference convert_call)
    _CALL_SKIP = frozenset({
        "range", "len", "print", "super", "isinstance", "issubclass",
        "type", "int", "float", "bool", "str", "tuple", "list", "dict",
        "set", "frozenset", "enumerate", "zip", "map", "filter", "getattr",
        "setattr", "hasattr", "repr", "id", "abs", "min", "max", "sum",
        "sorted", "reversed", "any", "all", "iter", "next", "vars",
        "locals", "globals",
    })

    def visit_Call(self, node):
        node = self.generic_visit(node)
        if isinstance(node.func, ast.Name) \
                and node.func.id in self._CALL_SKIP:
            return node
        node.func = _call(_HELPER + ".call", [node.func])
        self.wrapped_calls += 1
        return node

    # ---------------------------------------------------------------- util
    def _decls(self, names):
        """nonlocal/global declaration statements for generated fns."""
        g = [n for n in names if n in self.globals]
        nl = [n for n in names if n not in self.globals]
        out = []
        if nl:
            out.append(ast.Nonlocal(names=nl))
        if g:
            out.append(ast.Global(names=g))
        return out

    def _mkfn(self, name, body, state_names, args=None):
        body = self._decls(state_names) + (body or [ast.Pass()])
        if not body:
            body = [ast.Pass()]
        return ast.FunctionDef(
            name=name,
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=a) for a in (args or [])],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=body, decorator_list=[], returns=None)

    def _guards(self, names):
        """try/except pre-binding for every state name (makes the name a
        bound local so nonlocal chains resolve, and UNDEF-fills names
        unbound at entry)."""
        out = []
        for n in names:
            if n in self.globals:
                continue  # guards would shadow the global with a local
            out.extend(_parse_stmts(
                f"try:\n    {n}\n"
                f"except (NameError, UnboundLocalError):\n"
                f"    {n} = {_HELPER}.UNDEF"))
        return out

    def _getset(self, idx, names):
        tup = "(" + ", ".join(names) + ("," if names else "") + ")"
        get = self._mkfn(f"__pt{idx}_get",
                         _parse_stmts(f"return {tup}"), [])
        set_body = (_parse_stmts(f"{tup} = __pt_vals") if names
                    else [ast.Pass()])
        set_ = self._mkfn(f"__pt{idx}_set", set_body, names,
                          args=["__pt_vals"])
        return get, set_

    def _state_names(self, *stmt_lists):
        names = set()
        for ss in stmt_lists:
            names.update(_assigned_names(ss))
        # generated helper FUNCTIONS are always (re)defined before use in
        # their own scope — never cross-branch state. Generated loop
        # counters (__ptN_i) stay: they are genuine carry state.
        import re
        drop = re.compile(r"__pt\d+_(true|false|get|set|cond|body)$")
        return sorted(n for n in names if not drop.match(n))

    # ------------------------------------------------------------------ if
    def visit_If(self, node):
        node = self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            return node
        idx = self.n
        self.n += 1
        names = self._state_names(node.body, node.orelse)
        test = _PredRewriter().visit(node.test)
        tf = self._mkfn(f"__pt{idx}_true", node.body, names)
        ff = self._mkfn(f"__pt{idx}_false", node.orelse, names)
        get, set_ = self._getset(idx, names)
        call = ast.Expr(value=_call(_HELPER + ".run_if", [
            test,
            ast.Name(id=tf.name, ctx=ast.Load()),
            ast.Name(id=ff.name, ctx=ast.Load()),
            ast.Name(id=f"__pt{idx}_get", ctx=ast.Load()),
            ast.Name(id=f"__pt{idx}_set", ctx=ast.Load()),
        ]))
        out = self._guards(names) + [tf, ff, get, set_, call]
        for s in out:
            ast.copy_location(s, node)
        self.converted_sites += 1
        return out

    # --------------------------------------------------------------- while
    def visit_While(self, node):
        node = self.generic_visit(node)
        return self._convert_while(node)

    def _convert_while(self, node):
        if node.orelse or _has_escape(node.body, loop_ctx=True):
            return node
        idx = self.n
        self.n += 1
        names = self._state_names(node.body)
        test = _PredRewriter().visit(node.test)
        cf = self._mkfn(f"__pt{idx}_cond",
                        [ast.Return(value=test)], [])
        bf = self._mkfn(f"__pt{idx}_body", node.body, names)
        get, set_ = self._getset(idx, names)
        call = ast.Expr(value=_call(_HELPER + ".run_while", [
            ast.Name(id=cf.name, ctx=ast.Load()),
            ast.Name(id=bf.name, ctx=ast.Load()),
            ast.Name(id=f"__pt{idx}_get", ctx=ast.Load()),
            ast.Name(id=f"__pt{idx}_set", ctx=ast.Load()),
        ]))
        out = self._guards(names) + [cf, bf, get, set_, call]
        for s in out:
            ast.copy_location(s, node)
        self.converted_sites += 1
        return out

    # ----------------------------------------------------------------- for
    def visit_For(self, node):
        node = self.generic_visit(node)
        if (node.orelse
                or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or node.iter.keywords
                or any(isinstance(a, ast.Starred)
                       for a in node.iter.args)
                or _has_escape(node.body, loop_ctx=True)):
            return node
        idx = self.n
        self.n += 1
        r, i = f"__pt{idx}_range", f"__pt{idx}_i"
        # the loop target is pre-bound to start so it is never UNDEF in
        # the carry (documented divergence from CPython: an empty range
        # leaves the target bound to start instead of unbound)
        setup = _parse_stmts(
            f"{r} = {_HELPER}.range_args({{args}})\n{i} = {r}[0]\n"
            f"{node.target.id} = {r}[0]")
        # splice real arg expressions into the range_args call
        setup[0].value.args = list(node.iter.args)
        while_node = ast.While(
            test=_call(_HELPER + ".range_cond", [
                ast.Name(id=i, ctx=ast.Load()),
                _sub(r, 1), _sub(r, 2)]),
            body=([ast.Assign(targets=[node.target],
                              value=ast.Name(id=i, ctx=ast.Load()))]
                  + node.body
                  + _parse_stmts(f"{i} = {i} + {r}[2]")),
            orelse=[])
        for s in setup + [while_node]:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        out = self._convert_while(while_node)
        if out is while_node:  # inner conversion declined; keep plain for
            return node
        return setup + out


def _sub(name, i):
    return ast.Subscript(value=ast.Name(id=name, ctx=ast.Load()),
                         slice=ast.Constant(value=i), ctx=ast.Load())


# ==========================================================================
# entry point
# ==========================================================================

# id(code) -> (code_exec, fndef_name, has_factory); pins the original
# code object (key stability) AND the compiled artifact, so fresh
# function objects sharing a code (per-call closures) skip the AST
# pipeline and only re-exec + rebind cells
_artifact_cache: dict[int, tuple] = {}


def _instantiate(fn, code, fndef_name, has_factory, gns):
    loc: dict = {}
    exec(code, gns, loc)
    if has_factory:
        inner_code = None
        for const in loc["__pt_factory"].__code__.co_consts:
            if isinstance(const, types.CodeType) \
                    and const.co_name == fndef_name:
                inner_code = const
                break
        if inner_code is None:
            return None
        cellmap = dict(zip(fn.__code__.co_freevars, fn.__closure__ or ()))
        try:
            closure = tuple(cellmap[v] for v in inner_code.co_freevars)
        except KeyError:
            return None
        new = types.FunctionType(inner_code, gns, fn.__name__,
                                 fn.__defaults__, closure)
    else:
        new = loc[fndef_name]
        new.__defaults__ = fn.__defaults__
    new.__kwdefaults__ = fn.__kwdefaults__
    new.__dict__.update(fn.__dict__)
    new.__wrapped_original__ = fn
    return new


def convert_function(fn):
    """AST-convert ``fn``; returns the converted function, or ``None``
    when nothing was (or could be) converted (caller keeps the
    original). Mirrors ``program_translator.py:780``'s convert-on-entry,
    collapsed to one pass since our per-site dispatch happens at
    runtime."""
    if not isinstance(fn, types.FunctionType):
        return None
    import sys
    cached = _artifact_cache.get(id(fn.__code__))
    if cached is not None:
        gns = fn.__globals__
        gns.setdefault(_HELPER, sys.modules[__name__])
        return _instantiate(fn, *cached[:3], gns)
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    if not tree.body or not isinstance(tree.body[0], ast.FunctionDef):
        return None
    fndef = tree.body[0]
    if fndef.name != fn.__name__:
        return None
    if _has_mangled_names(fndef):
        return None  # source-level name mangling won't survive re-exec
    for dec in fndef.decorator_list:
        # stripping an unknown decorator would change behavior (and a
        # wrapping decorator means ``fn`` isn't this source anyway)
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = d.attr if isinstance(d, ast.Attribute) else \
            d.id if isinstance(d, ast.Name) else None
        if name != "to_static":
            return None
    decls = _DeclScanner()
    decls.visit(fndef)
    if decls.nonlocals:
        return None  # re-exec'd nonlocal writes would not share cells

    rw = _Rewriter(decls.globals, decls.nonlocals)
    new_body = []
    for s in fndef.body:
        r = rw.visit(s)
        new_body.extend(r if isinstance(r, list) else [r])
    fndef.body = new_body
    if not rw.converted_sites and not rw.wrapped_calls:
        return None
    fndef.decorator_list = []

    freevars = fn.__code__.co_freevars
    if freevars:
        # wrap in a factory that pre-binds the freevar names so the inner
        # def compiles them as free variables again; then rebuild the
        # function around the ORIGINAL closure cells (late rebinding in
        # the defining scope stays visible)
        factory = ast.FunctionDef(
            name="__pt_factory",
            args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=([ast.Assign(
                targets=[ast.Name(id=v, ctx=ast.Store())
                         for v in freevars],
                value=ast.Constant(value=None))]
                + [fndef,
                   ast.Return(value=ast.Name(id=fndef.name,
                                             ctx=ast.Load()))]),
            decorator_list=[], returns=None)
        mod = ast.Module(body=[factory], type_ignores=[])
    else:
        mod = ast.Module(body=[fndef], type_ignores=[])
    ast.fix_missing_locations(mod)

    filename = f"<dy2static {getattr(fn.__code__, 'co_filename', '?')}:" \
               f"{fn.__code__.co_firstlineno}>"
    code = compile(mod, filename, "exec")
    # 4th slot pins the original code object so the cache key id cannot
    # be recycled by a new code object at the same address
    _artifact_cache[id(fn.__code__)] = (code, fndef.name, bool(freevars),
                                        fn.__code__)

    gns = fn.__globals__
    gns.setdefault(_HELPER, sys.modules[__name__])
    return _instantiate(fn, code, fndef.name, bool(freevars), gns)

"""Multi-step execution: K train steps as ONE device program.

TPU-native counterpart of the reference's dataloader+executor step loop:
under a single-controller with a network-attached chip every executable
launch pays a host round trip (the PJRT-client analog of kernel-launch
overhead). ``multi_step`` folds a window of K steps of an already-captured
``jit.to_static`` function into one ``lax.scan``: the per-step state
(params, optimizer moments, RNG) threads through the scan carry entirely
on-device, batches are fed as stacked scan inputs, and only the final
state and the per-step outputs return to the host. Step-time overhead
drops from O(K) round trips to O(1). ``WindowRunner`` additionally
hoists the remaining per-window host work (input staging, output
slicing) out of the steady-state path.

Constraints: every step must hit the SAME compiled specialization (same
shapes/dtypes/modes), and host-side hooks that normally run between steps
(LR-scheduler sync) apply once for the window — `.step()` the scheduler
K times afterwards, as the training loop already does per batch.

With the fused multi-tensor optimizer (``optimizer/flat.py``) the scan
carry holds a handful of flat dtype buckets (params, master weights,
moments, grads) instead of hundreds of per-param arrays: the capture
filters bucket member views out of its state (``jit/__init__.py``), so
the window program's carry — and its donation set — is O(buckets).
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def _resolve_exe(static_fn, first):
    """(exe, out0) for the specialization of ``first`` — compiling it
    with one eager-dispatched step (whose output is returned as
    ``out0``) if this is the first call."""
    if hasattr(static_fn, "_cache"):           # StaticFunction itself
        wrapped = static_fn
    else:                                      # bound-method partial
        wrapped = getattr(static_fn, "__wrapped__", None)
    if wrapped is None or not hasattr(wrapped, "_cache"):
        raise TypeError("multi_step expects a jit.to_static function")
    key = wrapped._cache_key(first, {})
    exe = wrapped._cache.get(key)
    out0 = None
    if exe is None:
        out0 = static_fn(*first)
        exe = wrapped._cache.get(key)
    if exe is None:
        raise RuntimeError(
            "step did not compile (eager fallback) — multi_step needs the "
            "compiled path; fix the graph break first")
    return exe, out0


def _split(exe, per_step_idx=()):
    """(carry_idx, const_idx, ps_idx) into ``exe.capt_state`` — the ONE
    place the promoted per-step indices are removed from the constants,
    shared by the window builder and the runner so their orderings can
    never drift apart."""
    carry_idx, const_idx = exe.state_split()
    ps_idx = list(per_step_idx)
    return carry_idx, [i for i in const_idx if i not in ps_idx], ps_idx


def _build_window(exe, donate, per_step_idx=()):
    """The jitted K-step window program for ``exe``: scan the step's pure
    function over stacked inputs, threading the written captured state
    through the (donated) carry and closing over the read-only state.

    ``per_step_idx``: indices into ``exe.capt_state`` promoted from scan
    constants to PER-STEP scanned inputs (leading [K] axis) — the
    mechanism behind per-step learning rates inside a window (a captured
    LR scalar is otherwise frozen for all K steps because its host-side
    scheduler sync runs once per launch, not once per step)."""
    capt = exe.capt_state
    n_state = len(exe.state_out_tensors)
    n_ret = exe.n_ret
    carry_idx, const_idx, ps_idx = _split(exe, per_step_idx)
    pure = exe._pure

    def window(carry_vals, const_vals, ps_stacks, *stacks):
        def body(carry, xs):
            ps_vals, arg_vals = xs
            state = [None] * len(capt)
            for i, v in zip(carry_idx, carry):
                state[i] = v
            for i, v in zip(const_idx, const_vals):
                state[i] = v
            for i, v in zip(ps_idx, ps_vals):
                state[i] = v
            outs = pure(*arg_vals, *state)
            return (list(outs[n_ret:n_ret + n_state]),
                    tuple(outs[:n_ret]))

        carry, rets = jax.lax.scan(body, list(carry_vals),
                                   (tuple(ps_stacks), tuple(stacks)))
        return carry, rets

    return jax.jit(window, donate_argnums=(0,) if donate else ())


def _run_window(exe, runner, stacks, per_step_idx=(), per_step_vals=()):
    """Execute one window: read the captured state, launch, write the
    post-window state back. Returns the stacked per-step outputs."""
    capt = exe.capt_state
    carry_idx, const_idx, ps_idx = _split(exe, per_step_idx)
    for sync in exe.discovery.host_syncs:
        sync()
    from . import _state_write
    carry_vals = [capt[i]._read() for i in carry_idx]
    const_vals = [capt[i]._read() for i in const_idx]
    # whole-program audit of the window once per runner (compile-time
    # only; make_jaxpr does not consume the soon-to-be-donated carry)
    audited = exe.__dict__.setdefault("_window_audit_done", set())
    if id(runner) not in audited:
        audited.add(id(runner))
        from .. import analysis as _analysis
        _analysis.audit_jitted(
            runner,
            (carry_vals, const_vals, tuple(per_step_vals)) + tuple(stacks),
            where=f"multi_step.{getattr(exe, '_fn_name', 'window')}")
    final_carry, rets = runner(carry_vals, const_vals,
                               tuple(per_step_vals), *stacks)
    for i, v in zip(carry_idx, final_carry):
        _state_write(capt[i], v)
    # leave the promoted tensors holding their LAST per-step value, as
    # if the host had fed each step individually
    for i, v in zip(ps_idx, per_step_vals):
        _state_write(capt[i], v[-1])
    return rets


class WindowRunner:
    """A K-step training window as ONE dispatch with pre-staged inputs.

    ``multi_step`` pays per-window host work that a network-attached chip
    bills at tunnel latency: a separate single-step dispatch for the
    first batch, per-window ``jnp.stack`` calls, and one device-slice
    dispatch per step to rebuild outputs. ``WindowRunner`` hoists all of
    it out of the steady-state path: ``stage()`` uploads a whole window
    of batches as stacked arrays once; ``run()`` is then exactly one
    compiled scan launch over all K steps (params/moments/RNG donated
    through the carry) returning the per-step outputs device-resident.

    Usage::

        w = WindowRunner(train_step, example_args, length=K)
        stacks = w.stage(batches)        # K host batches -> device
        losses = w.run(*stacks)          # ONE dispatch, K steps
        last = float(losses[-1])         # sync / readback

    NOTE: if ``static_fn`` has not yet compiled for this signature,
    construction primes it by executing ONE real step on
    ``example_args`` — exactly the state mutation of calling the step
    once. Construct after warmup (the usual case) to avoid it.
    """

    def __init__(self, static_fn, example_args, length, donate=True,
                 per_step=None):
        if length < 1:
            raise ValueError("window length must be >= 1")
        self.length = length
        first = tuple(example_args)
        exe, _ = _resolve_exe(static_fn, first)
        self._exe = exe
        self._n_args = len(first)
        self._ps_idx = []
        if per_step:
            pos = {id(t): i for i, t in enumerate(exe.capt_state)}
            carry = set(exe.state_split()[0])
            for t in per_step:
                i = pos.get(id(t))
                if i is None:
                    raise ValueError(
                        "per_step tensor is not captured state of this "
                        "step (it must be read by the compiled function)")
                if i in carry:
                    raise ValueError(
                        "per_step tensor is WRITTEN by the step — it "
                        "already threads through the scan carry")
                self._ps_idx.append(i)
        self._runner = _build_window(exe, donate, tuple(self._ps_idx))

    def stage(self, arg_batches):
        """Stack a window of batches into device arrays (one upload per
        argument position). Call outside the timed/steady-state path;
        the result can be reused across ``run`` calls (e.g.
        benchmarking) or double-buffered against the previous window's
        execution.

        Batches already resident on device (the common fit-loop case:
        DataLoader collate built device tensors) are stacked ON DEVICE
        — ``np.stack`` over device arrays would round-trip every batch
        through the tunnel (~17 s/window measured for 50 GPT batches
        vs milliseconds for the device-side stack)."""
        import numpy as np
        if len(arg_batches) != self.length:
            raise ValueError(
                f"expected {self.length} batches, got {len(arg_batches)}")
        cols = []
        for i in range(self._n_args):
            vals = [b[i]._read() if isinstance(b[i], Tensor) else b[i]
                    for b in arg_batches]
            if all(isinstance(v, jax.Array) for v in vals):
                cols.append(jnp.stack(vals))
            else:
                cols.append(jnp.asarray(np.stack(
                    [np.asarray(v) for v in vals])))
        return tuple(cols)

    def run(self, *stacks, outputs="all", per_step_vals=None):
        """One compiled K-step launch. Returns the per-step outputs as a
        list of ``length`` entries (device-resident until read); captured
        state (params, moments, RNG) holds the post-window values.

        ``outputs``: "all" rebuilds every step's outputs (one device
        slice per step); "last" only the final step's (the common
        train-loop need — logging the latest loss — at one slice);
        "stacked" returns the raw [K, ...] arrays with no slicing.

        ``per_step_vals``: one [length, ...] array per ``per_step``
        tensor declared at construction — that tensor takes value
        ``per_step_vals[j][k]`` during step k (e.g. a warmup LR ramp
        inside the window)."""
        exe = self._exe
        if len(per_step_vals or ()) != len(self._ps_idx):
            raise ValueError(
                f"expected {len(self._ps_idx)} per_step_vals arrays, "
                f"got {len(per_step_vals or ())}")
        ps_vals = tuple(jnp.asarray(v) for v in per_step_vals or ())
        for v in ps_vals:
            n = v.shape[0] if v.ndim else -1
            if n != self.length:
                raise ValueError(
                    f"per_step_vals arrays need leading dim "
                    f"{self.length}, got {n}")
        rets = _run_window(exe, self._runner, stacks, self._ps_idx,
                           ps_vals)
        if outputs == "stacked":
            return rets
        if outputs == "last":
            step_ret = [Tensor(r[-1]) for r in rets]
            return exe.ret_rebuild(step_ret)
        outs = []
        for s in range(self.length):
            step_ret = [Tensor(r[s]) for r in rets]
            outs.append(exe.ret_rebuild(step_ret))
        return outs

    def rebuild_host(self, rets):
        """``run(..., outputs="stacked")`` results -> list of per-step
        output structures over HOST-resident tensors: ONE device
        readback per output leaf (each ``outputs="all"`` step slice is
        a separate dispatch — ~3-12 ms each over a network-attached
        chip; reading the stacked arrays once amortizes that to one
        round trip per leaf for the whole window)."""
        import numpy as np
        host = [np.asarray(r) for r in rets]
        outs = []
        for s in range(self.length):
            step_ret = [Tensor(h[s]) for h in host]
            outs.append(self._exe.ret_rebuild(step_ret))
        return outs


def multi_step(static_fn, arg_batches: Sequence[Sequence], donate=True):
    """Run ``static_fn`` (a ``@jit.to_static`` function) over
    ``arg_batches`` — a sequence of per-step positional-arg tuples with
    identical shapes — in one compiled scan. Returns the list of per-step
    outputs (device-resident until read). State tensors captured by the
    step (parameters, moments, RNG) hold the post-window values, exactly
    as if the steps had been dispatched one by one.

    The first batch always runs as a single eager-dispatched step (it is
    also the compile trigger on first use); the remaining K-1 batches run
    as one scanned window. For a steady-state loop where even that
    per-window work matters, use :class:`WindowRunner`."""
    if not arg_batches:
        return []
    first = tuple(arg_batches[0])
    exe, out0 = _resolve_exe(static_fn, first)
    if out0 is None:  # already compiled — still dispatch the first batch
        out0 = static_fn(*first)
    rest = [tuple(b) for b in arg_batches[1:]]
    if not rest:
        return [out0]

    n_args = len(first)
    cache = getattr(exe, "_multi_step_cache", None)
    if cache is None:
        cache = exe._multi_step_cache = {}
    runner = cache.get((len(rest), donate))
    if runner is None:
        runner = cache[(len(rest), donate)] = _build_window(exe, donate)

    stacks = tuple(
        jnp.stack([jnp.asarray(b[i]._read() if isinstance(b[i], Tensor)
                               else b[i]) for b in rest])
        for i in range(n_args))
    rets = _run_window(exe, runner, stacks)
    outs = [out0]
    for s in range(len(rest)):
        step_ret = [Tensor(r[s]) for r in rets]
        outs.append(exe.ret_rebuild(step_ret))
    return outs

"""Multi-step execution: K train steps as ONE device program.

TPU-native counterpart of the reference's dataloader+executor step loop:
under a single-controller with a network-attached chip every executable
launch pays a host round trip (the PJRT-client analog of kernel-launch
overhead). ``multi_step`` folds a window of K steps of an already-captured
``jit.to_static`` function into one ``lax.scan``: the per-step state
(params, optimizer moments, RNG) threads through the scan carry entirely
on-device, batches are fed as stacked scan inputs, and only the final
state and the per-step outputs return to the host. Step-time overhead
drops from O(K) round trips to O(1).

Constraints: every step must hit the SAME compiled specialization (same
shapes/dtypes/modes), and host-side hooks that normally run between steps
(LR-scheduler sync) apply once for the window — `.step()` the scheduler
K times afterwards, as the training loop already does per batch.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def multi_step(static_fn, arg_batches: Sequence[Sequence], donate=True):
    """Run ``static_fn`` (a ``@jit.to_static`` function) over
    ``arg_batches`` — a sequence of per-step positional-arg tuples with
    identical shapes — in one compiled scan. Returns the list of per-step
    outputs (device-resident until read). State tensors captured by the
    step (parameters, moments, RNG) hold the post-window values, exactly
    as if the steps had been dispatched one by one."""
    if hasattr(static_fn, "_cache"):           # StaticFunction itself
        wrapped = static_fn
    else:                                      # bound-method partial
        wrapped = getattr(static_fn, "__wrapped__", None)
    if wrapped is None or not hasattr(wrapped, "_cache"):
        raise TypeError("multi_step expects a jit.to_static function")
    if not arg_batches:
        return []
    first = tuple(arg_batches[0])
    # ensure the specialization exists (capture/compile on the first batch)
    out0 = static_fn(*first)
    key = wrapped._cache_key(first, {})
    exe = wrapped._cache.get(key)
    if exe is None:
        raise RuntimeError(
            "step did not compile (eager fallback) — multi_step needs the "
            "compiled path; fix the graph break first")
    rest = [tuple(b) for b in arg_batches[1:]]
    if not rest:
        return [out0]

    n_args = len(first)
    n_ret = exe.n_ret
    state_ts = exe.state_out_tensors
    capt = exe.capt_state
    # carry = the written subset of captured state, by capt index
    carry_idx, const_idx = exe.state_split()
    pure = exe._pure

    cache = getattr(exe, "_multi_step_cache", None)
    if cache is None:
        cache = exe._multi_step_cache = {}
    runner = cache.get((len(rest), donate))
    if runner is None:
        def window(carry_vals, const_vals, *stacks):
            def body(carry, xs):
                vals = list(xs)
                state = [None] * len(capt)
                for i, v in zip(carry_idx, carry):
                    state[i] = v
                for i, v in zip(const_idx, const_vals):
                    state[i] = v
                outs = pure(*vals, *state)
                ret = outs[:n_ret]
                new_state = outs[n_ret:n_ret + len(state_ts)]
                return list(new_state), tuple(ret)

            carry, rets = jax.lax.scan(body, list(carry_vals), stacks)
            return carry, rets

        runner = jax.jit(window, donate_argnums=(0,) if donate else ())
        cache[(len(rest), donate)] = runner

    for sync in exe.discovery.host_syncs:
        sync()
    stacks = tuple(
        jnp.stack([jnp.asarray(b[i]._read() if isinstance(b[i], Tensor)
                               else b[i]) for b in rest])
        for i in range(n_args))
    carry_vals = [capt[i]._read() for i in carry_idx]
    const_vals = [capt[i]._read() for i in const_idx]
    final_carry, rets = runner(carry_vals, const_vals, *stacks)
    # write the post-window state back onto the captured tensors
    for i, v in zip(carry_idx, final_carry):
        capt[i]._data = v
        capt[i]._node = None
    outs = [out0]
    for s in range(len(rest)):
        step_ret = [Tensor(r[s]) for r in rets]
        outs.append(exe.ret_rebuild(step_ret))
    return outs

"""Audio feature layers (reference ``python/paddle/audio/features/layers.py``:
Spectrogram :34, MelSpectrogram :123, LogMelSpectrogram :247, MFCC :379)."""
from __future__ import annotations

from ..nn.layer import Layer
from . import functional as AF


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = AF.get_window(window, self.win_length)

    def forward(self, x):
        from .. import ops, signal
        spec = signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                           window=self.fft_window, center=self.center,
                           pad_mode=self.pad_mode)
        mag = ops.abs(spec)
        if self.power != 1.0:
            mag = mag ** self.power
        return mag


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode)
        self.fbank_matrix = AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm)

    def forward(self, x):
        from .. import ops
        spec = self._spectrogram(x)          # [..., freq, time]
        return ops.matmul(self.fbank_matrix, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db)
        self.dct_matrix = AF.create_dct(n_mfcc, n_mels)

    def forward(self, x):
        from .. import ops
        logmel = self._log_melspectrogram(x)   # [..., n_mels, time]
        return ops.matmul(ops.transpose(self.dct_matrix, [1, 0]), logmel)

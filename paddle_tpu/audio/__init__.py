"""``paddle.audio`` parity — spectral features and window functions.

Analog of ``python/paddle/audio/`` (``functional/window.py``,
``functional/functional.py`` hz_to_mel/mel_frequencies/compute_fbank_matrix,
``features/layers.py`` Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC).
Built on the framework stft (XLA FFT), so feature extraction is
jit-fusible and differentiable end-to-end.
"""
from . import functional  # noqa: F401
from . import datasets  # noqa: F401
from .features import (  # noqa: F401
    LogMelSpectrogram, MFCC, MelSpectrogram, Spectrogram,
)

__all__ = ["functional", "datasets", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]

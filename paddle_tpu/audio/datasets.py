"""``paddle.audio.datasets`` parity (reference
``python/paddle/audio/datasets/``: ``dataset.py`` AudioClassificationDataset,
``tess.py`` TESS, ``esc50.py`` ESC50). Zero-egress image: the archives must
be local directories of wav files; ``feat_type`` routes through
``paddle.audio.features`` exactly like the reference."""
from __future__ import annotations

import os
import wave

import numpy as np

from ..io import Dataset

_FEAT_TYPES = ("raw", "melspectrogram", "mfcc", "logmelspectrogram",
               "spectrogram")


def _read_wav(path):
    """(waveform float32 [-1, 1], sample_rate) via the stdlib wav reader
    (no soundfile/librosa in this image)."""
    with wave.open(path, "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        width = w.getsampwidth()
        ch = w.getnchannels()
        raw = w.readframes(n)
    if width == 2:
        data = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif width == 4:
        data = np.frombuffer(raw, np.int32).astype(np.float32) / 2**31
    elif width == 1:
        data = (np.frombuffer(raw, np.uint8).astype(np.float32)
                - 128.0) / 128.0
    else:
        raise ValueError(f"unsupported wav sample width {width}")
    if ch > 1:
        data = data.reshape(-1, ch).mean(axis=1)
    return data, sr


class AudioClassificationDataset(Dataset):
    """Reference ``audio/datasets/dataset.py``: (feature, label) items;
    ``feat_type='raw'`` yields the waveform, else a feature transform
    from ``paddle.audio.features``."""

    def __init__(self, files, labels, feat_type="raw", sample_rate=None,
                 **feat_kwargs):
        if feat_type not in _FEAT_TYPES:
            raise ValueError(f"feat_type must be one of {_FEAT_TYPES}")
        self.files = list(files)
        self.labels = list(labels)
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs
        self.sample_rate = sample_rate
        self._feat_layers = {}

    def _feature(self, wav, sr):
        if self.feat_type == "raw":
            return wav
        from ..core.tensor import Tensor
        layer = self._feat_layers.get(sr)
        if layer is None:  # mel/DCT bases are per-rate; build once
            from . import features as feats
            cls = {"melspectrogram": "MelSpectrogram",
                   "logmelspectrogram": "LogMelSpectrogram",
                   "mfcc": "MFCC",
                   "spectrogram": "Spectrogram"}[self.feat_type]
            kw = dict(self.feat_kwargs)
            if cls != "Spectrogram":   # Spectrogram is rate-agnostic
                kw.setdefault("sr", sr)
            layer = self._feat_layers[sr] = getattr(feats, cls)(**kw)
        return np.asarray(layer(Tensor(wav[None]))._read())[0]

    def __getitem__(self, idx):
        wav, sr = _read_wav(self.files[idx])
        if self.sample_rate and sr != self.sample_rate:
            # naive linear resample (keeps parity testable without scipy
            # signal dependencies in the hot path)
            n_out = int(round(len(wav) * self.sample_rate / sr))
            wav = np.interp(np.linspace(0, len(wav) - 1, n_out),
                            np.arange(len(wav)), wav).astype(np.float32)
            sr = self.sample_rate
        return self._feature(wav, sr), np.int64(self.labels[idx])

    def __len__(self):
        return len(self.files)


class TESS(AudioClassificationDataset):
    """Reference ``audio/datasets/tess.py:26``: Toronto emotional speech
    set — 7 emotions encoded in the filename's last underscore field
    (``..._angry.wav``). ``archive_path`` is the extracted directory."""

    EMOTIONS = ("angry", "disgust", "fear", "happy", "neutral", "ps",
                "sad")

    def __init__(self, archive_path=None, mode="train", n_folds=5,
                 split=1, feat_type="raw", **kwargs):
        if archive_path is None or not os.path.isdir(archive_path):
            raise RuntimeError(
                "TESS: pass archive_path= the extracted TESS directory "
                "(no network egress in this environment)")
        files, labels = [], []
        for root, _, names in sorted(os.walk(archive_path)):
            for nm in sorted(names):
                if not nm.lower().endswith(".wav"):
                    continue
                emotion = nm.rsplit("_", 1)[-1][:-4].lower()
                if emotion not in self.EMOTIONS:
                    continue
                files.append(os.path.join(root, nm))
                labels.append(self.EMOTIONS.index(emotion))
        # fold split like the reference: every n_folds-th item is eval
        sel = [(i % n_folds) != (split - 1) for i in range(len(files))]
        keep = [i for i, s in enumerate(sel)
                if (s if mode == "train" else not s)]
        super().__init__([files[i] for i in keep],
                         [labels[i] for i in keep],
                         feat_type=feat_type, **kwargs)


class ESC50(AudioClassificationDataset):
    """Reference ``audio/datasets/esc50.py``: 50-class environmental
    sounds; label and fold come from the filename
    (``{fold}-{id}-{take}-{target}.wav``)."""

    def __init__(self, archive_path=None, mode="train", split=1,
                 feat_type="raw", **kwargs):
        if archive_path is None or not os.path.isdir(archive_path):
            raise RuntimeError(
                "ESC50: pass archive_path= the extracted ESC-50 audio "
                "directory (no network egress in this environment)")
        files, labels, folds = [], [], []
        for root, _, names in sorted(os.walk(archive_path)):
            for nm in sorted(names):
                if not nm.lower().endswith(".wav"):
                    continue
                parts = nm[:-4].split("-")
                if len(parts) != 4:
                    continue
                files.append(os.path.join(root, nm))
                folds.append(int(parts[0]))
                labels.append(int(parts[3]))
        keep = [i for i in range(len(files))
                if ((folds[i] != split) if mode == "train"
                    else (folds[i] == split))]
        super().__init__([files[i] for i in keep],
                         [labels[i] for i in keep],
                         feat_type=feat_type, **kwargs)


__all__ = ["AudioClassificationDataset", "TESS", "ESC50"]

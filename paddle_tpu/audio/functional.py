"""Audio functional ops (reference ``python/paddle/audio/functional/``)."""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Reference ``functional/window.py get_window``: hann/hamming/
    blackman/bartlett/bohman/gaussian/general_gaussian/exponential/
    taylor/kaiser/tukey supported by scipy — we implement the common set
    natively and defer the exotic ones to scipy.signal when present."""
    n = win_length
    if isinstance(window, tuple):
        name, *params = window
    else:
        name, params = window, []
    m = n if not fftbins else n + 1
    k = np.arange(m)
    if name == "hann":
        w = 0.5 - 0.5 * np.cos(2 * math.pi * k / (m - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * math.pi * k / (m - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * math.pi * k / (m - 1))
             + 0.08 * np.cos(4 * math.pi * k / (m - 1)))
    elif name == "bartlett":
        w = 1 - np.abs(2 * k / (m - 1) - 1)
    elif name == "rect" or name == "boxcar" or name == "ones":
        w = np.ones(m)
    else:
        from scipy.signal import get_window as sp_get
        w = sp_get(window if params == [] else (name, *params), m,
                   fftbins=False)
    if fftbins:
        w = w[:-1]
    return Tensor(jnp.asarray(w, jnp.float32))


def hz_to_mel(freq, htk=False):
    """Reference ``functional.py hz_to_mel`` (slaney default)."""
    scalar = not hasattr(freq, "__len__")
    f = np.asarray(freq, np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10) /
                                            min_log_hz) / logstep, out)
    return float(out) if scalar else out


def mel_to_hz(mel, htk=False):
    scalar = not hasattr(mel, "__len__")
    m = np.asarray(mel, np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(m >= min_log_mel,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)),
                       out)
    return float(out) if scalar else out


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                       n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr, n_fft):
    return np.linspace(0, sr / 2, 1 + n_fft // 2)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Reference ``functional.py compute_fbank_matrix`` -> [n_mels,
    1 + n_fft//2] triangular filters."""
    f_max = f_max or sr / 2
    fft_f = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb, jnp.float32))


def power_to_db(magnitude, ref_value=1.0, amin=1e-10, top_db=80.0):
    """Reference ``functional.py power_to_db``."""
    from .. import ops
    x = magnitude if isinstance(magnitude, Tensor) else Tensor(magnitude)
    log_spec = 10.0 * ops.log10(ops.maximum(x, amin))
    log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        log_spec = ops.maximum(log_spec, ops.max(log_spec) - top_db)
    return log_spec


def create_dct(n_mfcc, n_mels, norm="ortho"):
    """DCT-II basis [n_mels, n_mfcc] (reference ``functional.py``)."""
    k = np.arange(n_mels)[:, None]
    f = np.arange(n_mfcc)[None, :]
    dct = np.cos(math.pi / n_mels * (k + 0.5) * f) * 2.0
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(1.0 / (2.0 * n_mels))
    return Tensor(jnp.asarray(dct, jnp.float32))


__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
           "fft_frequencies", "compute_fbank_matrix", "power_to_db",
           "create_dct"]

"""paddle_tpu.amp — automatic mixed precision.

Analog of ``python/paddle/amp/`` (reference ``auto_cast.py:279`` auto_cast,
``:858`` decorate, ``grad_scaler.py:573`` GradScaler). TPU-native choices:

- default low dtype is **bfloat16** (TPU MXU native; fp16 also supported);
- O1 casting happens in the op-dispatch funnel (``core/dispatch.py``): ops on
  the white list run with inputs cast to the low dtype, black-list ops are
  pinned to float32 — the analog of the reference's per-op AMP lists
  (``python/paddle/amp/amp_lists.py``);
- bf16 needs no loss scaling, so ``GradScaler(enable=False)`` is the natural
  TPU mode, but full dynamic scaling is implemented for fp16 parity.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from ..core import state
from ..core.tensor import Tensor, Parameter

# Ops that are numerically safe + MXU-bound: run in low precision.
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose", "bmm", "mm", "einsum", "addmm",
    "scaled_dot_product_attention", "flash_attn_unpadded", "mv",
}
# Numerically risky reductions/normalizations: pin to float32.
BLACK_LIST = {
    "softmax", "log_softmax", "cross_entropy", "nll_loss", "mse_loss",
    "l1_loss", "bce_with_logits", "binary_cross_entropy", "kl_div",
    "layer_norm", "rms_norm", "batch_norm", "instance_norm", "group_norm",
    "mean", "sum", "exp", "log", "pow", "cumsum", "logsumexp", "norm",
    "softmax_with_cross_entropy", "ctc_loss", "sigmoid_focal_loss",
}


class _AmpState:
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_amp = _AmpState()


def amp_state():
    return _amp


def _to_jnp_dtype(d):
    if d in ("bfloat16", "bf16"):
        return jnp.bfloat16
    if d in ("float16", "fp16"):
        return jnp.float16
    return jnp.dtype(d)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """Reference ``auto_cast.py:279``. Under O2 every op (except black list)
    runs in the low dtype; under O1 only white-list ops do."""
    old = (_amp.enabled, _amp.dtype, _amp.level, _amp.custom_white,
           _amp.custom_black)
    _amp.enabled = bool(enable)
    _amp.dtype = _to_jnp_dtype(dtype)
    _amp.level = level
    _amp.custom_white = set(custom_white_list or ())
    _amp.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_amp.enabled, _amp.dtype, _amp.level, _amp.custom_white,
         _amp.custom_black) = old


autocast = auto_cast


def amp_cast_inputs(name, vals):
    """Called from core.dispatch.apply when amp is enabled: returns vals cast
    per the active AMP lists."""
    white = (name in WHITE_LIST or name in _amp.custom_white)
    black = (name in BLACK_LIST or name in _amp.custom_black) and \
        name not in _amp.custom_white
    if _amp.level == "O2":
        target = jnp.float32 if black else _amp.dtype
    else:
        if black:
            target = jnp.float32
        elif white:
            target = _amp.dtype
        else:
            return vals
    out = []
    for v in vals:
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating) \
                and v.dtype != target:
            out.append(v.astype(target))
        else:
            out.append(v)
    return out


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """Reference ``auto_cast.py:858``: O2 casts model params to the low
    dtype; optimizers get master (float32) weights."""
    from ..nn import Layer

    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models)
    if level == "O2":
        low = _to_jnp_dtype(dtype)
        excluded = []
        if excluded_layers:
            excl_list = (excluded_layers if isinstance(excluded_layers, list)
                         else [excluded_layers])
            for m in model_list:
                for l in m.sublayers(include_self=True):
                    if isinstance(l, tuple(
                            e for e in excl_list if isinstance(e, type))) or \
                            l in [e for e in excl_list
                                  if isinstance(e, Layer)]:
                        excluded.append(id(l))
        from ..nn.layers import _BatchNormBase, LayerNorm, GroupNorm
        for m in model_list:
            for l in m.sublayers(include_self=True):
                # keep norm layers in fp32 (reference keeps BN/LN master)
                if isinstance(l, (_BatchNormBase, LayerNorm, GroupNorm)) or \
                        id(l) in excluded:
                    continue
                for pname, p in list(l._parameters.items()):
                    if p is None:
                        continue
                    v = p._read()
                    if not jnp.issubdtype(v.dtype, jnp.floating):
                        continue
                    import jax
                    if isinstance(v, jax.ShapeDtypeStruct):
                        # lazy (LazyGuard) parameter: retype abstractly
                        p._write(jax.ShapeDtypeStruct(
                            v.shape, low,
                            sharding=getattr(v, "sharding", None)))
                    else:
                        p._write(v.astype(low))
    if optimizers is None:
        return models if single_model else model_list
    single_opt = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if single_opt else list(optimizers)
    if master_weight is not False:
        for o in opt_list:
            o._multi_precision = True
    if single_model and single_opt:
        return models, optimizers
    return model_list, opt_list


class GradScaler:
    """Reference ``grad_scaler.py:573``: dynamic loss scaling for fp16.
    With bf16 (TPU default) pass ``enable=False`` — scale() and step() become
    pass-throughs, matching reference behavior when amp is off."""

    def __init__(self, enable=True, init_loss_scaling=65536.0,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def scale(self, var):
        if not self._enable:
            return var
        from .. import ops
        return ops.scale(var, scale=self._scale)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        # fused optimizer: unscale + inf-check over the flat grad
        # buckets — one multiply and one reduction per bucket instead of
        # a per-param chain; leftovers fall through to the loop below
        handled = set()
        flat_unscale = getattr(optimizer, "_flat_unscale", None)
        if flat_unscale is not None:
            found, handled = flat_unscale(inv)
        for p in optimizer._parameters:
            if p.grad is None or id(p) in handled:
                continue
            g = p.grad._read().astype(jnp.float32) * inv
            if not bool(jnp.all(jnp.isfinite(g))):
                found = True
            p.grad._write(g)
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update_scale()
        self._unscaled = False

    def minimize(self, optimizer, scaled_loss):
        # The documented idiom is ``scaler.scale(loss).backward();
        # scaler.minimize(opt, scaled)`` — backward has already run, so only
        # unscale + conditional step here (reference grad_scaler.py:202 does
        # the same: minimize never re-runs autodiff).
        self.step(optimizer)

    def update(self):
        if self._enable and self._unscaled:
            self._update_scale()
            self._unscaled = False

    def _update_scale(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every,
                "decr_every_n_nan_or_inf": self._decr_every,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps,
                "use_dynamic_loss_scaling": self._dynamic}

    def set_state_dict(self, sd):
        # restore EVERY knob state_dict() saves — dropping the
        # incr/decr policy here made a resumed fp16 run scale on the
        # constructor defaults instead of the trained-with policy
        self._scale = float(sd.get("scale", self._scale))
        self._incr_ratio = float(sd.get("incr_ratio", self._incr_ratio))
        self._decr_ratio = float(sd.get("decr_ratio", self._decr_ratio))
        self._incr_every = int(sd.get("incr_every_n_steps",
                                      self._incr_every))
        self._decr_every = int(sd.get("decr_every_n_nan_or_inf",
                                      self._decr_every))
        self._dynamic = bool(sd.get("use_dynamic_loss_scaling",
                                    self._dynamic))
        self._good_steps = int(sd.get("good_steps", 0))
        self._bad_steps = int(sd.get("bad_steps", 0))


def is_bfloat16_supported(device=None):
    return True


def is_float16_supported(device=None):
    return True

"""Dtype and place abstractions.

Capability analog of the reference's ``paddle/phi/common/`` scalar/dtype/place
layer (SURVEY C3; reference ``paddle/phi/common/place.h``, ``data_type.h``),
re-expressed for a JAX/XLA runtime: dtypes are jnp dtypes, a Place names an
XLA device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Canonical dtype table: paddle-style name -> jnp dtype.
_DTYPE_TABLE = {
    "float64": jnp.float64,
    "float32": jnp.float32,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "int64": jnp.int64,
    "int32": jnp.int32,
    "int16": jnp.int16,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "bool": jnp.bool_,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
}

float32 = jnp.float32
float64 = jnp.float64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
int64 = jnp.int64
int32 = jnp.int32
int16 = jnp.int16
int8 = jnp.int8
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128


def convert_dtype(dtype):
    """Normalize any dtype spec (str, np/jnp dtype, None) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name in _DTYPE_TABLE:
            return np.dtype(_DTYPE_TABLE[name])
        return np.dtype(name)
    try:
        return np.dtype(dtype)
    except TypeError:
        # jnp scalar types like jnp.float32
        return np.dtype(np.dtype(dtype).name)


def dtype_name(dtype) -> str:
    d = np.dtype(dtype)
    return d.name


def is_floating(dtype) -> bool:
    d = np.dtype(dtype)
    return jnp.issubdtype(d, jnp.floating)


def is_integer(dtype) -> bool:
    d = np.dtype(dtype)
    return jnp.issubdtype(d, jnp.integer)


def is_complex(dtype) -> bool:
    d = np.dtype(dtype)
    return jnp.issubdtype(d, jnp.complexfloating)


class Place:
    """Device identity. Analog of ``phi::Place`` (reference
    ``paddle/phi/common/place.h``) over jax devices."""

    def __init__(self, device: "jax.Device | str | Place | None" = None):
        if isinstance(device, Place):
            self._device = device._device
        elif isinstance(device, str):
            kind, _, idx = device.partition(":")
            idx = int(idx) if idx else 0
            devs = [d for d in jax.devices() if d.platform == _platform(kind)]
            if not devs:
                devs = jax.devices()
            self._device = devs[min(idx, len(devs) - 1)]
        elif device is None:
            self._device = jax.devices()[0]
        else:
            self._device = device

    @property
    def device(self):
        return self._device

    @property
    def platform(self) -> str:
        return self._device.platform

    def is_tpu_place(self) -> bool:
        return self._device.platform in ("tpu", "axon")

    def is_cpu_place(self) -> bool:
        return self._device.platform == "cpu"

    def __eq__(self, other):
        return isinstance(other, Place) and self._device == other._device

    def __hash__(self):
        return hash(self._device)

    def __repr__(self):
        return f"Place({self._device.platform}:{self._device.id})"


def _platform(kind: str) -> str:
    kind = kind.lower()
    if kind in ("tpu", "xla", "axon"):
        return "tpu"
    if kind in ("gpu", "cuda"):
        return "gpu"
    if kind not in ("cpu", ""):
        try:  # registered custom device types resolve to their platform
            from ..device.custom import resolve_type
            r = resolve_type(kind)
            if r is not None:
                return r
        except ImportError:
            pass
    return "cpu"


def TPUPlace(idx: int = 0) -> Place:
    return Place(f"tpu:{idx}")


def CPUPlace(idx: int = 0) -> Place:
    return Place(f"cpu:{idx}")


def get_default_dtype() -> np.dtype:
    from . import state

    return state.DEFAULT_DTYPE


def set_default_dtype(dtype) -> None:
    from . import state

    state.DEFAULT_DTYPE = convert_dtype(dtype)

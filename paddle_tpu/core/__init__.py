from . import state, dtype, autograd, dispatch, tensor  # noqa: F401
from .tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .dtype import Place, TPUPlace, CPUPlace  # noqa: F401

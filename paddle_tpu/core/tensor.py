"""Eager Tensor façade over jax.Array.

Capability analog of ``paddle::Tensor`` + ``phi::DenseTensor`` +
``egr::AutogradMeta`` (SURVEY C8/C16; reference
``paddle/phi/api/include/tensor.h:82``, ``paddle/phi/core/dense_tensor.h:37``,
``paddle/fluid/eager/autograd_meta.h:61``). The device buffer is a jax.Array
(HBM-resident, managed by PJRT — the allocator story of SURVEY C7 is XLA's);
autograd metadata (stop_gradient, grad, producing Node) lives here.

Tensor math methods are installed by ``paddle_tpu.ops`` (the analog of the
generated pybind method table, ``paddle/fluid/pybind/eager_method.cc``).
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import state
from .dtype import Place, convert_dtype


# Active capture tracker (set by paddle_tpu.jit); sees every read/write of
# concrete tensors so whole train steps can be lifted into one XLA program.
# THREAD-LOCAL (ISSUE 15): a capture intercepts only the capturing
# thread's tensor traffic.  With a process-global slot, one rank-thread's
# discovery pass recorded another thread's unrelated eager reads (and
# routed those reads through the foreign tracker), so concurrent
# training loops — the elastic supervisor's multi-rank CPU rig, or any
# two fits in threads — failed nondeterministically with "op structure
# is nondeterministic across calls".  Other modules keep reading
# ``tensor_mod._tracker``; the module-level ``__getattr__`` below
# resolves that name per thread.
class _TrackerSlot(threading.local):
    value = None


_tracker_tls = _TrackerSlot()


def set_tracker(tr):
    old = _tracker_tls.value
    _tracker_tls.value = tr
    return old


def __getattr__(name):
    # PEP 562: ``tensor_mod._tracker`` stays the cross-module read API
    if name == "_tracker":
        return _tracker_tls.value
    raise AttributeError(name)


# process-unique tensor ids for the grad tape (autograd keys grad
# buffers by these).  id() is NOT usable there: a discarded op output
# (e.g. the unused half of a (res, normed) pair) is freed at forward
# time and its id() gets reused by a LATER tensor — whose seeded
# cotangent would then alias onto the dead output's tape slot.
_uid_counter = itertools.count(1)


class Tensor:
    __slots__ = ("_data", "_stop_gradient", "_grad", "_node", "_hooks",
                 "_retain_grad", "name", "_dist", "_flat_view",
                 "_flat_src", "_uid", "__weakref__")

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 name=None):
        if isinstance(data, Tensor):
            data = data._read()
        dtype = convert_dtype(dtype)
        if isinstance(data, jax.ShapeDtypeStruct):
            # lazy (LazyGuard) tensor: abstract shape/dtype, no storage
            if dtype is not None and data.dtype != jnp.dtype(dtype):
                data = jax.ShapeDtypeStruct(
                    data.shape, jnp.dtype(dtype),
                    sharding=getattr(data, "sharding", None))
            from . import lazy as _lazy
            _lazy.register(self)
        elif not isinstance(data, jax.Array) and not isinstance(
                data, jax.core.Tracer):
            if dtype is None and isinstance(data, (float, list)) :
                arr = np.asarray(data)
                if arr.dtype == np.float64:
                    dtype = state.DEFAULT_DTYPE
            data = jnp.asarray(data, dtype=dtype)
            if place is not None:
                data = jax.device_put(data, Place(place).device)
        elif dtype is not None and data.dtype != dtype:
            data = data.astype(dtype)
        self._data = data
        self._uid = next(_uid_counter)
        self._stop_gradient = bool(stop_gradient)
        self._grad: Optional[Tensor] = None
        self._node = None
        self._hooks: list = []
        self._retain_grad = False
        self.name = name
        self._dist = None  # (ProcessMesh, placements) when distributed
        # (FlatStore, slot) when this tensor is a view into a flat
        # optimizer bucket (optimizer/flat.py); _flat_src anchors the
        # lazily-materialized cache to the flat array it was sliced from
        self._flat_view = None
        self._flat_src = None
        tr = _tracker_tls.value
        if tr is not None:
            tr.on_create(self)

    # --- raw data access (all ops funnel through here; the jit capture
    # tracker hooks these, cf. SOT's eval-frame interception, SURVEY L9) ---
    def _read(self):
        fv = self._flat_view
        if fv is not None:
            return fv[0].member_read(self, fv[1])
        tr = _tracker_tls.value
        if tr is not None:
            return tr.on_read(self)
        return self._data

    def _write(self, val):
        fv = self._flat_view
        if fv is not None:
            fv[0].member_write(self, fv[1], val)
            return
        tr = _tracker_tls.value
        if tr is not None:
            tr.on_write(self, val)
            return
        self._data = val

    def _adopt(self, other: "Tensor"):
        """In-place semantics: this tensor takes over ``other``'s value and
        grad history (used by ``__setitem__`` / ``add_`` style ops).

        If ``other``'s producing node consumed ``self`` (x.add_(y) pattern),
        the pre-mutation identity is moved onto a ghost tensor so the tape
        doesn't see a self-loop (the reference handles this with inplace
        version counters, ``paddle/fluid/eager/utils.h`` CheckInplace)."""
        new_node = other._node
        if new_node is not None and any(t is self for t in new_node.inputs):
            ghost = Tensor.__new__(Tensor)
            ghost._data = self._data
            ghost._uid = next(_uid_counter)
            ghost._stop_gradient = self._stop_gradient
            ghost._grad = None
            ghost._node = self._node
            ghost._hooks = []
            ghost._retain_grad = False
            ghost.name = None
            ghost._dist = None
            ghost._flat_view = None
            ghost._flat_src = None
            if self._node is not None:
                try:
                    i = self._node.out_ids.index(self._uid)
                    self._node.out_ids[i] = ghost._uid
                except ValueError:
                    pass
            new_node.inputs = [ghost if t is self else t
                               for t in new_node.inputs]
        self._write(other._data if _tracker_tls.value is None
                    else other._read())
        self._node = new_node
        if new_node is not None:
            try:
                idx = new_node.out_ids.index(other._uid)
                new_node.out_ids[idx] = self._uid
            except ValueError:
                pass
        self._stop_gradient = other._stop_gradient

    # --- properties -----------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        try:
            devs = getattr(self._data, "devices", None)
            if devs is not None:
                return Place(next(iter(devs())))
        except Exception:
            pass
        return Place()

    @property
    def stop_gradient(self):
        return self._stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._stop_gradient = bool(v)

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, g):
        if g is not None and not isinstance(g, Tensor):
            g = Tensor(g)
        self._grad = g

    @property
    def is_leaf(self):
        return self._node is None

    # --- distributed metadata (DistTensor analog, SURVEY D6) -----------
    @property
    def process_mesh(self):
        return self._dist[0] if self._dist is not None else None

    @property
    def placements(self):
        return self._dist[1] if self._dist is not None else None

    def is_dist(self):
        return self._dist is not None

    @property
    def T(self):
        from .. import ops
        return ops.transpose_last2(self)

    @property
    def mT(self):
        from .. import ops
        return ops.transpose_last2(self)

    # --- autograd -------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from .autograd import run_backward
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self, set_to_zero=False):
        """Drop (default) or zero the gradient. ``set_to_zero=True`` zeroes
        in place, keeping the grad object's identity stable — required for
        jit-captured gradient accumulation, where the compiled program
        threads the grad buffer as donated state across calls."""
        if set_to_zero and self._grad is not None:
            import jax.numpy as jnp
            z = jnp.zeros_like(self._grad._read())
            # through the write funnel: a grad that is a flat-bucket view
            # (fused optimizer) must record the local override
            self._grad._write(z)
            self._grad._node = None
        else:
            self._grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self._grad is not None:
            self._grad = Tensor(jnp.zeros_like(self._grad._data))
        else:
            self._grad = None

    def _accumulate_grad(self, g):
        if self._grad is None:
            self._grad = Tensor(g, stop_gradient=True)
        else:
            # accumulate IN PLACE (reference semantics: grads accumulate
            # into the same var). Keeping the grad object's identity stable
            # also lets the jit capture thread it as program state.
            try:
                base = self._grad._read()
            except Exception as e:
                if type(e).__name__ == "GraphBreak":
                    raise type(e)(
                        "gradient existed before capture: cross-call grad "
                        "accumulation cannot compile — clear_grad() before "
                        "the captured call, or zero grads inside the "
                        "captured function (clear_grad(set_to_zero=True))"
                    ) from e
                raise
            acc = base + g
            self._grad._write(acc)
            self._grad._node = None
        tr = _tracker_tls.value
        if tr is not None:
            tr.on_grad_write(self)

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Removable:
            def remove(self_inner):
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass
        return _Removable()

    def retain_grads(self):
        self._retain_grad = True

    def detach(self) -> "Tensor":
        return Tensor(self._read(), stop_gradient=True)

    def detach_(self) -> "Tensor":
        self._node = None
        self._stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from .. import ops
        return ops.assign(self)

    # --- host interop ---------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._read())

    def item(self):
        return self._read().item()

    def tolist(self):
        return np.asarray(self._read()).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._read())
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, *a, **k):
        return self._read().__dlpack__(*a, **k)

    # --- python protocol ------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __bool__(self):
        return bool(self._read())

    def __float__(self):
        return float(self._read())

    def __int__(self):
        return int(self._read())

    def __index__(self):
        return int(self._read())

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        sg = self._stop_gradient
        try:
            body = repr(np.asarray(self._data))
            body = body[body.index("(") + 1: body.rindex(")")] if "(" in body else body
        except Exception:
            body = f"<traced {self._data}>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
                f"stop_gradient={sg},\n       {body})")

    # numpy precedence
    __array_priority__ = 100


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """``paddle.to_tensor`` analog (reference
    ``python/paddle/tensor/creation.py``)."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


class Parameter(Tensor):
    """Trainable tensor. Analog of ``paddle.base.framework.Parameter`` /
    ``EagerParamBase`` (reference ``python/paddle/base/framework.py``)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer",
                 "is_distributed", "need_clip", "no_sync")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.need_clip = True
        self.no_sync = False

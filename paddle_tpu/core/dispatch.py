"""Op dispatch: every framework op funnels through `apply`.

Capability analog of the PHI kernel dispatch + eager ad-function codegen
(SURVEY C9/C15/C16; reference ``paddle/phi/core/kernel_factory.h:316``
SelectKernelOrThrowError and the generated ``*_ad_func`` forward functions of
``eager_gen.py``): unwrap tensors, run the XLA-lowered compute, and — when any
differentiable input requires grad — record a jax.vjp node on the tape.

There is no KernelKey{backend,layout,dtype} selection: XLA owns backend and
layout; dtype promotion is jnp's. That whole reference subsystem collapses
into this one file by design.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from . import state
from .autograd import Node
from .tensor import Tensor

_TRACER_TYPES = (jax.core.Tracer,)
_amp_mod = None  # lazily bound paddle_tpu.amp (breaks the import cycle)


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.result_type(x), jnp.inexact)


def _flatten(args):
    """Shallow-flatten args: Tensors may appear directly or inside one level
    of list/tuple (concat/stack take tensor lists)."""
    tensors = []
    spec = []
    for a in args:
        if isinstance(a, Tensor):
            spec.append(("t", len(tensors)))
            tensors.append(a)
        elif isinstance(a, (list, tuple)) and any(
                isinstance(x, Tensor) for x in a):
            inner = []
            for x in a:
                if isinstance(x, Tensor):
                    inner.append(("t", len(tensors)))
                    tensors.append(x)
                else:
                    inner.append(("c", x))
            spec.append(("seq", type(a), inner))
        else:
            spec.append(("c", a))
    return tensors, spec


def _rebuild(spec, vals):
    out = []
    for s in spec:
        if s[0] == "t":
            out.append(vals[s[1]])
        elif s[0] == "c":
            out.append(s[1])
        else:
            _, typ, inner = s
            seq = [vals[i[1]] if i[0] == "t" else i[1] for i in inner]
            out.append(list(seq) if typ is list else tuple(seq))
    return out


def _check_nan_inf(name, vals):
    for v in vals:
        if isinstance(v, _TRACER_TYPES):
            return
        if jnp.issubdtype(v.dtype, jnp.inexact):
            if not bool(jnp.all(jnp.isfinite(v))):
                raise FloatingPointError(
                    f"Operator '{name}' output contains NaN/Inf "
                    f"(FLAGS check_nan_inf; reference analog "
                    f"paddle/fluid/eager/nan_inf_utils.h)")


# set by paddle_tpu.profiler while recording: fn(name, t0_ns, t1_ns)
_profile_hook = None


def _reraise_with_op_context(name, vals, e):
    """Attach operator context (SURVEY C2 enforce): which op, what
    operand shapes/dtypes. Framework errors and jit-capture control-flow
    exceptions pass through untouched."""
    from . import errors as _errors
    if isinstance(e, _errors.EnforceNotMet):
        raise
    # GraphBreak etc. steer the jit fallback machinery — never wrap
    if type(e).__name__ == "GraphBreak":
        raise
    wrapped = _errors.InvalidArgumentError(
        _errors.op_error_context(name, vals, e))
    wrapped.op_name = name  # machine-readable op id alongside error_code
    raise wrapped from e


def apply(name: str, fn: Callable, *args, **kwargs):
    """Run op ``fn`` over (unwrapped) args; record grad node if needed.

    Keyword args are static attributes; a Tensor passed as a kwarg is
    unwrapped to its value (read through the jit tracker) but NOT
    differentiated — ops must take differentiable operands positionally.
    """
    hook = _profile_hook   # local: the profiler may clear it mid-op
    if hook is not None:
        import time as _time
        _t0 = _time.perf_counter_ns()
        try:
            return _apply(name, fn, *args, **kwargs)
        finally:
            # an observer must never fail the op it observes: a raising
            # hook would mask the op's own result/exception
            try:
                hook(name, _t0, _time.perf_counter_ns())
            except Exception:
                pass
    return _apply(name, fn, *args, **kwargs)


def _apply(name: str, fn: Callable, *args, **kwargs):
    tensors, spec = _flatten(args)
    vals = [t._read() for t in tensors]
    if kwargs:
        kwargs = {k: (v._read() if isinstance(v, Tensor) else v)
                  for k, v in kwargs.items()}

    # AMP O1/O2 cast (analog of the generated ad_func AMP block, SURVEY C16)
    global _amp_mod
    if _amp_mod is None:
        from .. import amp as _amp_mod_imported
        _amp_mod = _amp_mod_imported
    if _amp_mod.amp_state().enabled:
        vals = _amp_mod.amp_cast_inputs(name, vals)

    grad_on = state.is_grad_enabled()
    diff_idx = [i for i, t in enumerate(tensors)
                if grad_on and not t.stop_gradient and _is_float(vals[i])]

    if not diff_idx:
        try:
            out_vals = fn(*_rebuild(spec, vals), **kwargs)
        except Exception as e:
            _reraise_with_op_context(name, vals, e)
        return _wrap_outputs(name, out_vals, node=None, any_grad=False)

    def pure(*dvals):
        merged = list(vals)
        for i, dv in zip(diff_idx, dvals):
            merged[i] = dv
        return fn(*_rebuild(spec, merged), **kwargs)

    # LAZY linearization: run the plain forward now; jax.vjp happens at
    # backward time from the saved input values (autograd.run_backward).
    # Measured (benchmarks/eager_bench.py): eager jax.vjp-per-op costs
    # ~10x a plain dispatch, so grad-enabled forwards that never reach a
    # backward (eval loops, branch probes) must not pay it. The trade: a
    # backwarded op re-runs its primal inside jax.vjp (fwd executes
    # twice); measured fwd+bwd cost moves ~4.7ms -> ~5.5ms per 256x256
    # linear on CPU — eager is dispatch-bound, and the jit path (where
    # throughput lives) traces identically either way.
    try:
        out_vals = fn(*_rebuild(spec, vals), **kwargs)
    except Exception as e:
        _reraise_with_op_context(name, vals, e)
    out, node_outs = _wrap_outputs(name, out_vals, node=..., any_grad=True)
    node = Node(
        name, None,
        inputs=[tensors[i] for i in diff_idx],
        out_ids=[o._uid for o in node_outs],
        out_avals=[jax.ShapeDtypeStruct(o._data.shape, o._data.dtype)
                   for o in node_outs],
        pure=pure,
        seq_type=(tuple if isinstance(out_vals, tuple)
                  else list if isinstance(out_vals, list) else None),
        diff_vals=[vals[i] for i in diff_idx])
    for o in node_outs:
        o._node = node
    return out


def _wrap_outputs(name, out_vals, node, any_grad):
    if state.get_flag("check_nan_inf"):
        flat = out_vals if isinstance(out_vals, (tuple, list)) else [out_vals]
        _check_nan_inf(name, [v for v in flat if hasattr(v, "dtype")])

    def mk(v):
        t = Tensor(v)
        if any_grad and _is_float(v):
            t._stop_gradient = False
        return t

    if isinstance(out_vals, (tuple, list)):
        outs = [mk(v) for v in out_vals]
        if node is None:
            return (tuple(outs) if isinstance(out_vals, tuple) else outs)
        return (tuple(outs) if isinstance(out_vals, tuple) else outs), outs
    t = mk(out_vals)
    if node is None:
        return t
    return t, [t]


def primitive(name_or_fn=None, name: str | None = None):
    """Decorator turning a pure jnp function into a framework op.

    The decorated function's positional args may be Tensors (or lists of
    Tensors); keyword args are static attributes (analog of op Attrs).
    """
    def deco(fn, opname=None):
        opname = (opname or fn.__name__).lstrip("_")
        for suffix in ("_impl",):
            if opname.endswith(suffix):
                opname = opname[: -len(suffix)]

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return apply(opname, fn, *args, **kwargs)

        wrapper.raw = fn  # un-wrapped (jax-level) implementation
        return wrapper

    if callable(name_or_fn):
        return deco(name_or_fn)
    return lambda fn: deco(fn, name_or_fn or name)


def unwrap(x):
    """Tensor|array|scalar -> jax value."""
    if isinstance(x, Tensor):
        return x._read()
    return x


def wrap(v, stop_gradient=True) -> Tensor:
    return Tensor(v, stop_gradient=stop_gradient)

"""Structured errors — the PADDLE_ENFORCE analog (SURVEY C2).

Reference ``paddle/phi/core/enforce.h`` (PADDLE_ENFORCE_* macros) and
``paddle/phi/core/errors.h`` (typed error codes). Python-first shape: a
typed exception hierarchy (each also subclassing the builtin exception
user code would except), ``enforce_*`` check helpers for op/layer
implementations, and an op-context wrapper used by the dispatch funnel so
a failing kernel reports WHICH op failed with WHAT operand shapes/dtypes
— the enforce context stack trace of the reference, minus the C++ frames.
"""
from __future__ import annotations

from typing import Any, Sequence


class EnforceNotMet(RuntimeError):
    """Base of all framework errors (reference ``EnforceNotMet``).

    Every subclass carries a stable ``error_code`` (the analog of the
    reference's ``phi::ErrorCode`` enum, ``paddle/phi/core/errors.h``)
    so tooling and logs can match on code rather than message text.
    """

    error_code = "PDT-E000"  # LEGACY


class InvalidArgumentError(EnforceNotMet, ValueError):
    error_code = "PDT-E001"


class NotFoundError(EnforceNotMet, KeyError):
    error_code = "PDT-E002"


class OutOfRangeError(EnforceNotMet, IndexError):
    error_code = "PDT-E003"


class AlreadyExistsError(EnforceNotMet):
    error_code = "PDT-E004"


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    error_code = "PDT-E005"


class PreconditionNotMetError(EnforceNotMet):
    error_code = "PDT-E006"


class PermissionDeniedError(EnforceNotMet, PermissionError):
    error_code = "PDT-E007"


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    error_code = "PDT-E008"


class UnimplementedError(EnforceNotMet, NotImplementedError):
    error_code = "PDT-E009"


class UnavailableError(EnforceNotMet):
    error_code = "PDT-E010"


class FatalError(EnforceNotMet):
    error_code = "PDT-E011"


class StaticAnalysisError(EnforceNotMet):
    """Raised by the graph lint (``paddle_tpu.analysis``) when
    ``PDTPU_ANALYSIS=error`` and a warn-or-worse finding survives
    suppression."""

    error_code = "PDT-E012"


class NonFiniteStepError(EnforceNotMet, FloatingPointError):
    """Raised by ``resilience.StepGuard`` when MORE than the budgeted
    number of consecutive training steps produced non-finite loss or
    gradients (each bad step inside the budget is skipped in-graph, so
    parameters and optimizer state stay clean up to the raise)."""

    error_code = "PDT-E013"


class CheckpointCorruptError(EnforceNotMet):
    """A checkpoint exists but fails validation — torn write, missing
    shard/data files, or a manifest that doesn't match the files on
    disk. The message lists the offending files/keys; versioned loads
    (``resilience.CheckpointManager``) fall back to the previous
    complete version instead of surfacing this."""

    error_code = "PDT-E014"


class CheckpointNotFoundError(EnforceNotMet, FileNotFoundError):
    """No loadable checkpoint at the given location (no versions at
    all, or every version failed validation)."""

    error_code = "PDT-E015"


class PageBudgetError(EnforceNotMet, ValueError):
    """A serving request can NEVER be satisfied by the engine's page
    pool: ``ceil((prompt + max_new_tokens) / page_size)`` exceeds the
    usable pool (``total_pages - 1``; page 0 is the reserved null page).
    Raised eagerly at ``ContinuousBatchingEngine.add_request`` so an
    unservable request is rejected at submission instead of poisoning
    the queue and crashing ``step()`` after it drains."""

    error_code = "PDT-E016"


class QueueFullError(EnforceNotMet):
    """``ContinuousBatchingEngine.add_request`` under the ``reject``
    admission policy: the bounded queue (``max_queue``) is full. Callers
    shed load (retry later / route elsewhere); the ``block`` policy
    steps the engine until room frees instead of raising."""

    error_code = "PDT-E017"


class NonFiniteLogitsError(EnforceNotMet, FloatingPointError):
    """The serving decode guard found non-finite logits for ONE request
    (device-side finite-ness flag carried through the mixed/decode
    programs). The engine fails only that request — recorded on its
    ``CompletedRequest.error`` with ``finish_reason == "failed"`` — and
    co-resident requests finish unperturbed; this error is never raised
    through the engine loop."""

    error_code = "PDT-E018"


class CacheIntegrityError(EnforceNotMet):
    """The serving page allocator's conservation invariants broke: a
    page double-freed, referenced while on the free list, the reserved
    null page 0 entering circulation, or
    ``pages_in_use + pages_free + cached_pages`` no longer summing to
    the usable pool (``inference/prefix_cache.py``).  Raised by
    ``PrefixCache.check()`` (the randomized property test calls it
    after every mutation) and defensively by the acquire/release paths
    — a raise here means an allocator bug, never a user error."""

    error_code = "PDT-E019"


class EngineStallError(EnforceNotMet, TimeoutError):
    """A serving engine dispatch exceeded the stall-watchdog deadline
    (``observability/watchdog.py``; ``watchdog_stall_ms`` flag /
    ``watchdog_ms`` engine kwarg).  The watchdog captured every
    thread's stack and dumped the flight record + Chrome trace before
    interrupting the stalled dispatch thread, so the caller gets a
    coded, postmortem-ready error instead of a hung ``step()``.  The
    dispatch did not complete — its slot state is untouched, so the
    next ``step()`` re-plans and re-dispatches it bitwise."""

    error_code = "PDT-E020"


class CollectiveTimeoutError(EnforceNotMet, TimeoutError):
    """A collective (``Group.psum_mean``, ``DataParallel.
    apply_collective_grads``, a pipeline ppermute dispatch, or the
    elastic supervisor's store-backed gradient/state allreduce)
    exceeded ``collective_timeout_ms`` without completing — the
    signature of a dead or wedged peer rank, which would otherwise
    hang every survivor forever inside the psum.  The collective
    watchdog (``observability/watchdog.py``) captured every thread's
    stack and dumped the flight record before interrupting the blocked
    caller, so survivors fail coded and the elastic recovery path
    (``resilience/elastic_train.py`` ``FleetSupervisor``) can quiesce,
    reshard and resume instead of waiting on a rank that is never
    coming back."""

    error_code = "PDT-E021"


class StoreTimeoutError(EnforceNotMet, TimeoutError):
    """A TCPStore ``get``/``wait`` deadline expired: the key never
    appeared within the timeout.  Distinguishes a store partition or a
    peer that never published (retry/reshard territory — the elastic
    supervisor treats it as a membership signal) from a programming
    error; subclasses ``TimeoutError`` so existing callers that catch
    the builtin keep working.  Retry/backoff behavior is unchanged —
    a timeout is a SERVED answer ("not there yet"), not a transport
    failure, so it is never retried by the store client."""

    error_code = "PDT-E022"


class CollectiveScheduleError(EnforceNotMet):
    """Ranks disagree on the collective schedule for the upcoming
    session: the whole-program analyzer (``analysis/program.py``)
    hashed each rank's ordered collective schedule — every psum /
    ppermute / all_gather with axis, shape and dtype — and the
    store-backed cross-check at group setup (``verify_schedule``) found
    a mismatch.  Raised *before* the first collective is issued, so the
    divergence fails fast and coded instead of hanging every rank until
    the PDT-E021 watchdog timeout mid-step.  Usual causes: a
    rank-dependent branch around a collective (PDT221 flags the static
    form), or config skew between nodes (different bucket sizes,
    gradient-sync settings, or model shapes)."""

    error_code = "PDT-E023"


class ReplicaLostError(EnforceNotMet, ConnectionError):
    """A fleet-serving replica (``inference.router.FleetRouter``) was
    declared dead — a failed heartbeat, an exhausted placement retry
    budget, a stalled step past the watchdog deadline, or the
    ``router_replica_lost`` drill.  The router bumps the fleet
    generation, writes one coded flight record, and requeues the dead
    replica's queued AND in-flight requests to the surviving replicas
    (from-scratch re-prefill; greedy decode is deterministic and
    batch-invariant, so the requeued outputs are bitwise-identical to
    an unfaulted run).  Callers normally never see this raised — a
    lost replica costs latency, not requests; it only surfaces when
    the LAST replica dies with work still queued."""

    error_code = "PDT-E024"


class MigrationError(EnforceNotMet):
    """A live request migration between serving replicas failed
    (``inference.router.FleetRouter.drain`` / lame-duck / scale-in,
    ISSUE 20): the KV-snapshot transfer exhausted its bounded retry
    budget (the ``router_migration_transient`` drill) or the payload
    failed CRC validation at restore (``engine_snapshot_torn`` — a
    torn transfer).  The fleet degrades, never loses the request: a
    torn snapshot is REJECTED at ``restore_request`` and the source
    replica keeps serving it; an exhausted transfer budget falls back
    to the PR17 cold requeue (front-of-line re-prefill on a survivor,
    bitwise by greedy determinism, demand counted once) with exactly
    one coded flight record carrying this code."""

    error_code = "PDT-E025"


def enforce(cond: bool, msg: str, exc=InvalidArgumentError):
    """PADDLE_ENFORCE: raise ``exc`` with ``msg`` unless ``cond``."""
    if not cond:
        raise exc(msg)


def enforce_eq(a, b, what: str = "value"):
    if a != b:
        raise InvalidArgumentError(
            f"{what} mismatch: expected {b!r}, got {a!r}")


def enforce_gt(a, b, what: str = "value"):
    if not a > b:
        raise InvalidArgumentError(f"{what} must be > {b!r}, got {a!r}")


def enforce_ge(a, b, what: str = "value"):
    if not a >= b:
        raise InvalidArgumentError(f"{what} must be >= {b!r}, got {a!r}")


def enforce_shape(x, expected: Sequence, what: str = "tensor"):
    """Check a shape against a pattern; ``None``/-1 dims are wildcards."""
    shape = tuple(getattr(x, "shape", x))
    if len(shape) != len(expected) or any(
            e not in (None, -1) and int(e) != int(s)
            for s, e in zip(shape, expected)):
        raise InvalidArgumentError(
            f"{what}: expected shape {list(expected)}, got {list(shape)}")


def enforce_dtype(x, allowed, what: str = "tensor"):
    d = str(getattr(x, "dtype", x))
    allowed = [allowed] if isinstance(allowed, str) else list(allowed)
    if not any(a in d for a in allowed):
        raise InvalidArgumentError(
            f"{what}: dtype must be one of {allowed}, got {d}")


def _describe(v: Any) -> str:
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is None:
        return repr(v)[:40]
    return f"{dtype}[{','.join(str(s) for s in shape)}]"


def op_error_context(name: str, vals: Sequence, err: Exception) -> str:
    """Build the operator-context message the dispatch funnel attaches
    (the enforce context stack of the reference)."""
    args = ", ".join(_describe(v) for v in vals)
    # the original error's stable code when it has one, else the code of
    # the InvalidArgumentError wrapper this message is built for
    code = getattr(type(err), "error_code", None) or \
        InvalidArgumentError.error_code
    return (f"Error raised by operator '{name}' with operands ({args}).\n"
            f"  {type(err).__name__}: {err} [{code}]")

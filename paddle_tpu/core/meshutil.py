"""Small shard_map helpers shared by the manual-collective modules
(ring attention, pipeline, MoE, DP grad sync)."""
from __future__ import annotations

from jax import lax


def pvary(xs, axes):
    """Mark values as varying over the given manual mesh axes (shard_map's
    vma type system; the API name differs across jax versions — and the
    type system does not exist at all before jax 0.5, where this is a
    no-op)."""
    axes = tuple(axes)
    if not axes:
        return xs
    if hasattr(lax, "pcast"):
        return lax.pcast(xs, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(xs, axes)
    return xs  # jax < 0.5: no varying-manual-axes type system


def axis_size(axis):
    """``lax.axis_size`` across jax versions (pre-0.5 lacks it; the size
    of a manual mesh axis is the psum of 1 over it — a compile-time
    constant, not a runtime collective)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def partial_auto_supported() -> bool:
    """True when ``shard_map`` can leave some mesh axes to GSPMD
    (``axis_names`` a strict subset).  The legacy experimental
    shard_map (jax < 0.5) cannot: its eager impl raises
    ``NotImplementedError`` outright when ``auto`` is non-empty, and
    even under jit the old SPMD partitioner hard-crashes on
    ``ppermute``/``all_gather`` inside a partial-auto region (a
    ``PartitionId``/manual-subgroup CHECK failure) — so callers that
    mix manual collectives with a GSPMD-owned TP axis must demote on
    the legacy path instead of splitting the program."""
    import jax
    return hasattr(jax, "shard_map")


def legacy_manual_vjp() -> bool:
    """True on the legacy experimental shard_map (jax < 0.5): its AD has
    no varying-axes (vma) type system, so a ``jax.vjp`` taken INSIDE the
    body produces purely LOCAL cotangents — callers must psum cotangents
    of replicated inputs over the axes they are invariant on themselves
    (the modern path inserts those psums automatically when the seed is
    ``pvary``-marked)."""
    import jax
    return not hasattr(jax, "shard_map")


def shard_map(f, mesh, in_specs, out_specs, axis_names=None):
    """``jax.shard_map`` across jax versions.

    jax >= 0.5 spells it ``jax.shard_map(f, mesh=..., in_specs=...,
    out_specs=..., axis_names=...)``; before that it lives at
    ``jax.experimental.shard_map.shard_map`` with ``auto=`` (the
    COMPLEMENT of ``axis_names`` — axes left to GSPMD) instead of
    ``axis_names`` and a ``check_rep`` flag whose replication checker
    predates the vma type system and rejects valid psum/where patterns
    the modern checker accepts — so it is disabled on the legacy path.
    """
    import jax
    smap = getattr(jax, "shard_map", None)
    if smap is not None:
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = frozenset(axis_names)
        return smap(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    **kw)
    from jax.experimental.shard_map import shard_map as legacy
    kw = {}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kw["auto"] = auto
    return legacy(f, mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False, **kw)

"""Small shard_map helpers shared by the manual-collective modules
(ring attention, pipeline, MoE)."""
from __future__ import annotations

from jax import lax


def pvary(xs, axes):
    """Mark values as varying over the given manual mesh axes (shard_map's
    vma type system; the API name differs across jax versions)."""
    axes = tuple(axes)
    if not axes:
        return xs
    if hasattr(lax, "pcast"):
        return lax.pcast(xs, axes, to="varying")
    return lax.pvary(xs, axes)

"""Define-by-run autograd engine over jax.vjp.

Capability analog of the reference eager autograd (SURVEY C16:
``paddle/fluid/eager/grad_node_info.h:197`` GradNodeBase/Edge,
``paddle/fluid/eager/backward.cc:105`` RunBackward queue engine,
``tensor_wrapper.h`` forward-tensor saving) — but TPU-native: instead of
hand-written grad kernels, every op records the ``jax.vjp`` linearization of
its XLA computation, and the backward engine is the same reverse topological
queue walk with per-tensor consumer counting.
"""
from __future__ import annotations

import contextlib
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import state


class Node:
    """One recorded op in the grad graph. Analog of ``egr::GradNodeBase``.

    ``vjp_fn`` may be None: the linearization is built LAZILY at backward
    time from ``pure`` + ``diff_vals`` (the forward-time input snapshot),
    so grad-enabled forwards that never backward pay no jax.vjp cost."""

    __slots__ = ("name", "vjp_fn", "inputs", "out_ids", "out_avals",
                 "consumed", "pure", "seq_type", "diff_vals")

    def __init__(self, name, vjp_fn, inputs, out_ids, out_avals, pure=None,
                 seq_type=None, diff_vals=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = inputs        # diff-input Tensors (strong refs = TensorWrapper)
        self.out_ids = out_ids      # ._uid of each output tensor (never
                                    # reused, unlike id() of a freed one)
        self.out_avals = out_avals  # ShapeDtypeStruct per output
        self.pure = pure            # primal fn of the diff inputs (for create_graph)
        self.seq_type = seq_type    # None | tuple | list: primal output pytree
        self.diff_vals = diff_vals  # input values for lazy linearization
        self.consumed = False

    def pack_cots(self, cots):
        if self.seq_type is None:
            return cots[0]
        return self.seq_type(cots)

    def __repr__(self):
        return f"<Node {self.name} n_in={len(self.inputs)} n_out={len(self.out_ids)}>"


def _zero_cotangent(aval):
    if jnp.issubdtype(aval.dtype, jnp.floating) or jnp.issubdtype(
        aval.dtype, jnp.complexfloating
    ):
        return jnp.zeros(aval.shape, aval.dtype)
    # Non-differentiable (int/bool) outputs take float0 cotangents under jax.vjp.
    return np.zeros(aval.shape, dtype=jax.dtypes.float0)


def _accum(buf, key, val):
    old = buf.get(key)
    buf[key] = val if old is None else old + val


def _val(g):
    from .tensor import Tensor

    return g._read() if isinstance(g, Tensor) else g


def _cast(g, dtype):
    from .tensor import Tensor

    if isinstance(g, Tensor):
        return Tensor(g._read(), dtype=dtype, stop_gradient=g.stop_gradient)
    return g.astype(dtype)


def _vjp_through_dispatch(n, out_grads):
    """create_graph path: re-linearize the primal so the backward op itself
    is recorded on the tape (double/higher-order grad — the analog of the
    reference's double_grad node generation in eager_gen.py)."""
    from . import dispatch
    from .tensor import Tensor

    float_pos = [i for i, a in enumerate(n.out_avals)
                 if jnp.issubdtype(a.dtype, jnp.inexact)]
    g_args = [out_grads[i] if isinstance(out_grads[i], Tensor)
              else Tensor(out_grads[i]) for i in float_pos]
    n_g = len(g_args)
    avals, pure = n.out_avals, n.pure

    def call(*a):
        gs, xs = a[:n_g], a[n_g:]
        full, gi = [], iter(gs)
        for i, av in enumerate(avals):
            if i in float_pos:
                full.append(next(gi))
            else:
                full.append(np.zeros(av.shape, dtype=jax.dtypes.float0))
        _, vjp = jax.vjp(pure, *xs)
        return tuple(vjp(n.pack_cots(full)))

    outs = dispatch.apply("grad::" + n.name, call, *g_args, *n.inputs)
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    return list(outs)


def run_backward(tensors, grad_tensors=None, retain_graph=False, accumulate=True,
                 inputs=None, create_graph=False):
    """Reverse-walk the recorded graph from ``tensors``.

    Mirrors ``egr::RunBackward`` (reference ``paddle/fluid/eager/backward.cc:105``):
    seed output grads, count consumers, queue-process nodes whose outputs are
    final, accumulate leaf grads.

    If ``accumulate`` write ``.grad`` on leaves; always returns a dict
    ``tensor._uid -> grad array`` for tensors in ``inputs`` (paddle.grad
    path).
    """
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    grad_buf: dict[int, Any] = {}
    keepalive: dict[int, Tensor] = {}

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._node is None:
            raise RuntimeError(
                "backward() called on a tensor with stop_gradient=True and no "
                "grad graph")
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g = jnp.ones(t.shape, t.dtype)
        else:
            g = g._read() if isinstance(g, Tensor) else jnp.asarray(g)
        if create_graph:
            g = Tensor(g, stop_gradient=True)
        _accum(grad_buf, t._uid, g)
        keepalive[t._uid] = t

    # --- build reachable node set (walk producers through inputs) ---
    reachable: set[int] = set()
    nodes: dict[int, Node] = {}
    stack = [t._node for t in tensors if t._node is not None]
    while stack:
        n = stack.pop()
        if id(n) in reachable:
            continue
        if n.consumed:
            raise RuntimeError(
                f"grad graph for op '{n.name}' already freed; pass "
                "retain_graph=True to backward through it again")
        reachable.add(id(n))
        nodes[id(n)] = n
        for ti in n.inputs:
            if ti._node is not None:
                stack.append(ti._node)

    # consumer_count[tensor_uid] = reachable nodes consuming that tensor
    consumer_count: dict[int, int] = {}
    for n in nodes.values():
        for ti in n.inputs:
            consumer_count[ti._uid] = consumer_count.get(ti._uid, 0) + 1
            keepalive[ti._uid] = ti

    # node_wait[node] = its outputs that still have pending consumers
    node_wait: dict[int, int] = {}
    producer_of: dict[int, Node] = {}
    for n in nodes.values():
        for oid in n.out_ids:
            producer_of[oid] = n
        node_wait[id(n)] = sum(
            1 for oid in n.out_ids if consumer_count.get(oid, 0) > 0)

    processed: list[Node] = []
    queue = [n for n in nodes.values() if node_wait[id(n)] == 0]

    finalized: set[int] = set()

    def finalize(tid):
        """All consumers of tensor tid processed: its grad is final."""
        if tid in finalized:
            return
        finalized.add(tid)
        t = keepalive.get(tid)
        if t is None:
            return
        g = grad_buf.get(tid)
        if g is not None and t._hooks:
            for h in t._hooks:
                out = h(g if isinstance(g, Tensor) else _wrap_grad(t, g))
                if out is not None:
                    g = out if isinstance(out, Tensor) else jnp.asarray(out)
            grad_buf[tid] = g
        is_leaf = t._node is None
        if accumulate and g is not None and not t.stop_gradient and (
                is_leaf or t._retain_grad):
            t._accumulate_grad(_val(g))
        prod = producer_of.get(tid)
        if prod is not None and id(prod) in node_wait:
            node_wait[id(prod)] -= 1
            if node_wait[id(prod)] == 0:
                queue.append(prod)

    while queue:
        n = queue.pop()
        out_grads = []
        for oid, aval in zip(n.out_ids, n.out_avals):
            g = grad_buf.get(oid)
            if g is None:
                g = _zero_cotangent(aval)
            elif _val(g).dtype != aval.dtype and jnp.issubdtype(
                    aval.dtype, jnp.floating):
                g = _cast(g, aval.dtype)
            out_grads.append(g)
        if create_graph and n.pure is not None:
            cots = _vjp_through_dispatch(n, out_grads)
        else:
            out_grads = [_val(g) for g in out_grads]
            if n.vjp_fn is None:  # lazy: linearize on first backward
                try:
                    _, n.vjp_fn = jax.vjp(n.pure, *n.diff_vals)
                except Exception as e:
                    from . import errors as _errors
                    raise _errors.InvalidArgumentError(
                        _errors.op_error_context(
                            "grad::" + n.name, n.diff_vals, e)) from e
            cots = n.vjp_fn(n.pack_cots(out_grads))
        processed.append(n)
        for ti, cot in zip(n.inputs, cots):
            from .tensor import Tensor as _T
            if cot is not None and not (
                    not isinstance(cot, _T) and hasattr(cot, "dtype")
                    and cot.dtype == jax.dtypes.float0):
                _accum(grad_buf, ti._uid, cot)
            consumer_count[ti._uid] -= 1
            if consumer_count[ti._uid] == 0:
                finalize(ti._uid)

    # Seed tensors with no reachable consumers are final too (leaf seeds).
    for t in tensors:
        if consumer_count.get(t._uid, 0) == 0:
            finalize(t._uid)

    if not retain_graph:
        for n in processed:
            n.vjp_fn = None
            n.inputs = ()
            n.pure = None  # frees the closure pinning forward buffers
            n.diff_vals = None
            n.consumed = True

    if inputs is not None:
        return {t._uid: grad_buf.get(t._uid) for t in inputs}
    return None


def _wrap_grad(t, g):
    from .tensor import Tensor

    return Tensor(g, stop_gradient=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """``paddle.grad`` analog (reference ``python/paddle/autograd/``):
    grads of outputs w.r.t. inputs without touching ``.grad``."""
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if retain_graph is None:
        retain_graph = create_graph
    res = run_backward(outputs, grad_outputs, retain_graph=retain_graph,
                       accumulate=False, inputs=inputs,
                       create_graph=create_graph)
    grads = []
    for t in inputs:
        g = res.get(t._uid)
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "one of the input tensors receives no gradient "
                    "(set allow_unused=True to get None)")
            grads.append(None)
        elif isinstance(g, Tensor):
            grads.append(g)
        else:
            grads.append(Tensor(g, stop_gradient=not create_graph))
    return grads


@contextlib.contextmanager
def no_grad():
    old = state.set_grad_enabled(False)
    try:
        yield
    finally:
        state.set_grad_enabled(old)


@contextlib.contextmanager
def enable_grad():
    old = state.set_grad_enabled(True)
    try:
        yield
    finally:
        state.set_grad_enabled(old)


class set_grad_enabled(contextlib.ContextDecorator):
    def __init__(self, mode: bool):
        self._mode = mode
        self._old = None

    def __enter__(self):
        self._old = state.set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        state.set_grad_enabled(self._old)
        return False

"""LazyGuard — deferred (abstract) parameter initialization.

Capability analog of ``paddle.LazyGuard`` (reference
``python/paddle/nn/initializer/lazy_init.py``): layers built under the
guard allocate NO real storage — parameters carry only shape/dtype (a
``jax.ShapeDtypeStruct``), plus a sharding once annotated. TPU-native
purpose: author a model whose full parameter set exceeds host memory,
pin its GSPMD shardings (``shard_gpt`` etc.), and AOT-lower the real
captured train step with :func:`paddle_tpu.jit.aot_lower` — abstract
inputs, no execution — for scale validation and compile-cache priming.

A lazy tensor cannot be computed with eagerly; any op on it raises when
jax tries to treat the ShapeDtypeStruct as a value. That mirrors the
reference, where lazy parameters hold no value until ``initialize``.
"""
from __future__ import annotations

import weakref

_active = False
_registry: "weakref.WeakSet" = weakref.WeakSet()


class LazyGuard:
    """Context manager: parameters created inside are abstract."""

    def __enter__(self):
        global _active
        self._prev = _active
        _active = True
        return self

    def __exit__(self, *exc):
        global _active
        _active = self._prev
        return False


def in_lazy_mode() -> bool:
    return _active


def register(t) -> None:
    """Track a lazily-created tensor (jit.aot_lower enumerates these to
    turn them into abstract program inputs)."""
    _registry.add(t)


def lazy_tensors():
    """Live lazily-created tensors whose data is still abstract."""
    import jax
    return [t for t in _registry
            if isinstance(getattr(t, "_data", None), jax.ShapeDtypeStruct)]

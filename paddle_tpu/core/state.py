"""Global framework state: default dtype, grad mode, RNG, flags.

Capability analog of the reference flags/env system (SURVEY C1,
``paddle/common/flags.cc``) and the global tracer state
(``paddle/fluid/imperative/tracer.h``).
"""
from __future__ import annotations

import os
import threading

import numpy as np

DEFAULT_DTYPE = np.dtype("float32")

# --- flags registry (analog of PHI_DEFINE_EXPORTED_*; env override via
# PDTPU_<name>, mirroring FLAGS_<name> env behavior in flags_native.cc) ---
_FLAGS: dict[str, object] = {}
_FLAG_DEFS: dict[str, tuple[type, object, str]] = {}


def define_flag(name: str, default, help_str: str = ""):
    ftype = type(default)
    env = os.environ.get("PDTPU_" + name.upper())
    val = default
    if env is not None:
        if ftype is bool:
            val = env.lower() in ("1", "true", "yes")
        else:
            val = ftype(env)
    _FLAG_DEFS[name] = (ftype, default, help_str)
    _FLAGS[name] = val
    return val


def get_flags(names=None):
    if names is None:
        return dict(_FLAGS)
    if isinstance(names, str):
        names = [names]
    return {n: _FLAGS[n] for n in names}


def set_flags(flags: dict):
    for k, v in flags.items():
        if k not in _FLAG_DEFS:
            raise KeyError(f"unknown flag {k!r}")
        _FLAGS[k] = _FLAG_DEFS[k][0](v)


def get_flag(name: str):
    return _FLAGS[name]


# Core flags (subset of the 138 reference flags that are meaningful on TPU).
define_flag("check_nan_inf", False, "scan op outputs for nan/inf (numeric sanitizer)")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; 3: only log stats")
define_flag("benchmark", False, "sync + time every op")
define_flag("eager_delete_tensor_gb", 0.0, "GC threshold (no-op under XLA; kept for parity)")
define_flag("use_stride_kernel", True, "allow view/stride ops to alias (jax always copies-on-write)")
define_flag("log_level", 0, "framework VLOG level")
define_flag("analysis", "warn",
            "graph-lint mode (paddle_tpu.analysis): off = analyzers "
            "skipped entirely; warn = findings surface as LintWarnings "
            "(notes to the logger); error = any warn-or-worse finding "
            "raises StaticAnalysisError. Env override PDTPU_ANALYSIS.")
define_flag("fused_opt", True,
            "flat-buffer multi-tensor optimizer path (optimizer/flat.py "
            "+ ops/pallas/fused_optimizer.py): dtype-bucketed flat "
            "params/grads/moments updated by one fused kernel per "
            "bucket. PDTPU_FUSED_OPT=off force-disables (per-param "
            "fallback). Exotic cases (per-param LR/clip/regularizer, "
            "sharded or lazy params, unsupported optimizers/clips) fall "
            "back automatically.")
define_flag("serving_max_queue", 0,
            "bounded admission queue for inference.ContinuousBatching"
            "Engine: add_request past this depth applies the queue "
            "policy. 0 = unbounded (lab default; PDT109 notes it). "
            "Engine kwarg max_queue overrides per instance.")
define_flag("serving_queue_policy", "reject",
            "what a full serving queue does to add_request: 'reject' "
            "raises QueueFullError (PDT-E017) so the caller sheds "
            "load; 'block' steps the engine until room frees. Engine "
            "kwarg queue_policy overrides per instance.")
define_flag("serving_deadline_ms", 0.0,
            "default per-request deadline for the serving engine, "
            "checked at step boundaries (finish_reason 'timeout'). "
            "0 = no deadline. add_request(deadline_ms=...) overrides "
            "per request.")
define_flag("serving_dispatch_retries", 3,
            "bounded resilience.retry RE-attempts after a transient "
            "failure of a serving engine dispatch (N retries = N+1 "
            "attempts; 0 disables retry). Transient ConnectionErrors "
            "— incl. the injected engine_dispatch fault site — are "
            "absorbed; anything else propagates.")
define_flag("serving_prefix_cache", True,
            "cross-request KV prefix cache for the serving engine "
            "(inference/prefix_cache.py): admissions map shared "
            "prompt/few-shot prefixes onto already-written KV pages "
            "via a radix index (copy-on-write at the divergence page, "
            "LRU eviction under pool pressure) and preempt-requeue "
            "re-admission restores from its own published pages "
            "instead of re-prefilling. Outputs are bitwise-identical "
            "either way. PDTPU_SERVING_PREFIX_CACHE=off restores "
            "uncached admission; engine kwarg prefix_cache overrides "
            "per instance. PDT110 notes high-traffic engines built "
            "with the cache off.")
# String spellings that disable the prefix cache, shared by the engine's
# prefix_cache kwarg parse and the PDT110 lint so they cannot diverge.
PREFIX_CACHE_OFF_SPELLINGS = ("off", "false", "0", "no")
define_flag("serving_kv_quant", False,
            "int8 KV page pools for the serving engine (ISSUE 7): "
            "pages store int8 with per-page scale side-pools "
            "(quantization.kv_quantize), dequantized inside the ragged "
            "paged-attention kernel's DMA loop — KV bytes per resident "
            "sequence drop >2x (serving_bench recomputes the roofline "
            "from the quantized bytes) at token-identical greedy "
            "outputs on the serving parity suite. Default off; "
            "PDTPU_SERVING_KV_QUANT=1 (or engine kwarg kv_quant) "
            "enables, and the off state is bitwise-identical to the "
            "pre-quantization fp path.")
# Spellings that toggle KV quantization in the engine's kv_quant kwarg
# (off set shared with the prefix cache — one convention for on/off
# strings).  Unlike prefix_cache (bitwise-identical either way), this
# switch changes numerics, so unrecognized spellings must never
# silently enable it: the engine raises, the env alias ignores.
KV_QUANT_OFF_SPELLINGS = PREFIX_CACHE_OFF_SPELLINGS
KV_QUANT_ON_SPELLINGS = ("on", "true", "1", "yes")
# Both env spellings — the canonical PDTPU_SERVING_KV_QUANT the flag
# registry derives and the short PDTPU_KV_QUANT alias — parse through
# the SAME on/off sets (define_flag's bool parse misses "on"), the
# alias taking precedence when both are set.
for _env_name in ("PDTPU_SERVING_KV_QUANT", "PDTPU_KV_QUANT"):
    _env_kvq = os.environ.get(_env_name)
    if _env_kvq is not None:
        if _env_kvq.lower() in KV_QUANT_ON_SPELLINGS:
            _FLAGS["serving_kv_quant"] = True
        elif _env_kvq.lower() in KV_QUANT_OFF_SPELLINGS:
            _FLAGS["serving_kv_quant"] = False
del _env_name, _env_kvq
define_flag("serving_megakernel", False,
            "fused decode megakernel path for the serving engine "
            "(ISSUE 18, ops/pallas/fused_decode_qkv.py + "
            "fused_decode_mlp.py): each decode layer runs as ~3 fused "
            "dispatches (norm+QKV+RoPE+paged-KV-append, attention, "
            "out-proj+residual+MLP+residual) plus one guarded-argmax "
            "sampling epilogue riding the final norm+lm_head, instead "
            "of ~10 unfused ops. Token streams are bitwise-identical "
            "either way (the megakernel replays the exact unfused op "
            "order); only dispatches-per-token moves. Default off "
            "until the TPU round lands; engine kwarg megakernel "
            "overrides per instance. PDT120 notes overload-tuned "
            "engines built with the megakernel off-spelled.")
# Spellings for the engine's megakernel kwarg — same convention as
# kv_quant (strict parse: unrecognized spellings raise rather than
# silently picking a path, since dispatch count is a measured claim).
MEGAKERNEL_OFF_SPELLINGS = KV_QUANT_OFF_SPELLINGS
MEGAKERNEL_ON_SPELLINGS = KV_QUANT_ON_SPELLINGS
define_flag("serving_spec_decode", False,
            "speculative decoding for the serving engine (ISSUE 9, "
            "inference/speculative.py): per decode step each slot "
            "submits its current token plus K proposed tokens as one "
            "ragged verify segment (q_lens=K+1 through the existing "
            "mixed program) and advances by the longest draft prefix "
            "the target model agrees with plus one free token. Greedy "
            "outputs are bitwise-identical to the flag off; only "
            "tokens-per-dispatch moves. Engine kwarg spec_decode "
            "overrides per instance.")
define_flag("serving_spec_k", 4,
            "draft tokens proposed per slot per speculative decode "
            "step (the verify segment is K+1 rows, padded to the "
            "engine's q_block). Engine kwarg spec_k overrides.")
define_flag("serving_spec_proposer", "ngram",
            "default proposer for spec_decode engines: 'ngram' is the "
            "model-free prompt-lookup proposer (zero extra FLOPs). "
            "Pass a Proposer instance (e.g. DraftModelProposer) via "
            "the engine's spec_proposer kwarg for a draft model.")
define_flag("serving_spec_temperature", 0.0,
            "speculative-mode sampling temperature. 0 (default) = "
            "greedy token-equality acceptance, bitwise vs plain "
            "decode. > 0 samples the target's tokens — pair it with "
            "serving_spec_rejection_sampling or the output "
            "distribution skews toward the proposer (PDT113).")
define_flag("serving_spec_rejection_sampling", False,
            "lossless speculative SAMPLING acceptance: drafts accept "
            "with probability p(draft) under the temperature-scaled "
            "target distribution and rejections resample from the "
            "residual, so the output distribution is exactly the "
            "target's. Only meaningful with "
            "serving_spec_temperature > 0.")
define_flag("serving_tp", 0,
            "default tensor-parallel degree for serving engines "
            "(ISSUE 13): a ContinuousBatchingEngine constructed "
            "WITHOUT mesh= builds a 1-axis mesh over the first N "
            "devices and shards its two compiled programs over it — "
            "weights column/row split per the canonical Megatron "
            "rules, KV page pools sharded by kv-head (GQA-aware), "
            "block tables/lengths replicated, one psum at the "
            "attention output and the MLP reduce. 0/1 = single-device "
            "(today's engine, bitwise). Engine kwargs mesh=/tp_axis= "
            "override per instance; greedy outputs are token-identical "
            "to the single-device engine either way. PDT116 notes "
            "engines built single-device while a multi-device mesh is "
            "in scope.")
define_flag("serving_disagg_prefill_workers", 1,
            "default prefill-group size for inference.DisaggServer "
            "(disaggregated prefill/decode serving): how many engine "
            "instances admit + chunk-prefill new requests before the "
            "KV-page handoff. DisaggServer kwarg prefill_workers "
            "overrides.")
define_flag("serving_disagg_decode_workers", 1,
            "default decode-group size for inference.DisaggServer: "
            "how many engine instances run the latency-bound decode "
            "windows on handed-off KV pages. DisaggServer kwarg "
            "decode_workers overrides.")
define_flag("serving_disagg_handoff_retries", 3,
            "bounded resilience.retry RE-attempts for one KV-page "
            "handoff transfer (KVPageTransport.ship) after a "
            "transient ConnectionError — incl. the injected "
            "engine_handoff_transient fault site. N retries = N+1 "
            "attempts; 0 disables retry.")
define_flag("serving_fleet_replicas", 2,
            "default live-replica count for inference.FleetRouter "
            "when replicas= is an int or omitted: how many "
            "ContinuousBatchingEngine workers the router builds over "
            "the shared model (compiled serving programs cache on the "
            "model, so N same-geometry replicas compile once). "
            "FleetRouter kwarg replicas overrides.")
define_flag("serving_fleet_affinity", True,
            "prefix-cache-aware placement for inference.FleetRouter: "
            "route each prompt to the replica whose radix prefix "
            "cache reports the longest page-aligned hit "
            "(cached_prefix_tokens), spilling to the least-loaded "
            "replica when no replica holds the prefix. False = "
            "deterministic round-robin over the live replicas. "
            "FleetRouter kwarg affinity overrides.")
define_flag("serving_fleet_heartbeat_ms", 0.0,
            "fleet-router replica heartbeat timeout (ms): a live "
            "replica whose last successful step is older than this is "
            "declared dead (generation bump, coded flight record, "
            "queued + in-flight requests requeued to survivors). 0 "
            "disables the timeout detector — in-process replicas beat "
            "synchronously, so the timeout matters for rpc-backed "
            "replicas. FleetRouter kwarg heartbeat_timeout_ms "
            "overrides.")
define_flag("serving_fleet_dispatch_retries", 3,
            "bounded resilience.retry RE-attempts for one fleet-"
            "router placement dispatch (replica add_request) after a "
            "transient ConnectionError — incl. the injected "
            "router_dispatch_transient fault site. Exhausting the "
            "budget declares the replica dead and requeues the "
            "request. N retries = N+1 attempts; 0 disables retry. "
            "FleetRouter kwarg dispatch_retries overrides.")
define_flag("serving_fleet_scaleout_timeout_ms", 0.0,
            "watchdog deadline (ms) for admitting a standby replica "
            "on a sustained fleet-SLO burn-rate breach: past it the "
            "admission surfaces EngineStallError (PDT-E020) with a "
            "flight record and the fleet degrades gracefully on the "
            "live replicas. 0 disarms the watchdog (the "
            "router_scaleout_stall drill then raises after its "
            "bounded spin). FleetRouter kwarg scaleout_timeout_ms "
            "overrides.")
define_flag("serving_fleet_scalein_hold_s", 30.0,
            "how long the fleet SLO must stay recovered (no breached "
            "spec) before the fleet router drains a scaled-out "
            "standby back: the replica stops taking placements and "
            "returns to standby once idle. FleetRouter kwarg "
            "scalein_hold_s overrides.")
define_flag("serving_fleet_slo", "",
            "fleet-wide objectives for the serving router "
            "(inference/router.py): same spec grammar as serving_slo "
            "('queue_p95_ms=200,goodput=0.99'), evaluated over the "
            "ROUTER's registry (admission-queue wait, fleet finish "
            "reasons) rather than any one replica's. A sustained "
            "burn-rate breach admits a standby replica (scale-out); "
            "holding recovered for serving_fleet_scalein_hold_s "
            "drains it back. '' (default) arms nothing — no "
            "SLO-driven scaling; FleetRouter kwarg fleet_slo "
            "overrides.")
define_flag("serving_migration", False,
            "live request migration for the serving fleet (ISSUE 20): "
            "FleetRouter drain/scale-in/lame-duck MIGRATES resident "
            "requests warm to surviving replicas over the PR13 "
            "KVPageTransport (engine snapshot_request/restore_request) "
            "instead of waiting for in-flight decode or cold-requeuing "
            "prefilled work. Bitwise: a migrated stream equals the "
            "unmigrated stream token-for-token (greedy decode is "
            "deterministic and KV bytes are a pure function of the "
            "token prefix). Off (default) = PR17 behavior — drain "
            "waits, death cold-requeues; PDT122 notes routers that "
            "drain cold while deadlines/SLOs are configured. "
            "FleetRouter kwarg migration overrides.")
define_flag("serving_lameduck_ms", 0.0,
            "degraded-heartbeat age (ms) past which a live fleet "
            "replica enters LAME-DUCK: new placements stop and its "
            "residents are proactively migrated to survivors BEFORE "
            "the serving_fleet_heartbeat_ms death deadline, so a "
            "planned preemption (maintenance event, preemptible "
            "capacity) loses zero prefill work. Must be smaller than "
            "the heartbeat timeout to matter; 0 disables the detector "
            "(SIGTERM via resilience.preempt still triggers lame-duck "
            "when serving_migration is on). FleetRouter kwarg "
            "lameduck_ms overrides.")
define_flag("serving_migration_retries", 3,
            "bounded resilience.retry RE-attempts for one live-"
            "migration snapshot transfer (KVPageTransport."
            "ship_snapshot) after a transient ConnectionError — incl. "
            "the injected router_migration_transient fault site. "
            "Exhausting the budget writes one MigrationError "
            "(PDT-E025) flight record and falls back to the PR17 cold "
            "requeue (demand counted once). N retries = N+1 attempts; "
            "0 disables retry. FleetRouter kwarg migration_retries "
            "overrides.")
define_flag("dp_overlap_grad_sync", False,
            "overlap-scheduled bucketed DP gradient sync "
            "(distributed/overlap.py): DataParallel registers per-param "
            "hooks and issues one psum-mean per size-capped bucket as "
            "each bucket's grads finalize DURING backward, so the "
            "collectives hide behind the remaining backward compute; "
            "apply_collective_grads() drains the pending results. "
            "Bitwise-identical to the serialized sync. Off = the "
            "pre-overlap serialized path; DataParallel kwarg "
            "overlap_grad_sync overrides per instance. comm_ms / "
            "overlap_frac surface through the observability registry. "
            "PDT114 notes eager train loops that serialize the sync.")
define_flag("pp_overlap_p2p", True,
            "pipeline p2p/compute overlap (fleet/pipeline.py): issue "
            "each stage's ppermute activation/cotangent sends BEFORE "
            "the independent work of the same tick (output banking, "
            "leaf-grad accumulation) so XLA can run the ICI transfer "
            "under compute. Pure reordering of independent ops — "
            "values are bitwise-identical either way; off restores the "
            "send-last order for A/B timing.")
define_flag("train_glue_fusion", False,
            "fused residual-add+norm training glue kernels (ISSUE 19, "
            "ops/pallas/fused_residual_norm.py): GPT/LLaMA training "
            "forwards thread a pending-branch through the block stack "
            "so every (residual add, pre-norm) pair — and the final "
            "norm — runs as ONE fused fwd/bwd Pallas dispatch; BERT's "
            "post-LN pairs fuse in place. Train-mode only (eval/serving "
            "keep the unfused path and its numerics). Default off: the "
            "standalone Pallas LN measured as a fusion BARRIER "
            "in-context (+6 ms/step on the GPT-124M bench, see "
            "nn/functional/norm.py) — the fused glue path ships dark "
            "until the TPU round prices it end-to-end, the "
            "serving_megakernel precedent. Numerics differ from the "
            "unfused chain by norm-formula ulps (two-pass variance vs "
            "E[x^2]-E[x]^2), so this is an A/B knob, not a "
            "bitwise-neutral toggle.")
# Spellings for the glue-fusion knob (same strict convention as
# kv_quant/megakernel: dispatch count is a measured claim, so an
# unrecognized spelling must raise, never silently pick a path).
GLUE_FUSION_OFF_SPELLINGS = KV_QUANT_OFF_SPELLINGS
GLUE_FUSION_ON_SPELLINGS = KV_QUANT_ON_SPELLINGS
define_flag("train_remat", "",
            "default selective-remat policy for hapi.Model training "
            "(ISSUE 19): when Model.prepare(remat=None) and this flag "
            "is non-empty, every remat-capable transformer block of "
            "the network gets activation recompute with this "
            "jax.checkpoint policy ('full', 'dots_saveable', "
            "'dots_and_kernels_saveable', 'transformer_saveable'; an "
            "on-spelling like '1'/'true' means "
            "'dots_and_kernels_saveable' — keep matmul/flash outputs, "
            "recompute the cheap elementwise/norm chain). Gradients "
            "are bitwise-identical remat on/off; only the saved-"
            "residual set (static_peak_bytes) and the backward's "
            "recompute fraction move. '' = off (the model config's own "
            "recompute field still applies).")
define_flag("train_prefetch", True,
            "double-buffered host->device input staging in Model.fit "
            "(ISSUE 19): batch N+1 is split and device_put while step "
            "N is still in flight (the hook runs between the step's "
            "dispatch and its blocking loss readback), so the transfer "
            "hides under device compute instead of extending the step "
            "loop. Loss trajectories are bitwise-identical to the "
            "synchronous feed — only WHEN the conversion happens "
            "moves. train.input_wait_ms / train.input_overlap_frac "
            "surface through the observability registry; off restores "
            "the synchronous convert-inside-the-step feed. PDT121 "
            "notes custom train loops that stage batches synchronously "
            "with no prefetch knob in scope.")
define_flag("metrics", True,
            "observability runtime (paddle_tpu.observability): metrics "
            "registry recording, structured-event ring buffer, serving "
            "timelines, training step telemetry and flight-recorder "
            "dumps. PDTPU_METRICS=off makes every record call a "
            "near-no-op (one dict lookup) and restores the "
            "pre-observability behavior bitwise; counters that back "
            "the serving engine's stats contract are created with "
            "always=True and keep recording either way.")
define_flag("serving_slo", "",
            "declarative latency/goodput objectives for serving "
            "engines (ISSUE 14, observability/slo.py): a comma-"
            "separated spec string like "
            "'ttft_p95_ms=500,tpot_p99_ms=100,goodput=0.99' evaluated "
            "over sliding windows of the engine's own timeline "
            "histograms with multi-window burn-rate alerting; a "
            "breach emits an slo.breach ring event and dumps a flight "
            "record. '' (default) arms nothing; engine kwarg slo "
            "overrides per instance (spec string or SLOSpec list). "
            "PDT117 notes engines with overload knobs but no SLO "
            "spec or watchdog.")
define_flag("serving_slo_window_s", 60.0,
            "slow/error-budget window for SLO burn-rate evaluation "
            "(observability/slo.py); the fast confirmation window is "
            "1/12 of it (the SRE two-window convention). SLOSpec "
            "kwargs fast_window_s/slow_window_s override per spec.")
define_flag("watchdog_stall_ms", 0.0,
            "stall-watchdog deadline (observability/watchdog.py): "
            "engine dispatches, DisaggServer handoffs, rpc invokes "
            "and Model.fit steps armed past this many ms without "
            "completing/heartbeating capture all thread stacks, dump "
            "the flight record + Chrome trace and emit watchdog.stall "
            "— the engine's dispatch additionally surfaces a coded "
            "EngineStallError (PDT-E020) instead of hanging its "
            "caller. 0 (default) = watchdog off; engine kwarg "
            "watchdog_ms overrides per instance. No-op with "
            "PDTPU_METRICS=off.")
define_flag("watchdog_poll_ms", 20.0,
            "stall-watchdog daemon-thread poll cadence; a stall is "
            "detected within deadline + one poll interval.")
define_flag("flight_keep", 40,
            "keep-last-K retention for flight records in "
            "PDTPU_FLIGHT_DIR (observability/events.py dump GC, "
            "mirroring CheckpointManager's keep-last-K): every dump "
            "deletes the oldest records (and their .trace.json/"
            ".stacks.txt companions) past this count. 0 = unbounded "
            "(the pre-ISSUE-14 behavior).")
define_flag("collective_timeout_ms", 0.0,
            "collective-watchdog deadline (resilience/elastic_train.py "
            "+ observability/watchdog.py): Group.psum_mean, "
            "DataParallel.apply_collective_grads, pipeline "
            "forward/train_batch dispatches and the elastic "
            "supervisor's store-backed allreduce armed past this many "
            "ms raise a coded CollectiveTimeoutError (PDT-E021) with "
            "thread stacks in a flight record instead of hanging every "
            "survivor behind a dead peer. 0 (default) = off; size the "
            "deadline above the worst case INCLUDING first compiles "
            "(an interrupt landing mid-compile aborts work that would "
            "have been cached). FleetSupervisor kwarg "
            "collective_timeout_ms overrides per instance.")
define_flag("elastic_snapshot_every", 50,
            "buddy in-memory snapshot cadence (resilience/"
            "elastic_train.py): every N optimizer steps each rank "
            "snapshots model/optimizer/RNG state to host memory and "
            "replicates it to its buddy rank asynchronously off the "
            "step path. 0 = snapshots off (recovery falls back to the "
            "newest COMPLETE CheckpointManager version); "
            "FleetSupervisor kwarg snapshot_every overrides.")
define_flag("elastic_buddy", 1,
            "buddy offset for in-memory snapshot replication: rank r "
            "replicates to rank (r + offset) % world "
            "(resilience/elastic_train.py). The dead rank's state is "
            "restored from its buddy's replica; only when the buddy is "
            "also gone does recovery read the on-disk checkpoint.")
define_flag("metrics_log_every", 0,
            "training StepTimer one-line log cadence: every N train "
            "steps hapi.Model.fit logs step wall-time, tokens/sec, "
            "MFU estimate and retrace count through the "
            "'paddle_tpu.observability' logger. 0 (default) = no "
            "periodic log; the gauges/histograms record regardless.")
define_flag("while_grad_max_trip_count", 256,
            "trip bound for differentiable while_loop under jit capture "
            "(lowered to a masked lax.scan; XLA has no reverse-mode "
            "while). A loop still live after this many iterations warns "
            "at runtime and returns the bound-truncated carry.")


class _GradMode(threading.local):
    def __init__(self):
        self.enabled = True


_grad_mode = _GradMode()


def is_grad_enabled() -> bool:
    return _grad_mode.enabled


def set_grad_enabled(enabled: bool) -> bool:
    old = _grad_mode.enabled
    _grad_mode.enabled = enabled
    return old


# --- global RNG (paddle.seed analog). Functional JAX PRNG under the hood:
# a mutable key that is split on every draw. The key lives in a Tensor and is
# read/written through the capture funnel, so a jit-captured train step
# threads the RNG state as a real input/output instead of baking a constant
# (the reference reaches the same end with stateful curand generators +
# seed/offset capture in CUDA graphs, SURVEY C30). ---
class _RNG:
    def __init__(self):
        self._key_var = None
        self._seed = 0

    def seed(self, s: int):
        import jax
        from .tensor import Tensor

        self._seed = int(s)
        key = jax.random.key_data(jax.random.PRNGKey(self._seed))
        if self._key_var is None:
            self._key_var = Tensor(key)
        else:
            self._key_var._write(key)

    def next_key(self):
        import jax

        if self._key_var is None:
            self.seed(0)
        key = jax.random.wrap_key_data(self._key_var._read())
        new_key, sub = jax.random.split(key)
        self._key_var._write(jax.random.key_data(new_key))
        return sub


default_rng = _RNG()


def seed(s: int):
    default_rng.seed(s)
    return default_rng

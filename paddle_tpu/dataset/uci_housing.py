"""UCI housing reader creators (reference
``python/paddle/dataset/uci_housing.py``). Samples are
``(features float32 [13] feature-scaled, price float32 [1])``.
"""
from __future__ import annotations

import os

import numpy as np

from . import common

__all__ = ['train', 'test']

TRAIN_RATIO = 0.8


def _load():
    path = os.path.join(common.DATA_HOME, 'uci_housing', 'housing.data')
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not present (no network egress to fetch it)")
    data = np.loadtxt(path)
    maxs, mins = data.max(axis=0), data.min(axis=0)
    avgs = data.mean(axis=0)
    feats = (data[:, :-1] - avgs[:-1]) / np.maximum(
        maxs[:-1] - mins[:-1], 1e-8)
    return feats.astype('float32'), data[:, -1:].astype('float32')


def _reader_creator(start_frac, end_frac):
    def reader():
        x, y = _load()
        n = len(x)
        for i in range(int(n * start_frac), int(n * end_frac)):
            yield x[i], y[i]
    return reader


def train():
    return _reader_creator(0.0, TRAIN_RATIO)


def test():
    return _reader_creator(TRAIN_RATIO, 1.0)

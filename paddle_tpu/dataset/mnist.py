"""MNIST reader creators (reference ``python/paddle/dataset/mnist.py``).

Samples are ``(image float32 [784] scaled to [-1, 1], label int)``,
matching the reference reader format.
"""
from __future__ import annotations

import os

import numpy as np

from . import common

__all__ = ['train', 'test']


def _reader_creator(image_path, label_path):
    from ..vision.datasets import MNIST

    def reader():
        ds = MNIST(image_path=image_path, label_path=label_path)
        for img, label in ((ds.images[i], ds.labels[i])
                           for i in range(len(ds))):
            yield (img.reshape(-1).astype('float32') / 127.5 - 1.0,
                   int(label))
    return reader


def _paths(split):
    d = os.path.join(common.DATA_HOME, 'mnist')
    return (os.path.join(d, f'{split}-images-idx3-ubyte.gz'),
            os.path.join(d, f'{split}-labels-idx1-ubyte.gz'))


def train():
    return _reader_creator(*_paths('train'))


def test():
    return _reader_creator(*_paths('t10k'))

"""Legacy reader-style datasets (reference ``python/paddle/dataset/``).

Each submodule exposes ``train()``/``test()`` *reader creators* (zero-arg
callables yielding samples) over the same on-disk formats the reference
downloads. This runtime has no network egress, so files must be supplied
locally (pass paths, or set ``paddle.dataset.common.DATA_HOME``).
"""
from . import common, mnist, uci_housing, cifar

__all__ = ['common', 'mnist', 'cifar', 'uci_housing']

"""Shared dataset plumbing (reference ``python/paddle/dataset/common.py``).

``download`` verifies a *local* cached copy (md5-checked) instead of
fetching — this runtime has zero egress.
"""
from __future__ import annotations

import hashlib
import os

DATA_HOME = os.path.expanduser(os.environ.get(
    "PDTPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))

__all__ = ['DATA_HOME', 'md5file', 'download']


def md5file(fname):
    m = hashlib.md5()
    with open(fname, 'rb') as f:
        for chunk in iter(lambda: f.read(4096), b''):
            m.update(chunk)
    return m.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Resolve the locally cached file for ``url``; never fetches."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name if save_name else url.split('/')[-1])
    if os.path.exists(filename) and (
            not md5sum or md5file(filename) == md5sum):
        return filename
    raise FileNotFoundError(
        f"dataset file {filename} not present (and this runtime has no "
        f"network egress to fetch {url}); place the file there or pass "
        "explicit paths to the paddle.vision.datasets classes.")

"""CIFAR reader creators (reference ``python/paddle/dataset/cifar.py``).

Samples are ``(image float32 [3072] in [0, 1], label int)``.
"""
from __future__ import annotations

import os

from . import common

__all__ = ['train10', 'test10', 'train100', 'test100']


def _reader_creator(cls, archive, mode):
    def reader():
        ds = cls(data_file=archive, mode=mode)
        for i in range(len(ds)):
            img = ds.images[i].transpose(2, 0, 1)  # CHW like the reference
            yield img.reshape(-1).astype('float32') / 255.0, int(ds.labels[i])
    return reader


def _archive(name):
    return os.path.join(common.DATA_HOME, 'cifar', name)


def train10():
    from ..vision.datasets import Cifar10
    return _reader_creator(Cifar10, _archive('cifar-10-python.tar.gz'),
                           'train')


def test10():
    from ..vision.datasets import Cifar10
    return _reader_creator(Cifar10, _archive('cifar-10-python.tar.gz'),
                           'test')


def train100():
    from ..vision.datasets import Cifar100
    return _reader_creator(Cifar100, _archive('cifar-100-python.tar.gz'),
                           'train')


def test100():
    from ..vision.datasets import Cifar100
    return _reader_creator(Cifar100, _archive('cifar-100-python.tar.gz'),
                           'test')

"""Optimizer base + the paddle optimizer family.

Analog of ``python/paddle/optimizer/optimizer.py:103`` (reference) and its
subclasses (adam.py, adamw.py, momentum.py, ...). TPU-native details:

- accumulators are jax.Arrays updated with pure jnp math through the
  Tensor ``_read``/``_write`` funnel, so a jit-captured train step folds the
  whole optimizer into the single compiled XLA program (the reference fuses
  this per-op with multi_tensor / fused CUDA kernels — XLA does it for us);
- ``multi_precision`` keeps float32 master weights for bf16/fp16 params,
  matching the reference's master-weight behavior under AMP-O2.
"""
from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ..core import state as _state
from ..core import tensor as _tm
from ..core.tensor import Parameter, Tensor
from ..nn.clip import ClipGradBase, ClipGradByGlobalNorm
from . import flat as _flat
from .lr import LRScheduler


class L2Decay:
    """paddle.regularizer.L2Decay analog."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        if parameters is None:
            raise ValueError(
                "parameters is required in dygraph mode (pass "
                "model.parameters())")
        if isinstance(parameters, (Parameter, Tensor)):
            parameters = [parameters]
        parameters = list(parameters)
        if parameters and isinstance(parameters[0], dict):
            self._param_groups = parameters
            self._parameters = [p for g in parameters
                                for p in g["params"]]
        else:
            self._param_groups = [{"params": parameters}]
            self._parameters = parameters
        self._learning_rate = learning_rate
        if weight_decay is None:
            self._regularization = None
        elif isinstance(weight_decay, (L1Decay, L2Decay)):
            self._regularization = weight_decay
        else:
            self._regularization = L2Decay(float(weight_decay))
        assert grad_clip is None or isinstance(grad_clip, ClipGradBase)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: dict[str, dict[int, Tensor]] = {}
        self._master_weights: dict[int, Tensor] = {}
        self._step_count = 0
        self._aux_state: dict = {}
        # fused multi-tensor path (optimizer/flat.py): dtype buckets of
        # flat param/grad/moment buffers, built lazily at first step()
        self._flat: list[_flat.FlatGroup] | None = None
        self._fused_off = False
        self._defuse_count = 0
        self._flat_created_log: list | None = None  # StepGuard hook
        # 0-d device scalar holding the current LR: under jit capture it is
        # threaded as an input (synced from the scheduler host-side before
        # each compiled invocation), so LR changes don't retrigger tracing.
        # Created here, not lazily — it must pre-exist any capture so the
        # tracker classifies it as an input rather than a temporary.
        self._lr_var = Tensor(jnp.float32(self.get_lr()))

    # --- lr -------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the learning rate is a scheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # --- accumulators (state lives in Tensors so jit capture threads it
    # through the compiled step as inputs/outputs) ------------------------
    def _acc(self, name, p, init=None, dtype=None):
        store = self._accumulators.setdefault(name, {})
        pid = id(p)
        if pid not in store:
            v = p._read()
            dt = dtype or (jnp.float32 if self._use_master(p) else v.dtype)
            store[pid] = Tensor(jnp.zeros(v.shape, dt) if init is None
                                else jnp.full(v.shape, init, dt))
        return store[pid]._read()

    def _set_acc(self, name, p, val):
        self._accumulators[name][id(p)]._write(val)

    def _use_master(self, p):
        return self._multi_precision and p._read().dtype in (
            jnp.bfloat16, jnp.float16)

    def _get_master(self, p):
        pid = id(p)
        if pid not in self._master_weights:
            self._master_weights[pid] = Tensor(
                p._read().astype(jnp.float32))
        return self._master_weights[pid]._read()

    # --- step -----------------------------------------------------------
    def _collect(self):
        pairs = []
        for p in self._parameters:
            if not getattr(p, "trainable", True) or p.stop_gradient:
                continue
            if p.grad is None:
                continue
            pairs.append((p, p.grad))
        return pairs

    def _apply_decay_to_grad(self, p, g32):
        """L2 regularization folded into the gradient (reference
        regularizer behavior — NOT decoupled adamw decay)."""
        reg = getattr(p, "regularizer", None) or self._regularization
        if isinstance(reg, L2Decay) and reg.coeff:
            master = (self._get_master(p) if self._use_master(p)
                      else p._read().astype(jnp.float32))
            return g32 + reg.coeff * master
        if isinstance(reg, L1Decay) and reg.coeff:
            master = (self._get_master(p) if self._use_master(p)
                      else p._read().astype(jnp.float32))
            return g32 + reg.coeff * jnp.sign(master)
        return g32

    @property
    def lr_var(self):
        """The captured LR scalar the compiled step reads — pass it as a
        ``jit.WindowRunner`` ``per_step`` tensor to feed a different LR
        to every step of a scanned window."""
        return self._lr_var

    def lr_window(self, length: int):
        """The next ``length`` scheduler LR values (current value first)
        as a float32 [length] array for a WindowRunner per-step slot,
        ADVANCING the scheduler by ``length`` steps — the window analog
        of calling ``scheduler.step()`` once per batch. With a fixed
        float LR the array is constant.

        The advance happens NOW, not when the window runs: if the
        subsequent ``run`` fails or is skipped, restore the scheduler
        from a prior ``state_dict()`` snapshot before retrying, or the
        schedule lands ``length`` steps ahead of the applied steps."""
        import numpy as np
        from .lr import LRScheduler
        sched = self._learning_rate
        if not isinstance(sched, LRScheduler):
            return np.full((length,), float(self._learning_rate),
                           np.float32)
        vals = []
        for _ in range(length):
            vals.append(float(sched()))
            sched.step()
        return np.asarray(vals, np.float32)

    def _live_lr(self):
        """Current LR as a traceable value. Under capture, reads the
        persistent lr scalar (a real program input) and registers a host-side
        sync so the scheduler's value is fed in before every invocation."""
        from ..core import tensor as _tm
        tr = _tm._tracker
        if tr is None:
            return self.get_lr()
        tr.add_host_sync(
            lambda: self._lr_var._write(jnp.float32(self.get_lr())))
        return self._lr_var._read()

    def step(self):
        self._step_count += 1
        pairs = self._collect()
        # step telemetry (ISSUE 8): eager-only wall time + fused bucket
        # dispatch count into the default observability registry. Under
        # jit capture the whole update is traced into the step program
        # — host timing there measures trace time, so skip it.
        from ..observability import metrics as _obs_metrics
        from ..observability.steptimer import note_optimizer_step
        import time as _time
        t0 = (_time.perf_counter()
              if _tm._tracker is None and _obs_metrics.enabled()
              else None)
        if self._fused_enabled():
            try:
                if self._fused_step(pairs):
                    if t0 is not None:
                        note_optimizer_step(
                            (_time.perf_counter() - t0) * 1e3,
                            fused_buckets=len(self._flat or ()))
                    return
            except _flat.FlatMismatch as e:
                self._defuse(str(e))
        elif self._flat is not None:
            # eligibility changed after fused steps ran (flag flipped,
            # clip swapped): fold bucket state — notably the per-bucket
            # beta-pow scalars — back into per-param accumulators before
            # the per-param path lazily re-creates them at 1.0
            self._defuse("fused path disabled", count=False)
        if self._grad_clip is not None:
            pairs = self._grad_clip(pairs)
        self._apply_pairs(pairs, self._live_lr())
        if t0 is not None:
            note_optimizer_step((_time.perf_counter() - t0) * 1e3)

    def _apply_pairs(self, pairs, lr):
        """The per-param update loop (grads already clipped)."""
        for p, g in pairs:
            lr_p = lr * p.optimize_attr.get("learning_rate", 1.0) \
                if hasattr(p, "optimize_attr") else lr
            g32 = g._read().astype(jnp.float32)
            g32 = self._apply_decay_to_grad(p, g32)
            if self._use_master(p):
                master = self._get_master(p)
                new_master = self._update(p, master, g32, lr_p)
                self._master_weights[id(p)]._write(new_master)
                p._write(new_master.astype(p._read().dtype))
            else:
                v = p._read()
                new_v = self._update(p, v.astype(jnp.float32), g32, lr_p)
                p._write(new_v.astype(v.dtype))

    # --- fused multi-tensor path (flat dtype buckets) --------------------
    def _fused_kind(self):
        """Fused-kernel kind for this optimizer, or None when the
        per-param path must run (subclasses override)."""
        return None

    _FUSED_MOMENTS = {"sgd": (), "momentum": ("velocity",),
                      "adam": ("moment1", "moment2"),
                      "adamw": ("moment1", "moment2")}

    def _fused_enabled(self):
        if self._fused_off or not _state.get_flag("fused_opt"):
            return False
        if self._fused_kind() is None:
            return False
        gc = self._grad_clip
        if gc is not None and not isinstance(gc, ClipGradByGlobalNorm):
            return False
        return True

    @staticmethod
    def _fusable_param(p, v, clip_active):
        if isinstance(v, (jax.core.Tracer, jax.ShapeDtypeStruct)) or \
                not hasattr(v, "dtype"):
            return False  # lazy / abstract (aot) values
        if not jnp.issubdtype(v.dtype, jnp.floating):
            return False
        if p._dist is not None:
            return False
        sh = getattr(v, "sharding", None)
        if sh is not None and len(getattr(sh, "device_set", ())) > 1 \
                and not sh.is_fully_replicated:
            return False  # keep sharded state sharded (fleet/mp)
        if hasattr(p, "optimize_attr") and \
                p.optimize_attr.get("learning_rate", 1.0) != 1.0:
            return False
        if getattr(p, "regularizer", None) is not None:
            return False
        if clip_active and getattr(p, "need_clip", True) is False:
            return False
        return True

    def _build_flat(self, pairs):
        """Group fusable params into dtype buckets and build the flat
        stores. Returns the group list or None (structural no-fuse).
        Every validation runs BEFORE any view is bound, so a no-fuse
        return leaves the optimizer's tensors untouched."""
        kind = self._fused_kind()
        clip_active = isinstance(self._grad_clip, ClipGradByGlobalNorm)
        by_dtype: dict = {}
        for p, _g in pairs:
            v = p._read()
            if not self._fusable_param(p, v, clip_active):
                continue
            dt = jnp.dtype(v.dtype)
            if dt != jnp.float32 and self._FUSED_MOMENTS[kind] and not (
                    self._multi_precision and
                    dt in (jnp.bfloat16, jnp.float16)):
                # the flat moment stores are f32 but the per-param path
                # keeps accumulators in the param dtype when no master
                # weight applies — fusing would break bitwise parity
                # (and fuse-or-not would depend on accumulator history)
                continue
            by_dtype.setdefault(dt, []).append((p, v))
        # ---- validation pass (no mutation) ----
        betas = {}
        for dt, pv in by_dtype.items():
            members = [p for p, _ in pv]
            if kind in ("adam", "adamw"):
                got = self._uniform_beta_pows(members)
                if got is None:
                    return None
                betas[dt] = got
            if self._multi_precision and dt in (jnp.bfloat16, jnp.float16):
                for p, v in pv:
                    t = self._master_weights.get(id(p))
                    if t is None:
                        continue
                    tv = t._read()
                    if tv.dtype != jnp.float32 or \
                            tuple(tv.shape) != tuple(v.shape):
                        return None
            for name in self._FUSED_MOMENTS[kind]:
                store = self._accumulators.get(name, {})
                for p, v in pv:
                    t = store.get(id(p))
                    if t is None:
                        continue
                    tv = t._read()
                    if tv.dtype != jnp.float32 or \
                            tuple(tv.shape) != tuple(v.shape):
                        return None
        # ---- build pass ----
        groups = []
        log = self._flat_created_log
        for dt, pv in by_dtype.items():
            members = [p for p, _ in pv]
            values = [v for _, v in pv]
            use_master = self._multi_precision and dt in (
                jnp.bfloat16, jnp.float16)
            grp = _flat.FlatGroup(members, values, use_master)
            # beta powers collapse to one scalar per bucket; a prior
            # per-param history must be uniform for that to be exact
            b1v, b2v = betas.get(dt, (1.0, 1.0))
            pf = grp.flatten(values)
            grp.param_store = _flat.FlatStore(grp, "param", pf)
            if log is not None:
                log.append((grp.param_store.storage, pf))
            for i, p in enumerate(members):
                grp.param_store.bind(i, p)
            if use_master:
                if any(id(p) in self._master_weights for p in members):
                    mvals = []
                    for p, v in pv:
                        t = self._master_weights.get(id(p))
                        mvals.append(v.astype(jnp.float32) if t is None
                                     else t._read())
                    mf = grp.flatten(mvals, jnp.float32)
                else:
                    mf = pf.astype(jnp.float32)
                grp.master_store = _flat.FlatStore(grp, "master", mf)
                if log is not None:
                    log.append((grp.master_store.storage, mf))
                st = grp.master_store
                for i, p in enumerate(members):
                    t = self._master_weights.get(id(p))
                    if t is None:
                        t = Tensor(st._slice(mf, i))
                        self._master_weights[id(p)] = t
                    st.bind(i, t)
            for name in self._FUSED_MOMENTS[kind]:
                store = self._accumulators.setdefault(name, {})
                avals = []
                for p, v in pv:
                    t = store.get(id(p))
                    avals.append(jnp.zeros(v.shape, jnp.float32)
                                 if t is None else t._read())
                af = grp.flatten(avals, jnp.float32)
                st = _flat.FlatStore(grp, "moment", af)
                grp.moment_stores[name] = st
                if log is not None:
                    log.append((st.storage, af))
                for i, p in enumerate(members):
                    t = store.get(id(p))
                    if t is None:
                        t = Tensor(avals[i])
                        store[id(p)] = t
                    st.bind(i, t)
            if kind in ("adam", "adamw"):
                grp.b1p = Tensor(jnp.float32(b1v))
                grp.b2p = Tensor(jnp.float32(b2v))
                if log is not None:
                    log.append((grp.b1p, grp.b1p._read()))
                    log.append((grp.b2p, grp.b2p._read()))
            groups.append(grp)
        return groups or None

    def _uniform_beta_pows(self, members):
        """(b1, b2) when every member's saved beta-pow history agrees
        (the normal case: all params step together); None when mixed."""
        out = []
        for name in ("beta1_pow", "beta2_pow"):
            store = self._accumulators.get(name, {})
            ts = [store.get(id(p)) for p in members]
            if all(t is None for t in ts):
                out.append(1.0)
                continue
            if any(t is None for t in ts):
                return None
            first = None
            for t in ts:
                a = np.asarray(t._read()).ravel()
                if a.size == 0:
                    return None
                if first is None:
                    first = a.flat[0]
                if not np.all(a == first):
                    return None
            out.append(float(first))
        return out[0], out[1]

    def _make_spec(self, grp, has_clip):
        from ..ops.pallas.fused_optimizer import UpdateSpec
        kind = self._fused_kind()
        reg = self._regularization
        reg_kind, reg_coeff = None, 0.0
        if isinstance(reg, L2Decay) and reg.coeff:
            reg_kind, reg_coeff = "l2", reg.coeff
        elif isinstance(reg, L1Decay) and reg.coeff:
            reg_kind, reg_coeff = "l1", reg.coeff
        return UpdateSpec(
            kind=kind, beta1=getattr(self, "_beta1", 0.9),
            beta2=getattr(self, "_beta2", 0.999),
            eps=getattr(self, "_epsilon", 1e-8),
            momentum=getattr(self, "_momentum", 0.0),
            nesterov=getattr(self, "_nesterov", False),
            rescale=getattr(self, "_rescale", 1.0),
            decay=(self._coeff if kind == "adamw" else 0.0),
            reg=reg_kind, reg_coeff=reg_coeff,
            use_master=grp.use_master, has_clip=has_clip)

    def _gather_grads(self, grp, gmap):
        """Member grads -> the group's flat grad buffer (ONE concat),
        binding the grad tensors as views of it."""
        st = grp.grad_store
        gts = [gmap[id(p)] for p in grp.params]
        # the short-circuit (flat buffer already authoritative) is an
        # EAGER-only optimization: under capture the gather must always
        # run — discovery has to read the member grads so replay (whose
        # host flags are frozen post-discovery and which always takes
        # the gather branch) sees the same reads, and skipping it would
        # bake a program that ignores in-step grad accumulation
        if st is not None and not st._dirty and _tm._tracker is None \
                and all(st.owns(g, i) for i, g in enumerate(gts)):
            return
        vals = [g._read() for g in gts]
        flat = grp.flatten(vals, vals[0].dtype)
        if st is None:
            st = grp.grad_store = _flat.FlatStore(grp, "grad", flat)
        else:
            st.set_flat(flat)
        if _flat._replaying():
            # replay re-executes with temporary tracer grads: only the
            # value flow above is real, bindings must not mutate
            return
        anchor = st.storage._data
        concrete = _flat._concrete(anchor)
        for i, g in enumerate(gts):
            if not st.owns(g, i):
                st.bind(i, g)
            else:
                st.local[i] = False
            g._flat_src = anchor if concrete else None
        st._dirty = False

    def _fused_step(self, pairs):
        from ..ops.pallas import fused_optimizer as fo
        if not pairs:
            return False  # nothing to do; keep buckets/eligibility intact
        fl = self._flat
        if fl is None:
            fl = self._build_flat(pairs)
            if fl is None:
                self._fused_off = True  # structural: stop probing
                return False
            self._flat = fl
        gmap = {id(p): g for p, g in pairs}
        clip_active = isinstance(self._grad_clip, ClipGradByGlobalNorm)
        for grp in fl:
            for i, p in enumerate(grp.params):
                if id(p) not in gmap:
                    raise _flat.FlatMismatch(
                        "bucketed parameter has no gradient this step")
                if not grp.param_store.owns(p, i):
                    raise _flat.FlatMismatch(
                        "parameter re-bound outside its bucket")
                if getattr(p, "regularizer", None) is not None or \
                        (hasattr(p, "optimize_attr") and
                         p.optimize_attr.get("learning_rate", 1.0) != 1.0) \
                        or (clip_active and
                            getattr(p, "need_clip", True) is False):
                    raise _flat.FlatMismatch(
                        "per-param attribute changed after bucket build")
        bucketed = set()
        for grp in fl:
            bucketed.update(grp.pids)
        leftover = [(p, g) for p, g in pairs if id(p) not in bucketed]
        # fold any local view overrides (per-param fallback steps, user
        # writes) back into the flat buffers, then gather grads
        for grp in fl:
            for st in grp.stores():
                st.sync()
            self._gather_grads(grp, gmap)
        lr = self._live_lr()
        clip_scale = None
        if clip_active:
            sq = [jnp.sum(jnp.square(
                grp.grad_store.storage._read().astype(jnp.float32)))
                for grp in fl]
            for p, g in leftover:
                if g is None or getattr(p, "need_clip", True) is False:
                    continue
                sq.append(jnp.sum(jnp.square(
                    g._read().astype(jnp.float32))))
            if sq:
                clip_scale = self._grad_clip._flat_scale(sq)
        if clip_scale is not None and leftover:
            leftover = ClipGradByGlobalNorm._apply_scale(leftover,
                                                         clip_scale)
        for grp in fl:
            spec = self._make_spec(grp, clip_scale is not None)
            kw = {}
            names = self._FUSED_MOMENTS[spec.kind]
            if names:
                kw["m"] = grp.moment_stores[names[0]].flat_value()
            if len(names) > 1:
                kw["v"] = grp.moment_stores[names[1]].flat_value()
            if spec.use_master:
                kw["master"] = grp.master_store.flat_value()
            if grp.b1p is not None:
                kw["b1p"] = grp.b1p._read()
                kw["b2p"] = grp.b2p._read()
            new_w, new_master, nm, nv, nb1, nb2 = fo.fused_update(
                spec, w=grp.param_store.flat_value(),
                g=grp.grad_store.storage._read(), lr=lr,
                clip_scale=clip_scale, **kw)
            grp.param_store.set_flat(new_w)
            if new_master is not None:
                grp.master_store.set_flat(new_master)
            if nm is not None:
                grp.moment_stores[names[0]].set_flat(nm)
            if nv is not None:
                grp.moment_stores[names[1]].set_flat(nv)
            if nb1 is not None:
                grp.b1p._write(nb1)
                grp.b2p._write(nb2)
        if leftover:
            self._apply_pairs(leftover, lr)
        return True

    def _defuse(self, reason, warn=True, count=True):
        """Dissolve the flat buckets back into per-param tensors."""
        fl = self._flat
        if fl is None:
            return
        if _tm._tracker is not None:
            raise _flat.FlatMismatch(
                f"flat-bucket defuse required under jit capture ({reason})"
                " — defuse eagerly before capturing the step")
        for grp in fl:
            if grp.b1p is not None:
                for i, p in enumerate(grp.params):
                    for name, t in (("beta1_pow", grp.b1p),
                                    ("beta2_pow", grp.b2p)):
                        self._accumulators.setdefault(name, {})[id(p)] = \
                            Tensor(jnp.full(grp.shapes[i], t._read(),
                                            jnp.float32))
            for st in grp.stores():
                st.unbind_all()
            if grp.grad_store is not None:
                grp.grad_store.unbind_all()
        self._flat = None
        if count:
            self._defuse_count += 1
            if self._defuse_count >= 2:
                self._fused_off = True
        if warn:
            warnings.warn(
                f"fused optimizer path defused: {reason} "
                f"(per-param fallback)")

    def _flat_unscale(self, inv):
        """Bucketed unscale + inf-check for ``amp.GradScaler``: one
        multiply and one isfinite reduction per flat bucket instead of
        per-param chains. Returns (found_inf, handled param ids)."""
        fl = self._flat
        if not fl:
            return False, set()
        gmap = {id(p): g for p, g in self._collect()}
        found = False
        handled: set[int] = set()
        for grp in fl:
            if any(id(p) not in gmap for p in grp.params):
                continue
            try:
                self._gather_grads(grp, gmap)
            except _flat.FlatMismatch:
                continue
            g32 = grp.grad_store.storage._read().astype(jnp.float32) * inv
            if not bool(jnp.all(jnp.isfinite(g32))):
                found = True
            grp.grad_store.set_flat(g32)
            handled.update(grp.pids)
        return found, handled

    def _fused_guard_slots(self):
        """Every flat storage the fused update writes — the slots
        ``resilience.StepGuard`` snapshots/blends instead of the
        per-param views (O(buckets) selects, not O(params))."""
        out = []
        for grp in (self._flat or ()):
            for st in grp.stores():
                st.sync()
                out.append(st.storage)
            if grp.b1p is not None:
                out.extend((grp.b1p, grp.b2p))
        return out

    minimize = None  # set below

    def _update(self, p, w, g, lr):
        raise NotImplementedError

    def clear_grad(self, set_to_zero=False):
        # NOTE: the reference defaults set_to_zero=True (zero in place);
        # we default to dropping the buffer — zeroing is opt-in for
        # jit-captured gradient accumulation (hapi accumulate_grad_batches).
        handled: set[int] = set()
        if set_to_zero and self._flat is not None:
            # fused path: ONE zeros op per flat grad bucket; the
            # per-param grad views observe the zeros lazily
            for grp in self._flat:
                st = grp.grad_store
                if st is None:
                    continue
                if any(p._grad is None or not st.owns(p._grad, i)
                       for i, p in enumerate(grp.params)):
                    continue  # partially re-bound: per-param fallback
                st.fill_zeros()
                for p in grp.params:
                    p._grad._node = None
                    handled.add(id(p))
        for p in self._parameters:
            if id(p) in handled:
                continue
            p.clear_grad(set_to_zero=set_to_zero)

    clear_gradients = clear_grad

    # --- state dict -----------------------------------------------------
    def state_dict(self):
        sd = {}
        names = {id(p): (p.name or f"param_{i}")
                 for i, p in enumerate(self._parameters)}
        for acc_name, store in self._accumulators.items():
            for pid, val in store.items():
                if pid in names:
                    sd[f"{names[pid]}.{acc_name}"] = Tensor(val._read())
        for pid, val in self._master_weights.items():
            if pid in names:
                sd[f"{names[pid]}.master_weight"] = Tensor(val._read())
        # fused buckets keep ONE beta-pow scalar per bucket; emit it per
        # param so the per-param path (and older checkpoints) round-trip
        for grp in (self._flat or ()):
            if grp.b1p is None:
                continue
            for p in grp.params:
                nm = names.get(id(p))
                if nm is None:
                    continue
                sd[f"{nm}.beta1_pow"] = Tensor(grp.b1p._read())
                sd[f"{nm}.beta2_pow"] = Tensor(grp.b2p._read())
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        sd["@step"] = self._step_count
        return sd

    def set_state_dict(self, sd):
        if self._flat is not None:
            # dissolve the buckets first: loading replaces the per-param
            # accumulator tensors wholesale; the buckets rebuild from the
            # loaded values at the next step()
            self._defuse("set_state_dict", warn=False, count=False)
        names = {(p.name or f"param_{i}"): p
                 for i, p in enumerate(self._parameters)}
        self._step_count = int(sd.get("@step", 0))
        if "LR_Scheduler" in sd and isinstance(self._learning_rate,
                                               LRScheduler):
            self._learning_rate.set_state_dict(sd["LR_Scheduler"])
        for key, val in sd.items():
            if key in ("LR_Scheduler", "@step"):
                continue
            pname, acc = key.rsplit(".", 1)
            p = names.get(pname)
            if p is None:
                continue
            arr = val._read() if isinstance(val, Tensor) else \
                jnp.asarray(np.asarray(val))
            if acc == "master_weight":
                self._master_weights[id(p)] = Tensor(arr)
            else:
                self._accumulators.setdefault(acc, {})[id(p)] = Tensor(arr)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Apply the update from gradients already on the parameters.

        Matches the reference dygraph semantics (``optimizer.py`` minimize
        collects existing ``p.grad`` pairs; it does NOT re-run autodiff), so
        the canonical ``loss.backward(); opt.minimize(loss)`` idiom applies
        each gradient exactly once.
        """
        self.step()
        return None, None


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _update(self, p, w, g, lr):
        return w - lr * g

    def _fused_kind(self):
        return "sgd"


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov
        self._rescale = rescale_grad

    def _update(self, p, w, g, lr):
        if self._rescale != 1.0:
            g = g * self._rescale
        vel = self._acc("velocity", p)
        vel = self._momentum * vel + g
        self._set_acc("velocity", p, vel)
        if self._nesterov:
            return w - lr * (g + self._momentum * vel)
        return w - lr * vel

    def _fused_kind(self):
        return "momentum"


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad

    def _fused_kind(self):
        return None if self._amsgrad else "adam"

    def _beta_pows(self, p):
        b1p = self._acc("beta1_pow", p, init=1.0, dtype=jnp.float32)
        b2p = self._acc("beta2_pow", p, init=1.0, dtype=jnp.float32)
        b1p = b1p * self._beta1
        b2p = b2p * self._beta2
        self._set_acc("beta1_pow", p, b1p)
        self._set_acc("beta2_pow", p, b2p)
        return b1p, b2p

    def _update(self, p, w, g, lr):
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        b1p, b2p = self._beta_pows(p)
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        m_hat = m / (1 - b1p)
        if self._amsgrad:
            vmax = self._acc("moment2_max", p, dtype=jnp.float32)
            vmax = jnp.maximum(vmax, v)
            self._set_acc("moment2_max", p, vmax)
            v_hat = vmax / (1 - b2p)
        else:
            v_hat = v / (1 - b2p)
        return w - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)


class AdamW(Adam):
    """Decoupled weight decay (reference ``adamw.py``): decay applies to the
    weight directly, not through the gradient."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, name=name)
        self._coeff = float(weight_decay) if not isinstance(
            weight_decay, (L1Decay, L2Decay)) else weight_decay.coeff
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _fused_kind(self):
        if self._amsgrad or self._lr_ratio is not None or \
                self._apply_decay_param_fun is not None:
            return None
        return "adamw"

    def _update(self, p, w, g, lr):
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        decay = self._coeff
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            decay = 0.0
        if decay:
            w = w * (1.0 - lr * decay)
        return super()._update(p, w, g, lr)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update(self, p, w, g, lr):
        m = self._acc("moment", p, dtype=jnp.float32)
        u = self._acc("inf_norm", p, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=1.0, dtype=jnp.float32)
        b1p = b1p * self._beta1
        self._set_acc("beta1_pow", p, b1p)
        m = self._beta1 * m + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * u, jnp.abs(g))
        self._set_acc("moment", p, m)
        self._set_acc("inf_norm", p, u)
        return w - lr / (1 - b1p) * m / (u + self._epsilon)


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update(self, p, w, g, lr):
        acc = self._acc("moment", p, init=self._init_acc, dtype=jnp.float32)
        acc = acc + jnp.square(g)
        self._set_acc("moment", p, acc)
        return w - lr * g / (jnp.sqrt(acc) + self._epsilon)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon, self._rho = epsilon, rho

    def _update(self, p, w, g, lr):
        avg_sq = self._acc("avg_squared_grad", p, dtype=jnp.float32)
        avg_up = self._acc("avg_squared_update", p, dtype=jnp.float32)
        avg_sq = self._rho * avg_sq + (1 - self._rho) * jnp.square(g)
        delta = jnp.sqrt(avg_up + self._epsilon) / \
            jnp.sqrt(avg_sq + self._epsilon) * g
        avg_up = self._rho * avg_up + (1 - self._rho) * jnp.square(delta)
        self._set_acc("avg_squared_grad", p, avg_sq)
        self._set_acc("avg_squared_update", p, avg_up)
        return w - lr * delta


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _update(self, p, w, g, lr):
        ms = self._acc("mean_square", p, dtype=jnp.float32)
        mom = self._acc("momentum", p, dtype=jnp.float32)
        ms = self._rho * ms + (1 - self._rho) * jnp.square(g)
        self._set_acc("mean_square", p, ms)
        if self._centered:
            mg = self._acc("mean_grad", p, dtype=jnp.float32)
            mg = self._rho * mg + (1 - self._rho) * g
            self._set_acc("mean_grad", p, mg)
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * mom + lr * g / denom
        self._set_acc("momentum", p, mom)
        return w - mom


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update(self, p, w, g, lr):
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=1.0, dtype=jnp.float32)
        b2p = self._acc("beta2_pow", p, init=1.0, dtype=jnp.float32)
        b1p, b2p = b1p * self._beta1, b2p * self._beta2
        self._set_acc("beta1_pow", p, b1p)
        self._set_acc("beta2_pow", p, b2p)
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        decay = self._lamb_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            decay = 0.0
        update = r + decay * w
        w_norm = jnp.linalg.norm(w)
        u_norm = jnp.linalg.norm(update)
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return w - lr * trust * update


class LBFGS(Optimizer):
    """Limited-memory BFGS with two-loop recursion and optional
    strong-Wolfe line search (reference ``python/paddle/optimizer/lbfgs.py``:
    LBFGS :120, ``_strong_wolfe`` :247). Full-batch optimizer:
    ``step(closure)`` re-evaluates the loss/gradient as the line search
    probes points — closure must zero grads, run backward, return loss."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kwargs):
        super().__init__(learning_rate=learning_rate, parameters=parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self.max_iter = max_iter
        self.max_eval = max_eval or max_iter * 5 // 4
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s: list = []
        self._y: list = []
        self._prev_flat_grad = None

    # -- flat parameter/grad views (float32 working precision) ---------
    def _trainable(self):
        return [p for p in self._parameters
                if getattr(p, "trainable", True) and not p.stop_gradient]

    def _flat_params(self):
        return jnp.concatenate(
            [p._read().astype(jnp.float32).ravel()
             for p in self._trainable()])

    def _flat_grad(self):
        gs = []
        for p in self._trainable():
            g = p.grad
            gs.append(jnp.zeros(p._read().size, jnp.float32) if g is None
                      else g._read().astype(jnp.float32).ravel())
        return jnp.concatenate(gs)

    def _set_flat_params(self, flat):
        off = 0
        for p in self._trainable():
            v = p._read()
            n = v.size
            p._write(flat[off:off + n].reshape(v.shape).astype(v.dtype))
            off += n

    def _dir_deriv(self, flat_grad, d):
        return float(jnp.dot(flat_grad, d))

    def _eval(self, closure, x, t, d):
        self._set_flat_params(x + t * d)
        loss = float(closure())
        g = self._flat_grad()
        return loss, g

    def step(self, closure):
        import numpy as _np
        with_ls = self.line_search_fn == "strong_wolfe"
        lr = float(self.get_lr())
        loss = float(closure())
        flat_grad = self._flat_grad()
        evals = 1
        if float(jnp.abs(flat_grad).max()) <= self.tol_grad:
            return loss

        for it in range(self.max_iter):
            # two-loop recursion
            q = flat_grad
            alphas = []
            for s, y in zip(reversed(self._s), reversed(self._y)):
                rho = 1.0 / max(float(jnp.dot(y, s)), 1e-10)
                a = rho * float(jnp.dot(s, q))
                alphas.append((a, rho, s, y))
                q = q - a * y
            if self._y:
                y_last, s_last = self._y[-1], self._s[-1]
                gamma = float(jnp.dot(s_last, y_last)) / max(
                    float(jnp.dot(y_last, y_last)), 1e-10)
                q = q * gamma
            for a, rho, s, y in reversed(alphas):
                b = rho * float(jnp.dot(y, q))
                q = q + s * (a - b)
            d = -q
            gtd = self._dir_deriv(flat_grad, d)
            if gtd > -self.tol_change:
                break

            x0 = self._flat_params()
            t = lr if (self._s or it > 0) else min(
                1.0, 1.0 / max(float(jnp.abs(flat_grad).sum()), 1e-10)) * lr
            if with_ls:
                t, loss_new, grad_new, ls_evals = _strong_wolfe(
                    lambda tt: self._eval(closure, x0, tt, d), t, d,
                    loss, flat_grad, gtd)
                evals += ls_evals
            else:
                loss_new, grad_new = self._eval(closure, x0, t, d)
                evals += 1
            self._set_flat_params(x0 + t * d)

            s = t * d
            ygrad = grad_new - flat_grad
            if float(jnp.dot(s, ygrad)) > 1e-10:
                self._s.append(s)
                self._y.append(ygrad)
                if len(self._s) > self.history_size:
                    self._s.pop(0)
                    self._y.pop(0)
            if (abs(loss_new - loss) < self.tol_change
                    or float(jnp.abs(grad_new).max()) <= self.tol_grad
                    or evals >= self.max_eval):
                loss, flat_grad = loss_new, grad_new
                break
            loss, flat_grad = loss_new, grad_new
        self._prev_flat_grad = flat_grad
        return loss

    def _update(self, p, w, g, lr):  # pragma: no cover - step() overridden
        raise RuntimeError("LBFGS.step requires a closure")


def _strong_wolfe(evaluate, t, d, f0, g0, gtd0, c1=1e-4, c2=0.9,
                  max_ls=25):
    """Strong-Wolfe cubic line search (reference ``lbfgs.py:247``).
    ``evaluate(t)`` -> (loss, flat_grad) at x0 + t*d."""
    import jax.numpy as jnp

    def dd(g):
        return float(jnp.dot(g, d))

    f_prev, g_prev, t_prev = f0, g0, 0.0
    evals = 0
    bracket = None
    for _ in range(max_ls):
        f_new, g_new = evaluate(t)
        evals += 1
        if f_new > f0 + c1 * t * gtd0 or (evals > 1 and f_new >= f_prev):
            bracket = (t_prev, t, f_prev, f_new, g_prev, g_new)
            break
        if abs(dd(g_new)) <= -c2 * gtd0:
            return t, f_new, g_new, evals
        if dd(g_new) >= 0:
            bracket = (t, t_prev, f_new, f_prev, g_new, g_prev)
            break
        t_prev, f_prev, g_prev = t, f_new, g_new
        t = t * 2.0
    else:
        return t, f_new, g_new, evals

    lo, hi, f_lo, f_hi, g_lo, g_hi = bracket
    for _ in range(max_ls):
        t = 0.5 * (lo + hi)
        f_new, g_new = evaluate(t)
        evals += 1
        if f_new > f0 + c1 * t * gtd0 or f_new >= f_lo:
            hi, f_hi, g_hi = t, f_new, g_new
        else:
            if abs(dd(g_new)) <= -c2 * gtd0:
                return t, f_new, g_new, evals
            if dd(g_new) * (hi - lo) >= 0:
                hi, f_hi, g_hi = lo, f_lo, g_lo
            lo, f_lo, g_lo = t, f_new, g_new
        if abs(hi - lo) < 1e-9:
            break
    return t, f_new, g_new, evals

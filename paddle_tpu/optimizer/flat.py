"""Flat dtype-bucketed optimizer state — the multi-tensor fused path.

Capability analog of the reference's ``multi_tensor_apply`` family
(``paddle/phi/kernels/fused_adam_kernel.cu``, ``multi_tensor_momentum``):
instead of updating O(num_params) small tensors one at a time, parameters
of one dtype are laid out in a single padded 1-D *flat buffer* per state
class (params, master weights, grads, per-moment accumulators) and the
whole update runs as a handful of fused kernels
(``ops/pallas/fused_optimizer.py``).

Aliasing story (jax.Arrays are immutable, so "views" are logical):

- A :class:`FlatStore` owns one flat storage ``Tensor`` plus per-member
  *view* tensors. A view keeps its public identity (``p``, ``p.grad``,
  ``opt._accumulators[...][pid]``) but its ``_read``/``_write`` funnel
  (``core/tensor.py``) routes here: reads materialize ``flat[off:off+n]``
  lazily (cached against the flat array's identity — jax arrays are
  immutable, so an identity match proves freshness), writes store a
  *local override* that the next ``sync()`` folds back with ONE concat.
- Under jit capture the storage tensor is the program input/output; the
  member views are invisible to the capture (``jit/__init__.py`` filters
  them), so a compiled train step threads a few flat arrays through its
  carry instead of hundreds of per-param arrays.
- GRAD stores are the exception: under a tracker their views read/write
  as plain tensors (the member's own funnel value). Gradients are
  produced per-param by autograd and may legitimately thread per-param
  through captured programs (gradient accumulation); baking a
  storage-slice read into the trace would go stale the moment another
  compiled program accumulates into the per-param value. Eagerly they
  still read through the flat buffer, which is what makes
  ``clear_grad(set_to_zero=True)`` a single ``zeros_like`` on the
  bucket with every view observing the zeros lazily.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import tensor as _tm
from ..core.tensor import Tensor

# flat buffers are padded to a multiple of this many elements so the
# Pallas kernel's (8, 128)-tiled 2-D view needs no per-step padding
ALIGN = 1024


class FlatMismatch(RuntimeError):
    """A member no longer matches its bucket slot (dtype/shape drift,
    e.g. ``amp.decorate`` re-casting after the bucket was built). The
    optimizer responds by defusing back to the per-param path."""


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _replaying():
    """True under a NON-discovery tracker (the jit replay/trace pass).
    Replay re-executes the step with temporary tracer-backed tensors:
    the store's host-side state (member bindings, local flags, dirty
    bit) must NOT mutate there — only value flow through the tracker's
    env is real. Discovery (step 0, concrete) and eager mutate."""
    tr = _tm._tracker
    return tr is not None and not getattr(tr, "is_discovery", False)


def _concrete(x):
    return isinstance(x, jax.Array) and not _is_tracer(x)


class FlatGroup:
    """One dtype bucket: shared geometry + the per-state-class stores."""

    def __init__(self, params, values, use_master):
        self.params = list(params)
        self.shapes = [tuple(v.shape) for v in values]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.offsets = []
        off = 0
        for n in self.sizes:
            self.offsets.append(off)
            off += n
        self.total = off
        self.padded = -(-off // ALIGN) * ALIGN
        self.dtype = values[0].dtype
        self.use_master = use_master
        self.pids = {id(p): i for i, p in enumerate(self.params)}
        # stores (filled by the optimizer's bucket build)
        self.param_store: Optional[FlatStore] = None
        self.master_store: Optional[FlatStore] = None
        self.moment_stores: dict[str, FlatStore] = {}
        self.b1p: Optional[Tensor] = None  # per-bucket beta-pow scalars
        self.b2p: Optional[Tensor] = None
        self.grad_store: Optional[FlatStore] = None

    def flatten(self, values, dtype=None):
        """values (member order) -> one padded flat array (ONE concat)."""
        dt = dtype or values[0].dtype
        pieces = []
        for i, v in enumerate(values):
            if tuple(v.shape) != self.shapes[i]:
                raise FlatMismatch(
                    f"member {i} shape {tuple(v.shape)} != bucket slot "
                    f"{self.shapes[i]}")
            if v.dtype != dt:
                raise FlatMismatch(
                    f"member {i} dtype {v.dtype} != bucket dtype {dt}")
            pieces.append(jnp.ravel(v))
        pad = self.padded - self.total
        if pad:
            pieces.append(jnp.zeros((pad,), dt))
        return jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]

    def stores(self):
        out = []
        if self.param_store is not None:
            out.append(self.param_store)
        if self.master_store is not None:
            out.append(self.master_store)
        out.extend(self.moment_stores.values())
        return out


class FlatStore:
    """One flat buffer + its member views (see module docstring)."""

    def __init__(self, group: FlatGroup, kind: str, flat_value):
        self.group = group
        self.kind = kind  # "param" | "master" | "moment" | "grad"
        self.storage = Tensor(flat_value)
        self.storage._flat_view = (self, -1)
        n = len(group.params)
        self.members: list[Optional[Tensor]] = [None] * n
        self.local = [False] * n
        self._dirty = False

    # ---- binding ---------------------------------------------------------
    def bind(self, i: int, t: Tensor):
        """Adopt ``t`` as the view of slot ``i``. The caller guarantees
        ``t``'s current logical value equals the slot's flat slice."""
        t._flat_view = (self, i)
        st = self.storage._data
        t._flat_src = st if _concrete(st) else None
        self.members[i] = t
        self.local[i] = False

    def owns(self, t: Tensor, i: int) -> bool:
        fv = t._flat_view
        return fv is not None and fv[0] is self and fv[1] == i

    def unbind_all(self):
        """Materialize every member into a plain tensor (defuse). Eager
        only — under capture the optimizer raises instead."""
        if _tm._tracker is not None:
            raise FlatMismatch("cannot defuse flat buckets under capture")
        for i, t in enumerate(self.members):
            if t is None or not self.owns(t, i):
                continue
            val = self.member_read(t, i)
            t._flat_view = None
            t._flat_src = None
            t._data = val
            self.members[i] = None
        self.storage._flat_view = None

    # ---- the view funnel (called from Tensor._read/_write) ---------------
    def member_read(self, t: Tensor, i: int):
        tr = _tm._tracker
        if i < 0:  # the storage tensor itself
            if tr is None and self._dirty:
                self.sync()
            return tr.on_read(t) if tr is not None else t._data
        if tr is not None:
            if self.kind == "grad":
                # under capture a grad view is a plain tensor: the trace
                # must consume the member's own (possibly accumulated)
                # value, never a baked storage slice (see module doc).
                # Refresh only under DISCOVERY (concrete): inside a jax
                # trace even a slice of a concrete array is a tracer,
                # and caching one would leak it past the trace.
                if not self.local[i] and not _replaying():
                    self._refresh(t, i)
                return tr.on_read(t)
            if self.local[i]:
                return tr.on_read(t)
            return self._slice(self.storage._read(), i)
        if self.local[i]:
            return t._data
        flat = self.storage._data
        if t._flat_src is flat:
            return t._data
        val = self._slice(flat, i)
        t._data = val
        t._flat_src = flat
        return val

    def member_write(self, t: Tensor, i: int, val):
        tr = _tm._tracker
        if i >= 0 and _replaying() and self.kind != "grad":
            # a local view override cannot compile: discovery's sync()
            # folds it into the storage and resets the host _dirty
            # flag, so the replayed trace would skip the fold and the
            # compiled program silently drops the write. Raising HERE
            # (replay runs inside exe.build's trace-failure net) turns
            # that into the standard decline -> eager fallback, whose
            # concrete discovery output is correct; replay also catches
            # views first bound DURING discovery, where the write
            # preceded binding. Grad views are exempt: backward writes
            # them and the gather always re-reads members under capture.
            from ..jit import GraphBreak
            raise GraphBreak(
                f"write to a fused-bucket {self.kind} view under jit "
                "capture cannot compile — mutate the tensor outside "
                "the captured step, or disable the fused optimizer "
                "path (PDTPU_FUSED_OPT=off)")
        if i >= 0 and not _replaying():
            self.local[i] = True
            self._dirty = True
            t._flat_src = None
        if tr is not None:
            tr.on_write(t, val)
        else:
            t._data = val

    def _refresh(self, t: Tensor, i: int):
        """Bring a stale eager cache up to date from the concrete flat
        (discovery passes read ``t._data`` raw through the tracker)."""
        flat = self.storage._data
        if _concrete(flat) and not _is_tracer(t._data) \
                and t._flat_src is not flat:
            t._data = self._slice(flat, i)
            t._flat_src = flat

    def _slice(self, flat, i):
        g = self.group
        o, n = g.offsets[i], g.sizes[i]
        return flat[o:o + n].reshape(g.shapes[i])

    # ---- flat-level operations ------------------------------------------
    def set_flat(self, val):
        """Replace the whole flat buffer; views re-materialize lazily."""
        self.storage._write(val)
        if not _replaying():
            self.local = [False] * len(self.local)
            self._dirty = False

    def flat_value(self):
        """Current flat value with local member overrides folded in."""
        if self._dirty:
            self.sync()
        return self.storage._read()

    def sync(self):
        """Fold local member overrides back into the flat storage with
        ONE concat (raises FlatMismatch on dtype/shape drift)."""
        if not self._dirty:
            return
        tr = _tm._tracker
        # raw storage read (not through member_read: the storage's own
        # funnel would re-enter this sync on the dirty flag)
        flat = tr.on_read(self.storage) if tr is not None \
            else self.storage._data
        dt = flat.dtype
        vals = []
        for i, t in enumerate(self.members):
            if self.local[i] and t is not None:
                vals.append(tr.on_read(t) if tr is not None else t._data)
            else:
                vals.append(self._slice(flat, i))
        self.set_flat(self.group.flatten(vals, dtype=dt))

    def fill_zeros(self):
        """Zero the flat buffer in ONE op; views observe lazily."""
        self.set_flat(jnp.zeros_like(self.storage._read()))
        tr = _tm._tracker
        if tr is not None:
            # under capture, per-member zero slices (constant-folded by
            # XLA) keep the traced per-param grad values in sync with
            # the zeroed bucket — grad views read as plain tensors there
            zf = self.storage._read()
            for i, t in enumerate(self.members):
                if t is not None and self.owns(t, i):
                    t._write(self._slice(zf, i))
            if not _replaying():
                self.local = [False] * len(self.local)
                self._dirty = False

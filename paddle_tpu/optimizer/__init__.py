"""paddle_tpu.optimizer — optimizers + LR schedulers.

Analog of ``python/paddle/optimizer/`` (reference ``optimizer.py:103``).
"""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adamax, Adagrad, Adadelta,
    RMSProp, Lamb, LBFGS, L1Decay, L2Decay,
)

"""Autoregressive generation with KV caches.

Capability analog of the reference ecosystem's ``model.generate`` (greedy /
temperature / nucleus sampling; the reference keeps generation in PaddleNLP
but ships the primitives in-tree: ``top_p_sampling``, block/paged KV
attention kernels — SURVEY C12). TPU-shaped: the decode step is ONE jitted
program with static shapes — caches are preallocated [B, max_len, Hkv, D]
and updated in place with ``dynamic_update_slice`` at the traced position;
attention masks positions beyond the current length. The per-token Python
loop re-invokes the same compiled step (functional cache threading — no
retrace after the first token).

Decode megakernel (ISSUE 18): ``_gpt_decode_fused``/``_llama_decode_fused``
(and the TP analogs behind ``_tp_decode_fused_fns``/``make_tp_window(...,
megakernel=True)``) run the per-token layer chain as ~3 fused Pallas
dispatches (``ops/pallas/fused_decode_qkv`` -> paged attention ->
``ops/pallas/fused_decode_mlp``) plus one guarded-argmax sampling
epilogue. Bitwise-identical to the unfused bodies; the serving engine
selects them via its ``megakernel`` kwarg / ``serving_megakernel`` flag.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.dispatch import primitive
from ..core.tensor import Tensor


@primitive
def cache_attention(q, k_new, v_new, k_cache, v_cache, pos,
                    scale=None):
    """One decode step of attention against a preallocated KV cache.

    q/k_new/v_new: [B, 1, H(q|kv), D]; caches [B, L, Hkv, D]; pos [1]
    (traced). Returns (out [B, 1, Hq, D], k_cache', v_cache'). GQA: kv
    heads repeat to match q heads. Positions > pos are masked out.
    """
    p = pos.reshape(())
    kc = lax.dynamic_update_slice_in_dim(k_cache, k_new.astype(
        k_cache.dtype), p, axis=1)
    vc = lax.dynamic_update_slice_in_dim(v_cache, v_new.astype(
        v_cache.dtype), p, axis=1)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    hq, hk = q.shape[2], kc.shape[2]
    kt, vt = kc, vc
    if hk != hq:
        kt = jnp.repeat(kt, hq // hk, axis=2)
        vt = jnp.repeat(vt, hq // hk, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, kt,
                        preferred_element_type=jnp.float32) * s
    valid = (jnp.arange(kc.shape[1]) <= p)[None, None, None, :]
    logits = jnp.where(valid, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vt)
    return out, kc, vc


@primitive
def paged_cache_attention(q, k_new, v_new, k_pages, v_pages, pos,
                          block_tables=None, scale=None):
    """One decode step against a PAGED KV cache (the reference's
    ``block_multi_head_attention`` capability — SURVEY C12).

    q/k_new/v_new: [B, 1, H(q|kv), D]; page pools [Hkv, P, page_size, D];
    ``block_tables`` (static attr) [B, pages_per_seq] page ids; pos [1]
    traced. Appends the new token into its (page, slot) and attends over
    the pages via the Pallas paged-decode kernel (attention cost scales
    with the current length, not max_len).
    """
    from ..ops.pallas.paged_attention import paged_decode_attention

    p = pos.reshape(())
    bt = jnp.asarray(np.asarray(block_tables), jnp.int32)   # [B, NP]
    b = q.shape[0]
    ps = k_pages.shape[2]
    page = bt[jnp.arange(b), p // ps]                       # [B]
    slot = p % ps
    kn = jnp.swapaxes(k_new[:, 0], 0, 1).astype(k_pages.dtype)  # [Hk, B, D]
    vn = jnp.swapaxes(v_new[:, 0], 0, 1).astype(v_pages.dtype)
    k_pages = k_pages.at[:, page, slot].set(kn)
    v_pages = v_pages.at[:, page, slot].set(vn)
    seq_lens = jnp.full((b,), p + 1, jnp.int32)
    out = paged_decode_attention(q[:, 0], k_pages, v_pages, bt, seq_lens,
                                 scale=scale)
    return out[:, None].astype(q.dtype), k_pages, v_pages


def _slot_page_write(kn, vn, k_pages, v_pages, bt, positions,
                     k_scales=None, v_scales=None):
    """Write one token per slot into its (page, slot): the ONE home of
    the per-slot page-write discipline — :func:`paged_slot_attention`
    AND the tensor-parallel decode path (``_tp_attend_decode``) both
    write through here, so the 'identical bytes' invariants (prefix
    cache, preempt-requeue, TP-replicated GQA pools) cannot drift
    between them.  ``kn``/``vn`` are head-major ``[Hk, B, D]``;
    scales switch on the int8 quantize-on-write path."""
    from ..quantization import kv_quantize

    p = positions.reshape(-1).astype(jnp.int32)             # [B]
    b = p.shape[0]
    ps = k_pages.shape[2]
    page = bt[jnp.arange(b), jnp.minimum(p // ps, bt.shape[1] - 1)]
    slot = p % ps
    if k_scales is not None:
        kn, k_sc = kv_quantize(kn)
        vn, v_sc = kv_quantize(vn)
        k_scales = k_scales.at[:, page, slot].set(k_sc)
        v_scales = v_scales.at[:, page, slot].set(v_sc)
    k_pages = k_pages.at[:, page, slot].set(kn.astype(k_pages.dtype))
    v_pages = v_pages.at[:, page, slot].set(vn.astype(v_pages.dtype))
    return k_pages, v_pages, k_scales, v_scales


def _ragged_page_write(kn, vn, k_pages, v_pages, bt, tok_pos, tok_slot,
                       tok_valid, k_scales=None, v_scales=None):
    """Packed-token analog of :func:`_slot_page_write` (invalid tokens
    route to the reserved null page 0) — shared by
    :func:`ragged_paged_step` and the TP ragged path
    (``_tp_attend_ragged``)."""
    from ..quantization import kv_quantize

    ps = k_pages.shape[2]
    pos = tok_pos.astype(jnp.int32)
    sl = tok_slot.astype(jnp.int32)
    ok = tok_valid.astype(jnp.bool_)
    page = jnp.where(
        ok, bt[sl, jnp.minimum(pos // ps, bt.shape[1] - 1)], 0)
    wslot = jnp.where(ok, pos % ps, 0)
    if k_scales is not None:
        kn, k_sc = kv_quantize(kn)
        vn, v_sc = kv_quantize(vn)
        k_scales = k_scales.at[:, page, wslot].set(k_sc)
        v_scales = v_scales.at[:, page, wslot].set(v_sc)
    k_pages = k_pages.at[:, page, wslot].set(kn.astype(k_pages.dtype))
    v_pages = v_pages.at[:, page, wslot].set(vn.astype(v_pages.dtype))
    return k_pages, v_pages, k_scales, v_scales


@primitive
def paged_slot_attention(q, k_new, v_new, k_pages, v_pages, positions,
                         block_tables, scale=None, pages_per_block=None,
                         k_scales=None, v_scales=None):
    """One decode step against a paged KV cache with PER-SLOT state —
    the continuous-batching variant of :func:`paged_cache_attention`.

    Unlike the static-attribute form, ``positions`` [B] (each slot's
    current token index) and ``block_tables`` [B, NP] are TRACED
    tensors: the serving engine admits/retires requests by changing
    their VALUES between dispatches, never recompiling.  Writes each
    slot's new K/V at its own (page, slot) and attends through the
    ragged Pallas kernel with per-slot lengths.

    ``k_scales``/``v_scales`` [Hk, P, page_size] switch on the int8 KV
    path: the new K/V quantize on write (``quantization.kv_quantize``,
    one absmax scale per head per token slot — path-independent bytes),
    the kernel dequantizes in its DMA loop, and the updated scale pools
    return alongside the data pools.
    """
    from ..ops.pallas.paged_attention import paged_decode_attention

    if (k_scales is None) != (v_scales is None):
        raise ValueError("paged_slot_attention: pass both k_scales "
                         "and v_scales or neither")
    quant = k_scales is not None
    p = positions.reshape(-1).astype(jnp.int32)             # [B]
    bt = block_tables.astype(jnp.int32)
    kn = jnp.swapaxes(k_new[:, 0], 0, 1)                    # [Hk, B, D]
    vn = jnp.swapaxes(v_new[:, 0], 0, 1)
    k_pages, v_pages, k_scales, v_scales = _slot_page_write(
        kn, vn, k_pages, v_pages, bt, positions, k_scales, v_scales)
    out = paged_decode_attention(q[:, 0], k_pages, v_pages, bt, p + 1,
                                 scale=scale,
                                 pages_per_block=pages_per_block,
                                 k_scales=k_scales, v_scales=v_scales)
    out = out[:, None].astype(q.dtype)
    if quant:
        return out, k_pages, v_pages, k_scales, v_scales
    return out, k_pages, v_pages


@primitive
def ragged_paged_step(q, k_new, v_new, k_pages, v_pages, tok_pos,
                      tok_slot, tok_valid, kv_lens, q_lens, block_tables,
                      scale=None, q_block=8, pages_per_block=None,
                      k_scales=None, v_scales=None):
    """Attention for ONE continuously-batched step over packed tokens.

    q/k_new/v_new: [T, H(q|kv), D] — tokens of all sequences packed in
    slot order (each slot's segment padded to a ``q_block`` multiple);
    tok_pos/tok_slot/tok_valid: [T] per-token absolute position, owning
    slot, and validity (padding tokens route their K/V write to the
    engine's reserved null page 0); kv_lens/q_lens: [B] per-slot totals
    (kv INCLUDING this step's tokens).  Prefill chunks and single-token
    decodes share this one call — the kernel's per-sequence causal
    offset handles both.

    ``k_scales``/``v_scales`` [Hk, P, page_size] switch on the int8 KV
    path (ISSUE 7): this step's K/V quantize ON WRITE at page-slot
    granularity (``quantization.kv_quantize`` — each token's bytes are
    a pure function of its own K/V vector, so a page filled by prefill
    chunks or token-by-token decode holds identical bytes and prefix-
    cache reuse stays exact), the scale vectors land in side-pools
    indexed by the same block tables, and the ragged kernel dequantizes
    inside its DMA loop.  The updated scale pools return after the data
    pools.
    """
    from ..ops.pallas.paged_attention import ragged_paged_attention

    if (k_scales is None) != (v_scales is None):
        raise ValueError("ragged_paged_step: pass both k_scales "
                         "and v_scales or neither")
    quant = k_scales is not None
    bt = block_tables.astype(jnp.int32)
    kn = jnp.swapaxes(k_new, 0, 1)                          # [Hk, T, D]
    vn = jnp.swapaxes(v_new, 0, 1)
    k_pages, v_pages, k_scales, v_scales = _ragged_page_write(
        kn, vn, k_pages, v_pages, bt, tok_pos, tok_slot, tok_valid,
        k_scales, v_scales)
    out = ragged_paged_attention(q, k_pages, v_pages, bt,
                                 kv_lens.astype(jnp.int32),
                                 q_lens.astype(jnp.int32),
                                 q_block=q_block, scale=scale,
                                 pages_per_block=pages_per_block,
                                 k_scales=k_scales, v_scales=v_scales)
    out = out.astype(q.dtype)
    if quant:
        return out, k_pages, v_pages, k_scales, v_scales
    return out, k_pages, v_pages


@primitive
def guarded_argmax(lg, poison):
    """Greedy token pick with a device-side finite-ness flag — the
    serving decode guard's in-graph half (``resilience.serving``).
    (``guarded_argmax.raw`` is the jnp-level form the decode-window
    scan body uses.)

    ``lg`` [B, V] logits, ``poison`` [B] float32 (0.0 normally, NaN for
    a slot the ``engine_nan_decode`` drill poisons). Returns
    ``(nxt [B] int32, bad [B] bool)``. Adding 0.0f to finite logits is
    argmax-invariant (the lone effect, -0.0 -> +0.0, compares equal),
    so token streams are unchanged when the guard is idle; a bad row's
    token is forced to 0 so the engine's host replay sees a
    deterministic (discarded) value instead of argmax-over-NaN.

    Runs INSIDE the engine's compiled mixed/decode programs and rides
    the decode-window scan carry: detection of a non-finite request —
    whatever layer the NaN entered at, since rows only mix within a
    slot on the ``ragged_paged_step`` path — costs no extra host sync.
    """
    lg = lg.astype(jnp.float32) + poison.reshape(-1)[:, None]
    bad = jnp.logical_not(jnp.all(jnp.isfinite(lg), axis=-1))
    nxt = jnp.where(bad, 0, lg.argmax(-1)).astype(jnp.int32)
    return nxt, bad


@primitive
def verify_argmax(lg, tok_slot, tok_valid, poison):
    """Per-ROW greedy pick + per-slot finiteness flag — the ragged
    VERIFY entry of the speculative decoding subsystem (ISSUE 9;
    ``inference/speculative.py``).

    Where :func:`guarded_argmax` serves one gathered row per slot, the
    speculative mixed program needs the target's greedy token after
    EVERY packed position: a slot's verify segment (current token + K
    drafts, ``q_lens = K+1``) yields K+1 candidate tokens, and the host
    accepts the longest prefix whose drafts agree — the variable
    per-slot advance that multiplies tokens per dispatch.

    ``lg`` [T, V] packed logits, ``tok_slot``/``tok_valid`` [T] the
    packing vectors, ``poison`` [B] float32 (0.0 normally; NaN for a
    slot the ``engine_nan_decode``/``engine_draft_nan`` drills poison —
    broadcast to the slot's rows, argmax-invariant when 0).  Returns
    ``(toks [T] int32, bad [B] bool)``: ``bad`` is the PER-DRAFT guard
    — ANY non-finite valid row fails its slot alone (padding rows are
    masked; their logits are garbage by contract), and a bad row's
    token is forced to 0 so the host replay sees a deterministic
    discarded value."""
    sl = tok_slot.reshape(-1).astype(jnp.int32)
    pv = poison.reshape(-1)
    lg = lg.astype(jnp.float32) + pv[sl][:, None]
    valid = tok_valid.reshape(-1).astype(jnp.bool_)
    row_bad = jnp.logical_not(jnp.all(jnp.isfinite(lg), axis=-1)) \
        & valid
    toks = jnp.where(row_bad, 0, lg.argmax(-1)).astype(jnp.int32)
    bad = jnp.zeros(pv.shape[0], jnp.int32).at[sl].max(
        row_bad.astype(jnp.int32)) > 0
    return toks, bad


@primitive
def fused_qkv_step(x, norm_params, weights, biases, positions,
                   block_tables, k_pages, v_pages, k_scales=None,
                   v_scales=None, norm="layer", eps=1e-5, n_heads=1,
                   n_kv_heads=1, head_dim=1, rope_theta=None,
                   rows=None):
    """Decode-megakernel INGRESS (ISSUE 18): pre-attention norm + QKV
    projection (+ rope) + paged-KV append as ONE fused dispatch —
    ``ops/pallas/fused_decode_qkv.py``.

    ``x`` [B, H] residual stream, ``norm_params`` [w] or [w, b],
    ``weights`` one fused [H, 3*nh*hd] projection (GPT) or [wq, wk, wv]
    (LLaMA, rope applied when ``rope_theta`` is set), ``biases`` a
    matching list or []. Pool updates go through the kernel's DMA
    append, byte-identical to :func:`_slot_page_write` (the int8 path
    replays ``quantization.kv_quantize``'s exact math). Returns
    ``(q [B, nh, hd], k_pages, v_pages[, k_scales, v_scales])``.
    """
    from ..ops.pallas.fused_decode_qkv import fused_decode_qkv
    nw = norm_params[0]
    nb = norm_params[1] if len(norm_params) > 1 else None
    return fused_decode_qkv(
        x, nw, nb, list(weights), list(biases),
        positions.reshape(-1), block_tables, k_pages, v_pages,
        k_scales=k_scales, v_scales=v_scales, norm=norm, eps=eps,
        n_heads=n_heads, n_kv_heads=n_kv_heads, head_dim=head_dim,
        rope_theta=rope_theta, rows=rows)


@primitive
def paged_attend(q, k_pages, v_pages, block_tables, positions,
                 scale=None, pages_per_block=None, k_scales=None,
                 v_scales=None):
    """Attention half of :func:`paged_slot_attention` alone (the
    megakernel path appends K/V inside :func:`fused_qkv_step`, so its
    middle dispatch only reads the pools).  ``q`` [B, nh, hd] already
    squeezed; the positions→lengths and dtype conventions are verbatim
    ``paged_slot_attention``'s, so bytes cannot drift between the fused
    and unfused decode paths."""
    from ..ops.pallas.paged_attention import paged_decode_attention
    p = positions.reshape(-1).astype(jnp.int32)
    out = paged_decode_attention(q, k_pages, v_pages,
                                 block_tables.astype(jnp.int32), p + 1,
                                 scale=scale,
                                 pages_per_block=pages_per_block,
                                 k_scales=k_scales, v_scales=v_scales)
    return out.astype(q.dtype)


@primitive
def fused_mlp_step(x, att, wo, norm_params, w1, w2, bo=None, b1=None,
                   b2=None, w_up=None, arch="gpt", norm="layer",
                   eps=1e-5, rows=None):
    """Decode-megakernel EGRESS (ISSUE 18): out-projection + residual
    + post-norm + MLP + residual as ONE fused dispatch —
    ``ops/pallas/fused_decode_mlp.py``.  ``x`` [B, H] residual stream,
    ``att`` [B, nh*hd] attention output; returns the next layer's
    residual stream [B, H]."""
    from ..ops.pallas.fused_decode_mlp import fused_decode_mlp
    nw = norm_params[0]
    nb = norm_params[1] if len(norm_params) > 1 else None
    return fused_decode_mlp(x, att, wo, bo, nw, nb, w1, b1, w2, b2,
                            w_up, arch=arch, norm=norm, eps=eps,
                            rows=rows)


@primitive
def fused_decode_logits(x, norm_params, w_lm, poison, b_lm=None,
                        norm="layer", eps=1e-5, transpose_lm=False,
                        rows=None):
    """Decode-megakernel SAMPLING EPILOGUE (ISSUE 18): final norm +
    lm_head + the :func:`guarded_argmax` finiteness-guarded greedy pick
    as ONE fused dispatch.  ``transpose_lm`` selects the tied-embedding
    ``matmul(h, wte, transpose_y=True)`` spelling.  Returns
    ``(logits [B, V] pre-poison, nxt [B] int32, bad [B] bool)`` — nxt
    and bad exactly match ``guarded_argmax(logits, poison)``."""
    from ..ops.pallas.fused_decode_mlp import fused_decode_epilogue
    nw = norm_params[0]
    nb = norm_params[1] if len(norm_params) > 1 else None
    return fused_decode_epilogue(x, nw, nb, w_lm, b_lm,
                                 poison.reshape(-1), norm=norm,
                                 eps=eps, transpose_lm=transpose_lm,
                                 rows=rows)


@primitive
def cache_prefill(k_new, v_new, k_cache, v_cache):
    """Write the WHOLE prompt's K/V [B, S, Hkv, D] into cache[:, :S] in
    one shot (batched prefill — the serving-path complement of the
    per-token ``cache_attention``; the reference reaches this via its
    fused multi-transformer prefill kernels)."""
    kc = lax.dynamic_update_slice_in_dim(
        k_cache, k_new.astype(k_cache.dtype), 0, axis=1)
    vc = lax.dynamic_update_slice_in_dim(
        v_cache, v_new.astype(v_cache.dtype), 0, axis=1)
    return kc, vc


@primitive
def paged_cache_prefill(k_new, v_new, k_pages, v_pages,
                        block_tables=None):
    """Scatter the prompt's K/V [B, S, Hkv, D] into the page pools at
    (page, slot) = (bt[b, t//ps], t%ps) for t in [0, S)."""
    b, s, hk, d = k_new.shape
    bt = jnp.asarray(np.asarray(block_tables), jnp.int32)
    ps = k_pages.shape[2]
    t = jnp.arange(s)
    page = bt[:, t // ps]                        # [B, S]
    slot = jnp.broadcast_to(t % ps, (b, s))      # [B, S]
    kn = jnp.transpose(k_new, (2, 0, 1, 3)).astype(k_pages.dtype)
    vn = jnp.transpose(v_new, (2, 0, 1, 3)).astype(v_pages.dtype)
    k_pages = k_pages.at[:, page, slot].set(kn)
    v_pages = v_pages.at[:, page, slot].set(vn)
    return k_pages, v_pages


def _apply_rope(x, cos, sin):
    """Rotate-half application — the ONE body both rope primitives share
    (llama.rope_angles is the one home of the angle convention)."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos + rotated * sin


@primitive
def rope_at(x, pos, theta=10000.0):
    """Half-rotation rope at explicit positions (decode / serving).
    Convention comes from llama.rope_angles (single home — training and
    decode paths cannot drift).  Three position shapes:

    * pos [1] (classic decode): one traced position for the whole batch;
    * pos [B] matching x [B, 1, H, D]: per-slot positions (the
      continuous-batching decode step — every slot is at its own depth);
    * pos [T] matching x [1, T, H, D]: per-token positions (the packed
      ragged prefill+decode step).
    """
    from .llama import rope_angles
    p = pos.reshape(-1)
    n = p.shape[0]
    cos, sin = rope_angles(p, x.shape[-1], theta)        # [n, D]
    if n == 1:
        cos, sin = cos.reshape(-1), sin.reshape(-1)      # broadcast all
    elif n == x.shape[0]:
        cos, sin = cos[:, None, None, :], sin[:, None, None, :]
    elif n == x.shape[1]:
        cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    else:
        raise ValueError(
            f"rope_at: {n} positions do not match x {x.shape}")
    return _apply_rope(x, cos, sin)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _zero_pool(shape, count, dtype="float32"):
    """``count`` zeroed arrays of ``shape`` in ONE device launch (jit's
    static-arg cache keeps one compiled program per geometry): a
    12-layer KV pool as 24 separate ``jnp.zeros`` dispatches pays 24
    launches of per-request latency over a network-attached chip.
    ``dtype`` (static string) lets the quantized serving engine build
    int8 data pools and f32 scale pools through the same program
    cache."""
    return tuple(jnp.zeros(shape, jnp.dtype(dtype))
                 for _ in range(count))


def make_import_scatter(n_pools, out_shardings=None):
    """The KV-page import scatter program (PR13 handoff, reused by
    ISSUE 20 live-migration restore): ONE donated jit per pool
    geometry that writes a payload's page rows into the pool pages
    named by ``idx``.  The page-id vector is traced DATA (padded to
    the block-table width by the caller), so every import/restore of
    a geometry rides the same compiled program; donation keeps the
    update in-place in HBM.  ``out_shardings`` pins the TP kv-head
    sharding when the pools live on a mesh."""
    def imp(idx, *args):
        pools, payload = args[:n_pools], args[n_pools:]
        return tuple(p.at[:, idx].set(pl.astype(p.dtype))
                     for p, pl in zip(pools, payload))

    kw = {} if out_shardings is None else {
        "out_shardings": tuple(out_shardings)}
    return jax.jit(imp, donate_argnums=tuple(range(1, 1 + n_pools)),
                   **kw)


def _split_caches(caches, n_layers):
    """Serving cache-list layout: ``[k0, v0, ..., kL-1, vL-1]`` for fp
    pools, with the int8 path APPENDING the per-page scale side-pools
    ``[ks0, vs0, ..., ksL-1, vsL-1]`` (``inference/engine.py`` builds
    the list; the length is self-describing).  Returns
    ``(data, scales)`` with ``scales == []`` on the fp path — the ONE
    place the decode/ragged forwards learn whether KV is quantized."""
    n = 2 * n_layers
    if len(caches) == 2 * n:
        return caches[:n], caches[n:]
    if len(caches) != n:
        raise ValueError(
            f"expected {n} (fp) or {2 * n} (int8 + scales) cache pools "
            f"for {n_layers} layers, got {len(caches)}")
    return caches, []


def _empty_caches(model, batch, max_len):
    cfg = model.cfg
    n_kv = getattr(cfg, "num_kv_heads", cfg.num_heads)
    shape = (batch, max_len, n_kv, cfg.head_dim)
    return [Tensor(a) for a in _zero_pool(shape, 2 * cfg.num_layers)]


def _attend_layer(attend, q, k, v, data, scales, li, pos):
    """One layer's cache update + attention, fp or int8: returns
    ``(att, new_data_pair, new_scale_pair)``.  The quantized call adds
    the layer's scale pools and gets them back updated."""
    kc, vc = data[2 * li], data[2 * li + 1]
    if scales:
        ks, vs = scales[2 * li], scales[2 * li + 1]
        att, kc, vc, ks, vs = attend(q, k, v, kc, vc, pos, ks, vs)
        return att, [kc, vc], [ks, vs]
    att, kc, vc = attend(q, k, v, kc, vc, pos)
    return att, [kc, vc], []


def _gpt_decode(model, ids_t, pos, caches, attend=cache_attention):
    """One-token logits for GPTForCausalLM given flat [k0,v0,k1,v1,...]
    caches (int8 serving appends scale pools — ``_split_caches``);
    returns (logits [B, V], new caches). ``pos`` may be [1]
    (one shared position) or [B] (per-slot positions — the serving
    engine's continuously-batched decode)."""
    from .. import ops
    gpt = model.gpt
    data, scales = _split_caches(caches, len(gpt.blocks))
    x = gpt.wte(ids_t) + gpt.wpe(ops.reshape(pos, [-1, 1]))
    new, new_sc = [], []
    for li, blk in enumerate(gpt.blocks):
        h = blk.ln1(x)
        b, s, hidden = h.shape
        qkv = ops.reshape(blk.attn.qkv(h),
                          [b, 1, 3, blk.attn.num_heads,
                           blk.attn.head_dim])
        q, k, v = ops.unbind(qkv, axis=2)
        att, pair, sc_pair = _attend_layer(attend, q, k, v, data,
                                           scales, li, pos)
        x = x + blk.attn.proj(ops.reshape(att, [b, 1, hidden]))
        x = x + blk.mlp(blk.ln2(x))
        new.extend(pair)
        new_sc.extend(sc_pair)
    h = gpt.ln_f(x)
    if model.lm_head is not None:
        logits = model.lm_head(h)
    else:
        logits = ops.matmul(h, gpt.wte.weight, transpose_y=True)
    return ops.reshape(logits, [logits.shape[0], -1]), new + new_sc


def _llama_decode(model, ids_t, pos, caches, attend=cache_attention):
    from .. import ops
    lm = model.llama
    data, scales = _split_caches(caches, len(lm.layers))
    x = lm.embed_tokens(ids_t)
    new, new_sc = [], []
    for li, layer in enumerate(lm.layers):
        att_in = layer.input_norm(x)
        a = layer.attn
        b = att_in.shape[0]
        q = ops.reshape(a.q_proj(att_in), [b, 1, a.num_heads, a.head_dim])
        k = ops.reshape(a.k_proj(att_in),
                        [b, 1, a.num_kv_heads, a.head_dim])
        v = ops.reshape(a.v_proj(att_in),
                        [b, 1, a.num_kv_heads, a.head_dim])
        q = rope_at(q, pos, theta=a.rope_theta)
        k = rope_at(k, pos, theta=a.rope_theta)
        att, pair, sc_pair = _attend_layer(attend, q, k, v, data,
                                           scales, li, pos)
        x = x + a.o_proj(ops.reshape(att, [b, 1, -1]))
        x = x + layer.mlp(layer.post_norm(x))
        new.extend(pair)
        new_sc.extend(sc_pair)
    h = lm.norm(x)
    if model.lm_head is not None:
        logits = model.lm_head(h)
    else:
        logits = ops.matmul(h, lm.embed_tokens.weight, transpose_y=True)
    return ops.reshape(logits, [logits.shape[0], -1]), new + new_sc


def _gpt_decode_fused(model, ids_t, pos, bt, caches, poison,
                      pages_per_block=None):
    """Megakernel decode step for GPTForCausalLM (ISSUE 18): one-token
    forward in ~3 fused dispatches per layer (:func:`fused_qkv_step` →
    :func:`paged_attend` → :func:`fused_mlp_step`) plus the
    :func:`fused_decode_logits` sampling epilogue, against the serving
    engine's per-slot paged caches.  ``bt`` rides as DATA (a traced
    [B, NP] tensor — the block-tables-as-data discipline that keeps the
    engine recompile-free); ``poison`` is the decode guard's [B] lane.
    Returns ``(logits [B, V], nxt [B] i32, bad [B] bool, new caches)``
    — logits/token/bad streams byte-identical to :func:`_gpt_decode`
    over ``paged_slot_attention`` + ``guarded_argmax``."""
    from .. import ops
    gpt = model.gpt
    data, scales = _split_caches(caches, len(gpt.blocks))
    x = gpt.wte(ids_t) + gpt.wpe(ops.reshape(pos, [-1, 1]))
    b = x.shape[0]
    x = ops.reshape(x, [b, x.shape[-1]])
    new, new_sc = [], []
    for li, blk in enumerate(gpt.blocks):
        a = blk.attn
        ks = scales[2 * li] if scales else None
        vs = scales[2 * li + 1] if scales else None
        outs = fused_qkv_step(
            x, [blk.ln1.weight, blk.ln1.bias], [a.qkv.weight],
            [a.qkv.bias], pos, bt, data[2 * li], data[2 * li + 1],
            k_scales=ks, v_scales=vs, norm="layer",
            eps=blk.ln1._epsilon, n_heads=a.num_heads,
            n_kv_heads=a.num_heads, head_dim=a.head_dim)
        q, kc, vc = outs[0], outs[1], outs[2]
        ks2 = vs2 = None
        if ks is not None:
            ks2, vs2 = outs[3], outs[4]
            new_sc.extend([ks2, vs2])
        new.extend([kc, vc])
        att = paged_attend(q, kc, vc, bt, pos,
                           pages_per_block=pages_per_block,
                           k_scales=ks2, v_scales=vs2)
        x = fused_mlp_step(x, ops.reshape(att, [b, -1]), a.proj.weight,
                           [blk.ln2.weight, blk.ln2.bias],
                           blk.mlp.fc1.weight, blk.mlp.fc2.weight,
                           bo=a.proj.bias, b1=blk.mlp.fc1.bias,
                           b2=blk.mlp.fc2.bias, arch="gpt",
                           norm="layer", eps=blk.ln2._epsilon)
    if model.lm_head is not None:
        w_lm, tr = model.lm_head.weight, False
    else:
        w_lm, tr = gpt.wte.weight, True
    logits, nxt, bad = fused_decode_logits(
        x, [gpt.ln_f.weight, gpt.ln_f.bias], w_lm, poison,
        norm="layer", eps=gpt.ln_f._epsilon, transpose_lm=tr)
    return logits, nxt, bad, new + new_sc


def _llama_decode_fused(model, ids_t, pos, bt, caches, poison,
                        pages_per_block=None):
    """Megakernel decode step for LlamaForCausalLM — rope folds into
    the ingress kernel (``rope_theta``), SwiGLU into the egress; see
    :func:`_gpt_decode_fused`."""
    from .. import ops
    lm = model.llama
    data, scales = _split_caches(caches, len(lm.layers))
    x = lm.embed_tokens(ids_t)
    b = x.shape[0]
    x = ops.reshape(x, [b, x.shape[-1]])
    new, new_sc = [], []
    for li, layer in enumerate(lm.layers):
        a = layer.attn
        ks = scales[2 * li] if scales else None
        vs = scales[2 * li + 1] if scales else None
        outs = fused_qkv_step(
            x, [layer.input_norm.weight],
            [a.q_proj.weight, a.k_proj.weight, a.v_proj.weight], [],
            pos, bt, data[2 * li], data[2 * li + 1], k_scales=ks,
            v_scales=vs, norm="rms", eps=layer.input_norm._epsilon,
            n_heads=a.num_heads, n_kv_heads=a.num_kv_heads,
            head_dim=a.head_dim, rope_theta=a.rope_theta)
        q, kc, vc = outs[0], outs[1], outs[2]
        ks2 = vs2 = None
        if ks is not None:
            ks2, vs2 = outs[3], outs[4]
            new_sc.extend([ks2, vs2])
        new.extend([kc, vc])
        att = paged_attend(q, kc, vc, bt, pos,
                           pages_per_block=pages_per_block,
                           k_scales=ks2, v_scales=vs2)
        x = fused_mlp_step(x, ops.reshape(att, [b, -1]),
                           a.o_proj.weight, [layer.post_norm.weight],
                           layer.mlp.gate_proj.weight,
                           layer.mlp.down_proj.weight,
                           w_up=layer.mlp.up_proj.weight, arch="llama",
                           norm="rms", eps=layer.post_norm._epsilon)
    if model.lm_head is not None:
        w_lm, tr = model.lm_head.weight, False
    else:
        w_lm, tr = lm.embed_tokens.weight, True
    logits, nxt, bad = fused_decode_logits(
        x, [lm.norm.weight], w_lm, poison, norm="rms",
        eps=lm.norm._epsilon, transpose_lm=tr)
    return logits, nxt, bad, new + new_sc


def _decode_fused_fn(model):
    """Megakernel analog of :func:`_decode_fn` (ISSUE 18) — the fused
    decode-step body for the serving engine's ``megakernel`` path."""
    from .gpt import GPTForCausalLM
    from .llama import LlamaForCausalLM
    if isinstance(model, GPTForCausalLM):
        return _gpt_decode_fused
    if isinstance(model, LlamaForCausalLM):
        return _llama_decode_fused
    raise TypeError(
        f"megakernel: unsupported model {type(model).__name__}")


def _ragged_attend_layer(q, k, v, data, scales, li, tok_pos, tok_slot,
                         tok_valid, kv_lens, q_lens, bt, q_block,
                         pages_per_block):
    """One layer's packed-token page write + ragged attention, fp or
    int8 (the :func:`_attend_layer` analog for the mixed serving step):
    returns ``(att, new_data_pair, new_scale_pair)``."""
    kc, vc = data[2 * li], data[2 * li + 1]
    if scales:
        att, kc, vc, ks, vs = ragged_paged_step(
            q, k, v, kc, vc, tok_pos, tok_slot, tok_valid, kv_lens,
            q_lens, bt, q_block=q_block,
            pages_per_block=pages_per_block,
            k_scales=scales[2 * li], v_scales=scales[2 * li + 1])
        return att, [kc, vc], [ks, vs]
    att, kc, vc = ragged_paged_step(
        q, k, v, kc, vc, tok_pos, tok_slot, tok_valid, kv_lens,
        q_lens, bt, q_block=q_block, pages_per_block=pages_per_block)
    return att, [kc, vc], []


def _gpt_ragged_forward(model, ids_t, tok_pos, tok_slot, tok_valid,
                        kv_lens, q_lens, bt, caches, q_block,
                        pages_per_block=None):
    """Packed-token forward for a continuously-batched serving step:
    ``ids_t`` [1, T] carries prefill chunks AND single decode tokens of
    all slots (segments in slot order, ``q_block``-padded); per-token
    position/slot/validity vectors drive the page writes and the ragged
    attention.  Returns ([T, V] logits — padding rows garbage — and the
    new page pools)."""
    from .. import ops
    gpt = model.gpt
    data, scales = _split_caches(caches, len(gpt.blocks))
    t = ids_t.shape[1]
    x = gpt.wte(ids_t) + gpt.wpe(ops.reshape(tok_pos, [1, -1]))
    new, new_sc = [], []
    for li, blk in enumerate(gpt.blocks):
        h = blk.ln1(x)
        hd, nh = blk.attn.head_dim, blk.attn.num_heads
        qkv = ops.reshape(blk.attn.qkv(h), [t, 3, nh, hd])
        q, k, v = ops.unbind(qkv, axis=1)                  # [T, nh, hd]
        att, pair, sc_pair = _ragged_attend_layer(
            q, k, v, data, scales, li, tok_pos, tok_slot, tok_valid,
            kv_lens, q_lens, bt, q_block, pages_per_block)
        x = x + blk.attn.proj(ops.reshape(att, [1, t, nh * hd]))
        x = x + blk.mlp(blk.ln2(x))
        new.extend(pair)
        new_sc.extend(sc_pair)
    h = gpt.ln_f(x)
    if model.lm_head is not None:
        logits = model.lm_head(h)
    else:
        logits = ops.matmul(h, gpt.wte.weight, transpose_y=True)
    return ops.reshape(logits, [t, -1]), new + new_sc


def _llama_ragged_forward(model, ids_t, tok_pos, tok_slot, tok_valid,
                          kv_lens, q_lens, bt, caches, q_block,
                          pages_per_block=None):
    from .. import ops
    lm = model.llama
    data, scales = _split_caches(caches, len(lm.layers))
    t = ids_t.shape[1]
    x = lm.embed_tokens(ids_t)
    new, new_sc = [], []
    for li, layer in enumerate(lm.layers):
        att_in = layer.input_norm(x)
        a = layer.attn
        q = ops.reshape(a.q_proj(att_in), [1, t, a.num_heads, a.head_dim])
        k = ops.reshape(a.k_proj(att_in),
                        [1, t, a.num_kv_heads, a.head_dim])
        v = ops.reshape(a.v_proj(att_in),
                        [1, t, a.num_kv_heads, a.head_dim])
        q = rope_at(q, tok_pos, theta=a.rope_theta)
        k = rope_at(k, tok_pos, theta=a.rope_theta)
        att, pair, sc_pair = _ragged_attend_layer(
            ops.reshape(q, [t, a.num_heads, a.head_dim]),
            ops.reshape(k, [t, a.num_kv_heads, a.head_dim]),
            ops.reshape(v, [t, a.num_kv_heads, a.head_dim]),
            data, scales, li, tok_pos, tok_slot, tok_valid,
            kv_lens, q_lens, bt, q_block, pages_per_block)
        x = x + a.o_proj(ops.reshape(att, [1, t, -1]))
        x = x + layer.mlp(layer.post_norm(x))
        new.extend(pair)
        new_sc.extend(sc_pair)
    h = lm.norm(x)
    if model.lm_head is not None:
        logits = model.lm_head(h)
    else:
        logits = ops.matmul(h, lm.embed_tokens.weight, transpose_y=True)
    return ops.reshape(logits, [t, -1]), new + new_sc


def _ragged_fn(model):
    """Family dispatch for the packed continuous-batching forward."""
    from .gpt import GPTForCausalLM
    from .llama import LlamaForCausalLM
    if isinstance(model, GPTForCausalLM):
        return _gpt_ragged_forward
    if isinstance(model, LlamaForCausalLM):
        return _llama_ragged_forward
    raise TypeError(
        f"serving engine: unsupported model {type(model).__name__}")


@primitive
def rope_span(x, theta=10000.0):
    """Half-rotation rope over positions 0..S-1 for the prefill pass:
    x [B, S, H, D]. Angles/application share the rope_at homes (f64
    tables like the training path — the decode path's traced-f32 angles
    differ in low-order bits, the same tolerance the cached-vs-full
    parity test already covers)."""
    from .llama import rope_angles
    cos, sin = rope_angles(np.arange(x.shape[1]), x.shape[-1], theta)
    return _apply_rope(x, jnp.asarray(cos)[None, :, None, :],
                       jnp.asarray(sin)[None, :, None, :])


def _prompt_attention(q, k, v, use_flash=True):
    import paddle_tpu.nn.functional as F
    return F.scaled_dot_product_attention(
        q, k, v, is_causal=True, dropout_p=0.0,
        backend=None if use_flash else "xla")


def _gpt_prefill(model, ids, caches, write):
    """Whole-prompt forward that fills the KV caches and returns the
    LAST position's logits — one compiled pass instead of S decode
    steps (the serving prefill/decode split)."""
    from .. import ops
    gpt = model.gpt
    b, s = ids.shape
    x = gpt.wte(ids) + gpt.wpe(ops.arange(0, s, dtype="int32"))
    new = []
    for li, blk in enumerate(gpt.blocks):
        h = blk.ln1(x)
        qkv = ops.reshape(blk.attn.qkv(h),
                          [b, s, 3, blk.attn.num_heads, blk.attn.head_dim])
        q, k, v = ops.unbind(qkv, axis=2)
        kc, vc = write(k, v, caches[2 * li], caches[2 * li + 1])
        att = _prompt_attention(q, k, v, blk.attn.use_flash)
        x = x + blk.attn.proj(ops.reshape(att, [b, s, -1]))
        x = x + blk.mlp(blk.ln2(x))
        new.extend([kc, vc])
    h = gpt.ln_f(x)
    last = h[:, s - 1:s]
    if model.lm_head is not None:
        logits = model.lm_head(last)
    else:
        logits = ops.matmul(last, gpt.wte.weight, transpose_y=True)
    return ops.reshape(logits, [b, -1]), new


def _llama_prefill(model, ids, caches, write):
    from .. import ops
    lm = model.llama
    b, s = ids.shape
    x = lm.embed_tokens(ids)
    new = []
    for li, layer in enumerate(lm.layers):
        att_in = layer.input_norm(x)
        a = layer.attn
        q = ops.reshape(a.q_proj(att_in), [b, s, a.num_heads, a.head_dim])
        k = ops.reshape(a.k_proj(att_in),
                        [b, s, a.num_kv_heads, a.head_dim])
        v = ops.reshape(a.v_proj(att_in),
                        [b, s, a.num_kv_heads, a.head_dim])
        q = rope_span(q, theta=a.rope_theta)
        k = rope_span(k, theta=a.rope_theta)
        kc, vc = write(k, v, caches[2 * li], caches[2 * li + 1])
        att = _prompt_attention(q, k, v,
                                model.cfg.use_flash_attention)
        x = x + a.o_proj(ops.reshape(att, [b, s, -1]))
        x = x + layer.mlp(layer.post_norm(x))
        new.extend([kc, vc])
    h = lm.norm(x)
    last = h[:, s - 1:s]
    if model.lm_head is not None:
        logits = model.lm_head(last)
    else:
        logits = ops.matmul(last, lm.embed_tokens.weight, transpose_y=True)
    return ops.reshape(logits, [b, -1]), new


def _decode_fn(model):
    """(decode_fn, prefill_fn, hard_position_limit): GPT's learned wpe
    table makes max_seq_len a hard bound; LLaMA's rope extrapolates."""
    from .gpt import GPTForCausalLM
    from .llama import LlamaForCausalLM
    if isinstance(model, GPTForCausalLM):
        return _gpt_decode, _gpt_prefill, True
    if isinstance(model, LlamaForCausalLM):
        return _llama_decode, _llama_prefill, False
    raise TypeError(f"generate: unsupported model {type(model).__name__}")


def _empty_paged_caches(model, batch, max_len, page_size):
    """Per-layer page pools [Hkv, B * pages_per_seq, page_size, D] plus the
    static block table (sequence b owns pages [b*NP, (b+1)*NP) — the
    deterministic allocation of uniform batched decode; a serving-style
    allocator would supply its own table)."""
    cfg = model.cfg
    n_kv = getattr(cfg, "num_kv_heads", cfg.num_heads)
    np_per_seq = -(-max_len // page_size)
    bt = np.arange(batch * np_per_seq, dtype=np.int32).reshape(
        batch, np_per_seq)
    shape = (n_kv, batch * np_per_seq, page_size, cfg.head_dim)
    caches = [Tensor(a) for a in _zero_pool(shape, 2 * cfg.num_layers)]
    return caches, bt


def _make_decode_window(exe, K, temperature, top_p, has_eos):
    """Fold K decode steps of a compiled step into ONE program: forward,
    sampling and the eos mask all run on device; the sampled token feeds
    back through the scan carry. One host dispatch per K tokens instead
    of per token — the serving analog of ``jit.multi_step``."""
    from jax import lax

    pure = exe._pure
    n_ret = exe.n_ret                      # logits + caches
    n_caches = n_ret - 1
    capt = exe.capt_state
    carry_idx, const_idx = exe.state_split()
    greedy = (top_p is None and temperature == 1.0)

    def window(tok, pos, caches, cstate, const_state, finished, eos_id,
               key):
        def body(c, _):
            tok, pos, caches, cstate, fin, key = c
            state = [None] * len(capt)
            for i, v in zip(carry_idx, cstate):
                state[i] = v
            for i, v in zip(const_idx, const_state):
                state[i] = v
            outs = pure(tok, pos, *caches, *state)
            lg = outs[0].astype(jnp.float32)
            new_caches = list(outs[1:1 + n_caches])
            new_cstate = list(outs[1 + n_caches:
                                   1 + n_caches + len(carry_idx)])
            if greedy:
                nxt = lg.argmax(-1).astype(jnp.int32)
            else:
                key, sub = jax.random.split(key)
                lg = lg / max(float(temperature), 1e-6)
                if top_p is not None:
                    from ..ops.special import nucleus_sample_jnp
                    p = jnp.full((lg.shape[0],), float(top_p),
                                 jnp.float32)
                    _, tok2d = nucleus_sample_jnp(sub, lg, p)
                    nxt = tok2d[:, 0].astype(jnp.int32)
                else:
                    nxt = jax.random.categorical(
                        sub, lg, axis=-1).astype(jnp.int32)
            if has_eos:
                nxt = jnp.where(fin, eos_id, nxt)
                fin = fin | (nxt == eos_id)
            return (nxt[:, None], pos + 1, new_caches, new_cstate, fin,
                    key), nxt

        (tok, pos, caches, cstate, fin, key), toks = lax.scan(
            body, (tok, pos, caches, cstate, finished, key), None,
            length=K)
        return toks, tok, pos, caches, cstate, fin, key

    return jax.jit(window, donate_argnums=(2, 3))


def generate(model, input_ids, max_new_tokens=32, temperature=1.0,
             top_p=None, eos_token_id=None, seed=None, use_jit=True,
             kv_cache="dense", page_size=16, prefill=True,
             decode_window=None):
    """Greedy / temperature / nucleus decoding with a KV cache.

    ``input_ids`` [B, S] prompt; returns [B, S + max_new_tokens] int32
    (rows stop changing after ``eos_token_id``). One compiled decode step
    serves both prefill and generation (same static shapes).

    ``kv_cache="paged"`` stores KV in a page pool with per-sequence block
    tables and attends through the Pallas paged-decode kernel (the
    reference's ``block_multi_head_attention`` serving path): attention
    compute scales with the current length instead of ``max_len``, the
    win at long sequences.

    ``prefill=True`` (default) processes the whole prompt in ONE compiled
    forward that fills the KV caches — prompt cost is a single pass
    instead of prompt_len decode steps (the serving prefill/decode
    split). ``prefill=False`` keeps the pure token-by-token path.

    ``decode_window``: scan K decode steps (forward + sampling + eos
    masking, all on device) into ONE dispatch — over a network-attached
    chip the wall time per token drops ~K-fold. Defaults to 8 for greedy
    decoding; sampling paths default to 1 because the windowed sampler
    draws from the device RNG stream (a different, equally-seeded stream
    than the host path) — pass decode_window>1 to opt in.
    """
    from .. import jit as jit_mod
    from ..ops.special import top_p_sampling

    if kv_cache not in ("dense", "paged"):
        raise ValueError(f"kv_cache must be 'dense' or 'paged', "
                         f"got {kv_cache!r}")
    decode, prefill_fn, hard_limit = _decode_fn(model)
    ids = np.asarray(input_ids.numpy()
                     if isinstance(input_ids, Tensor) else input_ids)
    batch, prompt_len = ids.shape
    max_len = prompt_len + max_new_tokens
    cfg = model.cfg
    if max_len > cfg.max_seq_len:
        if hard_limit:  # learned position table: out-of-range = garbage
            raise ValueError(f"max_len {max_len} exceeds max_seq_len "
                             f"{cfg.max_seq_len}")
        import warnings
        warnings.warn(f"generating past max_seq_len ({max_len} > "
                      f"{cfg.max_seq_len}): rope extrapolation territory")
    if kv_cache == "paged":
        import functools
        caches, bt = _empty_paged_caches(model, batch, max_len, page_size)
        attend = functools.partial(paged_cache_attention,
                                   block_tables=bt.tolist())
        write = functools.partial(paged_cache_prefill,
                                  block_tables=bt.tolist())
    else:
        caches = _empty_caches(model, batch, max_len)
        attend = cache_attention
        write = cache_prefill
    if decode_window is None:
        decode_window = 8 if (top_p is None and temperature == 1.0) else 1
    was_training = model.training
    model.eval()
    try:
        return _generate_loop(model, decode, prefill_fn, ids, batch,
                              prompt_len, max_len, max_new_tokens,
                              temperature, top_p, eos_token_id, seed,
                              use_jit, caches, attend, write, kv_cache,
                              prefill, decode_window)
    finally:
        if was_training:
            model.train()


def _generate_loop(model, decode, prefill_fn, ids, batch, prompt_len,
                   max_len, max_new_tokens, temperature, top_p,
                   eos_token_id, seed, use_jit, caches,
                   attend=cache_attention, write=cache_prefill,
                   kv_cache="dense", prefill=True, decode_window=1):
    from .. import jit as jit_mod
    from ..ops.special import top_p_sampling

    # compiled decode step cached per (batch, max_len) ON the model:
    # repeat generate() calls reuse the program instead of re-tracing.
    # page geometry is part of the key: the attend closure bakes in the
    # block table, whose shape depends on page_size.
    n_pages = caches[0].shape[1] if kv_cache == "paged" else 0
    cache_key = (batch, max_len, kv_cache, n_pages)
    step_cache = model.__dict__.setdefault("_decode_step_cache", {})
    step_fn = step_cache.get(cache_key)
    if step_fn is None:

        def step(tok, pos, *cs):
            import paddle_tpu as pp
            with pp.no_grad():
                logits, new = decode(model, tok, pos, list(cs),
                                     attend=attend)
            return (logits,) + tuple(new)

        step_fn = jit_mod.to_static(step) if use_jit else step
        if use_jit:
            step_cache[cache_key] = step_fn

    out = np.concatenate(
        [ids, np.zeros((batch, max_new_tokens), ids.dtype)], axis=1)
    finished = np.zeros(batch, bool)

    # batched prefill: ONE compiled whole-prompt pass fills the caches
    # and yields the first sampled token, replacing prompt_len-1 decode
    # steps (cached per (batch, prompt_len, cache kind) on the model)
    t_start = 0
    prefill_logits = None
    if prefill and prompt_len > 1:
        pf_key = ("prefill", batch, prompt_len, kv_cache, n_pages)
        pf_fn = step_cache.get(pf_key)
        if pf_fn is None:

            def pf(tok_ids, *cs):
                import paddle_tpu as pp
                with pp.no_grad():
                    logits, new = prefill_fn(model, tok_ids, list(cs),
                                             write)
                return (logits,) + tuple(new)

            pf_fn = jit_mod.to_static(pf) if use_jit else pf
            if use_jit:
                step_cache[pf_key] = pf_fn
        res = pf_fn(Tensor(jnp.asarray(ids.astype(np.int32))), *caches)
        prefill_logits, caches = res[0], list(res[1:])
        t_start = prompt_len - 1

    t = t_start
    while t < max_len - 1:  # last token needs no forward
        # windowed fast path: K tokens per dispatch, sampling on device.
        # Needs a compiled step (>=1 scalar call done), generation-region
        # positions, and >=2 tokens left in the window.
        if (decode_window > 1 and use_jit and t >= prompt_len - 1
                and t > t_start):
            wrapped = (step_fn if hasattr(step_fn, "_cache")
                       else getattr(step_fn, "__wrapped__", None))
            exe = (next(iter(wrapped._cache.values()), None)
                   if wrapped is not None and wrapped._cache else None)
            remaining = max_len - 1 - t
            if exe is not None and remaining >= 2:
                t = _run_decode_windows(
                    exe, out, t, remaining, decode_window,
                    caches, finished, temperature, top_p, eos_token_id,
                    seed)
                if eos_token_id is not None and finished.all():
                    # trim exactly where the scalar path would: one past
                    # the LAST row's first eos (windows may have written
                    # eos padding beyond it)
                    hit = out[:, prompt_len:t + 1] == eos_token_id
                    cols = prompt_len + hit.argmax(1)
                    out = out[:, :int(cols.max()) + 1]
                break

        if t == t_start and prefill_logits is not None:
            logits = prefill_logits
        else:
            tok = Tensor(jnp.asarray(out[:, t:t + 1].astype(np.int32)))
            pos = Tensor(jnp.asarray([t], jnp.int32))
            res = step_fn(tok, pos, *caches)
            logits, caches = res[0], list(res[1:])
        if t < prompt_len - 1:
            t += 1
            continue  # prompt region: ignore logits, just fill the cache
        lg = logits.numpy().astype(np.float32)
        if temperature != 1.0:
            lg = lg / max(temperature, 1e-6)
        if top_p is not None:
            # per-step key: seed+t keeps a seeded STREAM, not one quantile
            _, nxt = top_p_sampling(
                Tensor(jnp.asarray(lg)),
                Tensor(jnp.full((batch,), float(top_p))),
                seed=None if seed is None else seed + t)
            nxt = nxt.numpy().reshape(-1)
        elif temperature != 1.0:
            # temperature-only: categorical over the softened logits
            # (argmax would be scale-invariant, i.e. silently greedy)
            rng_t = np.random.default_rng(
                None if seed is None else seed + t)
            p = np.exp(lg - lg.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            nxt = np.array([rng_t.choice(p.shape[-1], p=row)
                            for row in p])
        else:
            nxt = lg.argmax(-1)
        if eos_token_id is not None:
            nxt = np.where(finished, eos_token_id, nxt)
            finished |= (nxt == eos_token_id)
        out[:, t + 1] = nxt.astype(out.dtype)
        if eos_token_id is not None and finished.all():
            out = out[:, :t + 2]
            break
        t += 1
    return Tensor(jnp.asarray(out.astype(np.int32)))


def _run_decode_windows(exe, out, t, remaining, decode_window,
                        caches, finished, temperature, top_p,
                        eos_token_id, seed):
    """Drive the scanned decode windows from position ``t`` (whose token
    is already in ``out``) to the end; returns the final position.
    Mutates ``out``/``finished`` in place and writes post-window state
    back onto the captured tensors."""
    has_eos = eos_token_id is not None
    capt = exe.capt_state
    carry_idx, const_idx = exe.state_split()
    for sync in exe.discovery.host_syncs:
        sync()
    cache_vals = [c._read() if isinstance(c, Tensor) else jnp.asarray(c)
                  for c in caches]
    cstate = [capt[i]._read() for i in carry_idx]
    const_state = [capt[i]._read() for i in const_idx]
    fin = jnp.asarray(finished)
    eos_id = jnp.int32(eos_token_id if has_eos else 0)
    # seed=None must stay genuinely random per call (the scalar path
    # draws fresh host randomness) — pull entropy from numpy
    key = jax.random.PRNGKey(
        seed if seed is not None
        else int(np.random.default_rng().integers(2 ** 31)))
    tok = jnp.asarray(out[:, t:t + 1].astype(np.int32))
    pos = jnp.asarray([t], jnp.int32)

    runners = exe.__dict__.setdefault("_decode_window_cache", {})
    # always run FULL windows (one compiled program per sampling config,
    # never per tail length); overshoot steps write into cache slots that
    # are discarded with the caches, and their tokens are sliced off
    K = decode_window
    rkey = (K, temperature, top_p, has_eos)
    runner = runners.get(rkey)
    if runner is None:
        runner = _make_decode_window(exe, K, temperature, top_p, has_eos)
        runners[rkey] = runner
        # whole-program audit once per window program (compile time
        # only; tracing does not consume the donated cache buffers)
        from .. import analysis as _analysis
        _analysis.audit_jitted(
            runner,
            (tok, pos, cache_vals, cstate, const_state, fin, eos_id,
             key),
            where=f"decode_window.{getattr(exe, '_fn_name', 'step')}")
    while remaining > 0:
        toks, tok, pos, cache_vals, cstate, fin, key = runner(
            tok, pos, cache_vals, cstate, const_state, fin, eos_id, key)
        valid = min(K, remaining)
        toks_np = np.asarray(toks)[:valid]       # [valid, B]
        out[:, t + 1:t + 1 + valid] = toks_np.T.astype(out.dtype)
        t += valid
        remaining -= valid
        if has_eos:
            # host mask from the WRITTEN tokens only (the device mask may
            # include overshoot-step hits on the final window)
            finished[:] = finished | (toks_np == eos_token_id).any(0)
            if finished.all():
                break
    for i, v in zip(carry_idx, cstate):
        capt[i]._data = v
        capt[i]._node = None
    return t


# ===================================================================
# Tensor-parallel serving programs (ISSUE 13; ``inference/distserve``)
# ===================================================================
#
# The serving engine's two compiled programs re-built for a mesh axis:
# weights column/row-split per the canonical Megatron rules
# (``GPTForCausalLMPipe.TP_RULES`` / ``shard_gpt``, re-laid-out
# HEAD-MAJOR so a ``PartitionSpec`` can split the fused qkv projection
# along heads instead of along its interleaved flat output dim), KV
# page pools sharded by kv-head, block tables / lengths / packing
# vectors replicated.  The program body runs under a fully-MANUAL
# ``core.meshutil.shard_map`` (partial-auto is broken on legacy jax and
# the Pallas ragged kernel cannot be GSPMD-partitioned anyway) with
# exactly ONE ``psum`` at the attention output projection and one at
# the MLP down-projection per layer — the textbook Megatron cut.
#
# GQA awareness: when ``Hk % tp == 0`` the K/V projections and pools
# shard with the q heads (contiguous head blocks keep the q->kv GQA
# mapping local).  When ``Hk < tp`` (and ``tp % Hk == 0``) the K/V
# side REPLICATES: every shard computes and writes all kv heads
# (identical bytes — the write is per-head deterministic), and each
# shard attends its q heads against a 1-head dynamic slice of the
# replicated pools (``tp/Hk`` consecutive shards serve one kv head).
#
# Greedy outputs are token-identical to the single-device engine: the
# only numerical difference is the psum's split reduction order
# (last-ulp on the logits), which the serving parity suite pins at the
# token level.

def _tp_axis_size(jmesh, axis):
    sizes = dict(zip(jmesh.axis_names, jmesh.devices.shape))
    if axis not in sizes:
        raise ValueError(
            f"tp_axis {axis!r} is not a mesh axis {jmesh.axis_names}")
    return int(sizes[axis])


class TPParams:
    """A model's weights re-laid-out + device_put for manual TP.

    ``names``/``vals``/``specs`` are parallel lists (the shard_map
    inputs and their ``PartitionSpec``s); ``meta`` carries the local
    geometry the program bodies need.  Extraction is a read-only
    SNAPSHOT of the model (serving engines own eval-mode models; the
    single-device engine sharing the model instance is untouched)."""

    __slots__ = ("names", "vals", "specs", "meta")

    def __init__(self, names, vals, specs, meta):
        self.names = names
        self.vals = vals
        self.specs = specs
        self.meta = meta


def tp_shard_params(model, jmesh, tp_axis):
    """Extract + shard a GPT/LLaMA's weights for the TP serving
    programs.  See the section comment for the layout; raises on head
    counts the cut cannot serve (``Hq % tp``, and for GQA
    ``Hk % tp and tp % Hk``)."""
    import jax as _jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .gpt import GPTForCausalLM
    from .llama import LlamaForCausalLM

    tp = _tp_axis_size(jmesh, tp_axis)
    cfg = model.cfg
    nh = cfg.num_heads
    hd = cfg.head_dim
    nhk = getattr(cfg, "num_kv_heads", nh)
    if nh % tp:
        raise ValueError(
            f"serving TP: num_heads {nh} not divisible by tp={tp}")
    if cfg.intermediate_size % tp:
        raise ValueError(
            f"serving TP: intermediate_size {cfg.intermediate_size} "
            f"not divisible by tp={tp}")
    shard_kv = nhk % tp == 0
    if not shard_kv and tp % nhk:
        raise ValueError(
            f"serving TP: GQA kv heads {nhk} neither divisible by nor "
            f"a divisor of tp={tp}")
    names, vals, specs = [], [], []

    def add(name, val, spec):
        names.append(name)
        vals.append(_jax.device_put(val, NamedSharding(jmesh, spec)))
        specs.append(spec)

    col = P(None, tp_axis)          # [h, out] split on out
    row = P(tp_axis)                # leading dim split
    rep = P()
    if isinstance(model, GPTForCausalLM):
        gpt = model.gpt
        add("wte", gpt.wte.weight._read(), rep)
        add("wpe", gpt.wpe.weight._read(), rep)
        for li, blk in enumerate(gpt.blocks):
            h = cfg.hidden_size
            add(f"b{li}.ln1.w", blk.ln1.weight._read(), rep)
            add(f"b{li}.ln1.b", blk.ln1.bias._read(), rep)
            # fused qkv: flat out dim is (3, nh, hd)-interleaved — a
            # contiguous column split would cut across q/k/v, so the
            # weight reshapes head-major and shards the HEAD dim
            add(f"b{li}.qkv.w",
                blk.attn.qkv.weight._read().reshape(h, 3, nh, hd),
                P(None, None, tp_axis))
            add(f"b{li}.qkv.b",
                blk.attn.qkv.bias._read().reshape(3, nh, hd),
                P(None, tp_axis))
            add(f"b{li}.proj.w",
                blk.attn.proj.weight._read().reshape(nh, hd, h), row)
            add(f"b{li}.proj.b", blk.attn.proj.bias._read(), rep)
            add(f"b{li}.ln2.w", blk.ln2.weight._read(), rep)
            add(f"b{li}.ln2.b", blk.ln2.bias._read(), rep)
            add(f"b{li}.fc1.w", blk.mlp.fc1.weight._read(), col)
            add(f"b{li}.fc1.b", blk.mlp.fc1.bias._read(), row)
            add(f"b{li}.fc2.w", blk.mlp.fc2.weight._read(), row)
            add(f"b{li}.fc2.b", blk.mlp.fc2.bias._read(), rep)
        add("ln_f.w", gpt.ln_f.weight._read(), rep)
        add("ln_f.b", gpt.ln_f.bias._read(), rep)
        if model.lm_head is not None:
            add("lm_head", model.lm_head.weight._read(), rep)
        family = "gpt"
    elif isinstance(model, LlamaForCausalLM):
        lm = model.llama
        add("wte", lm.embed_tokens.weight._read(), rep)
        for li, layer in enumerate(lm.layers):
            a = layer.attn
            h = cfg.hidden_size
            add(f"b{li}.in_norm.w", layer.input_norm.weight._read(),
                rep)
            add(f"b{li}.q.w",
                a.q_proj.weight._read().reshape(h, nh, hd),
                P(None, tp_axis))
            add(f"b{li}.k.w",
                a.k_proj.weight._read().reshape(h, nhk, hd),
                P(None, tp_axis) if shard_kv else rep)
            add(f"b{li}.v.w",
                a.v_proj.weight._read().reshape(h, nhk, hd),
                P(None, tp_axis) if shard_kv else rep)
            add(f"b{li}.o.w",
                a.o_proj.weight._read().reshape(nh, hd, h), row)
            add(f"b{li}.post_norm.w", layer.post_norm.weight._read(),
                rep)
            add(f"b{li}.gate.w", layer.mlp.gate_proj.weight._read(),
                col)
            add(f"b{li}.up.w", layer.mlp.up_proj.weight._read(), col)
            add(f"b{li}.down.w", layer.mlp.down_proj.weight._read(),
                row)
        add("norm.w", lm.norm.weight._read(), rep)
        if model.lm_head is not None:
            add("lm_head", model.lm_head.weight._read(), rep)
        family = "llama"
    else:
        raise TypeError(
            f"serving TP: unsupported model {type(model).__name__}")
    meta = {
        "family": family, "tp": tp, "axis": tp_axis,
        "nh_loc": nh // tp,
        "nhk_loc": nhk // tp if shard_kv else nhk,
        "shard_kv": shard_kv, "hd": hd,
        "shards_per_kv": 1 if shard_kv else tp // nhk,
    }
    return TPParams(names, vals, specs, meta)


def tp_cache_spec(meta, tp_axis):
    """PartitionSpec of one KV page pool (or scale side-pool) under
    this TP layout: sharded on the kv-head dim when ``Hk % tp == 0``,
    replicated otherwise (every shard writes all heads — identical
    bytes by construction)."""
    from jax.sharding import PartitionSpec as P
    return P(tp_axis) if meta["shard_kv"] else P()


def _tp_kv_slice(meta, pools, tp_axis):
    """The kv-head slice of (replicated) ``pools`` this shard attends
    with, or ``pools`` unchanged when they are head-sharded.  With
    ``Hk < tp``, ``tp/Hk`` consecutive shards serve one kv head, so
    the slice is ONE head at a traced per-shard offset."""
    if meta["shard_kv"]:
        return pools
    from jax import lax as _lax
    r = _lax.axis_index(meta["axis"])
    head = r // meta["shards_per_kv"]
    return [_lax.dynamic_slice_in_dim(p, head, 1, axis=0)
            for p in pools]


def _tp_attend_ragged(meta, q, kn, vn, kp, vp, tok_pos, tok_slot,
                      tok_valid, kv_lens, q_lens, bt, q_block, ppb,
                      ks=None, vs=None):
    """One layer's packed-token page write + ragged attention under
    TP.  Head-sharded pools go straight through
    :func:`ragged_paged_step`'s jnp body; replicated pools (GQA
    ``Hk < tp``) write ALL heads through the SAME
    :func:`_ragged_page_write` home (bytes cannot drift between the
    modes) and attend a 1-head slice."""
    from ..ops.pallas.paged_attention import ragged_paged_attention

    if meta["shard_kv"]:
        outs = ragged_paged_step.raw(
            q, kn, vn, kp, vp, tok_pos, tok_slot, tok_valid, kv_lens,
            q_lens, bt, q_block=q_block, pages_per_block=ppb,
            k_scales=ks, v_scales=vs)
        if ks is not None:
            att, kp, vp, ks, vs = outs
            return att, kp, vp, ks, vs
        att, kp, vp = outs
        return att, kp, vp, None, None
    bt_i = bt.astype(jnp.int32)
    knn = jnp.swapaxes(kn, 0, 1)                   # [Hk, T, D] (full)
    vnn = jnp.swapaxes(vn, 0, 1)
    kp, vp, ks, vs = _ragged_page_write(
        knn, vnn, kp, vp, bt_i, tok_pos, tok_slot, tok_valid, ks, vs)
    kp_s, vp_s = _tp_kv_slice(meta, [kp, vp], meta["axis"])
    sc_s = (_tp_kv_slice(meta, [ks, vs], meta["axis"])
            if ks is not None else (None, None))
    att = ragged_paged_attention(
        q, kp_s, vp_s, bt_i, kv_lens.astype(jnp.int32),
        q_lens.astype(jnp.int32), q_block=q_block,
        pages_per_block=ppb, k_scales=sc_s[0], v_scales=sc_s[1])
    return att.astype(q.dtype), kp, vp, ks, vs


def _tp_attend_decode(meta, q, kn, vn, kp, vp, positions, bt, ppb,
                      ks=None, vs=None):
    """Per-slot decode-step analog of :func:`_tp_attend_ragged`
    (replicated-KV writes go through :func:`_slot_page_write`, the
    same home ``paged_slot_attention`` uses)."""
    from ..ops.pallas.paged_attention import paged_decode_attention

    if meta["shard_kv"]:
        outs = paged_slot_attention.raw(
            q, kn, vn, kp, vp, positions, bt, pages_per_block=ppb,
            k_scales=ks, v_scales=vs)
        if ks is not None:
            att, kp, vp, ks, vs = outs
            return att, kp, vp, ks, vs
        att, kp, vp = outs
        return att, kp, vp, None, None
    p = positions.reshape(-1).astype(jnp.int32)
    bt_i = bt.astype(jnp.int32)
    knn = jnp.swapaxes(kn[:, 0], 0, 1)             # [Hk, B, D] (full)
    vnn = jnp.swapaxes(vn[:, 0], 0, 1)
    kp, vp, ks, vs = _slot_page_write(knn, vnn, kp, vp, bt_i,
                                      positions, ks, vs)
    kp_s, vp_s = _tp_kv_slice(meta, [kp, vp], meta["axis"])
    sc_s = (_tp_kv_slice(meta, [ks, vs], meta["axis"])
            if ks is not None else (None, None))
    att = paged_decode_attention(
        q[:, 0], kp_s, vp_s, bt_i, p + 1, pages_per_block=ppb,
        k_scales=sc_s[0], v_scales=sc_s[1])
    return att[:, None].astype(q.dtype), kp, vp, ks, vs


def _gpt_tp_body(model, tpp, q_block, ppb):
    """(ids, tok_pos, tok_slot, tok_valid, kv_lens, q_lens, bt, *flat)
    -> (logits [T, V] tp-replicated, new caches local) — the packed
    ragged forward under manual TP (shard_map body)."""
    from jax import lax as _lax

    from ..distributed.fleet.pipeline import functional_call
    from ..nn.functional.activation import _gelu_impl

    gpt = model.gpt
    meta = tpp.meta
    names = tpp.names
    n_p = len(names)
    L = len(gpt.blocks)
    axis = meta["axis"]

    def body(ids, tok_pos, tok_slot, tok_valid, kv_lens, q_lens, bt,
             *flat):
        pv = dict(zip(names, flat[:n_p]))
        caches = list(flat[n_p:])
        data, scales = _split_caches(caches, L)
        t = ids.shape[1]
        x = functional_call(gpt.wte, {"weight": pv["wte"]}, ids) \
            + functional_call(gpt.wpe, {"weight": pv["wpe"]},
                              tok_pos.reshape(1, -1))
        new, new_sc = [], []
        for li, blk in enumerate(gpt.blocks):
            h = functional_call(
                blk.ln1, {"weight": pv[f"b{li}.ln1.w"],
                          "bias": pv[f"b{li}.ln1.b"]}, x)
            h2 = h.reshape(t, -1)
            wq = pv[f"b{li}.qkv.w"]          # [h, 3, nh_loc, hd]
            qkv = (h2 @ wq.reshape(wq.shape[0], -1)
                   + pv[f"b{li}.qkv.b"].reshape(-1))
            qkv = qkv.reshape(t, 3, wq.shape[2], wq.shape[3])
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            ks = scales[2 * li] if scales else None
            vs = scales[2 * li + 1] if scales else None
            att, kc, vc, ks, vs = _tp_attend_ragged(
                meta, q, k, v, data[2 * li], data[2 * li + 1],
                tok_pos, tok_slot, tok_valid, kv_lens, q_lens, bt,
                q_block, ppb, ks, vs)
            new.extend([kc, vc])
            if ks is not None:
                new_sc.extend([ks, vs])
            wp = pv[f"b{li}.proj.w"]         # [nh_loc, hd, h]
            prj = att.reshape(t, -1) @ wp.reshape(-1, wp.shape[-1])
            prj = _lax.psum(prj, axis) + pv[f"b{li}.proj.b"]
            x = x + prj.reshape(1, t, -1)
            h = functional_call(
                blk.ln2, {"weight": pv[f"b{li}.ln2.w"],
                          "bias": pv[f"b{li}.ln2.b"]}, x)
            f1 = h.reshape(t, -1) @ pv[f"b{li}.fc1.w"] \
                + pv[f"b{li}.fc1.b"]
            f1 = _gelu_impl.raw(f1, approximate=True)
            f2 = f1 @ pv[f"b{li}.fc2.w"]
            f2 = _lax.psum(f2, axis) + pv[f"b{li}.fc2.b"]
            x = x + f2.reshape(1, t, -1)
        hf = functional_call(
            gpt.ln_f, {"weight": pv["ln_f.w"], "bias": pv["ln_f.b"]},
            x).reshape(t, -1)
        if model.lm_head is not None:
            logits = hf @ pv["lm_head"]
        else:
            logits = hf @ pv["wte"].T
        return logits, new + new_sc

    return body


def _gpt_tp_decode_body(model, tpp, ppb):
    """(tok [B,1], pos [B], bt, *flat) -> (logits [B, V], new caches)
    — the per-slot decode step under manual TP."""
    from jax import lax as _lax

    from ..distributed.fleet.pipeline import functional_call
    from ..nn.functional.activation import _gelu_impl

    gpt = model.gpt
    meta = tpp.meta
    names = tpp.names
    n_p = len(names)
    L = len(gpt.blocks)
    axis = meta["axis"]

    def body(tok, pos, bt, *flat):
        pv = dict(zip(names, flat[:n_p]))
        caches = list(flat[n_p:])
        data, scales = _split_caches(caches, L)
        b = tok.shape[0]
        x = functional_call(gpt.wte, {"weight": pv["wte"]}, tok) \
            + functional_call(gpt.wpe, {"weight": pv["wpe"]},
                              pos.reshape(-1, 1))
        new, new_sc = [], []
        for li, blk in enumerate(gpt.blocks):
            h = functional_call(
                blk.ln1, {"weight": pv[f"b{li}.ln1.w"],
                          "bias": pv[f"b{li}.ln1.b"]}, x)
            h2 = h.reshape(b, -1)
            wq = pv[f"b{li}.qkv.w"]
            qkv = (h2 @ wq.reshape(wq.shape[0], -1)
                   + pv[f"b{li}.qkv.b"].reshape(-1))
            qkv = qkv.reshape(b, 1, 3, wq.shape[2], wq.shape[3])
            q, k, v = (qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
            ks = scales[2 * li] if scales else None
            vs = scales[2 * li + 1] if scales else None
            att, kc, vc, ks, vs = _tp_attend_decode(
                meta, q, k, v, data[2 * li], data[2 * li + 1], pos,
                bt, ppb, ks, vs)
            new.extend([kc, vc])
            if ks is not None:
                new_sc.extend([ks, vs])
            wp = pv[f"b{li}.proj.w"]
            prj = att.reshape(b, -1) @ wp.reshape(-1, wp.shape[-1])
            prj = _lax.psum(prj, axis) + pv[f"b{li}.proj.b"]
            x = x + prj.reshape(b, 1, -1)
            h = functional_call(
                blk.ln2, {"weight": pv[f"b{li}.ln2.w"],
                          "bias": pv[f"b{li}.ln2.b"]}, x)
            f1 = h.reshape(b, -1) @ pv[f"b{li}.fc1.w"] \
                + pv[f"b{li}.fc1.b"]
            f1 = _gelu_impl.raw(f1, approximate=True)
            f2 = f1 @ pv[f"b{li}.fc2.w"]
            f2 = _lax.psum(f2, axis) + pv[f"b{li}.fc2.b"]
            x = x + f2.reshape(b, 1, -1)
        hf = functional_call(
            gpt.ln_f, {"weight": pv["ln_f.w"], "bias": pv["ln_f.b"]},
            x).reshape(b, -1)
        if model.lm_head is not None:
            logits = hf @ pv["lm_head"]
        else:
            logits = hf @ pv["wte"].T
        return logits, new + new_sc

    return body


def _llama_tp_body(model, tpp, q_block, ppb):
    from jax import lax as _lax

    from ..distributed.fleet.pipeline import functional_call

    lm = model.llama
    meta = tpp.meta
    names = tpp.names
    n_p = len(names)
    L = len(lm.layers)
    axis = meta["axis"]

    def body(ids, tok_pos, tok_slot, tok_valid, kv_lens, q_lens, bt,
             *flat):
        import jax as _jax
        pv = dict(zip(names, flat[:n_p]))
        caches = list(flat[n_p:])
        data, scales = _split_caches(caches, L)
        t = ids.shape[1]
        x = functional_call(lm.embed_tokens, {"weight": pv["wte"]},
                            ids)
        new, new_sc = [], []
        for li, layer in enumerate(lm.layers):
            a = layer.attn
            h = functional_call(
                layer.input_norm,
                {"weight": pv[f"b{li}.in_norm.w"]}, x)
            h2 = h.reshape(t, -1)
            wqq = pv[f"b{li}.q.w"]           # [h, nh_loc, hd]
            wkk = pv[f"b{li}.k.w"]           # [h, nhk_loc|nhk, hd]
            wvv = pv[f"b{li}.v.w"]
            q = (h2 @ wqq.reshape(wqq.shape[0], -1)).reshape(
                1, t, wqq.shape[1], wqq.shape[2])
            k = (h2 @ wkk.reshape(wkk.shape[0], -1)).reshape(
                1, t, wkk.shape[1], wkk.shape[2])
            v = (h2 @ wvv.reshape(wvv.shape[0], -1)).reshape(
                1, t, wvv.shape[1], wvv.shape[2])
            q = rope_at.raw(q, tok_pos, theta=a.rope_theta)
            k = rope_at.raw(k, tok_pos, theta=a.rope_theta)
            ks = scales[2 * li] if scales else None
            vs = scales[2 * li + 1] if scales else None
            att, kc, vc, ks, vs = _tp_attend_ragged(
                meta, q.reshape(t, wqq.shape[1], wqq.shape[2]),
                k.reshape(t, wkk.shape[1], wkk.shape[2]),
                v.reshape(t, wvv.shape[1], wvv.shape[2]),
                data[2 * li], data[2 * li + 1], tok_pos, tok_slot,
                tok_valid, kv_lens, q_lens, bt, q_block, ppb, ks, vs)
            new.extend([kc, vc])
            if ks is not None:
                new_sc.extend([ks, vs])
            wo = pv[f"b{li}.o.w"]            # [nh_loc, hd, h]
            prj = att.reshape(t, -1) @ wo.reshape(-1, wo.shape[-1])
            prj = _lax.psum(prj, axis)
            x = x + prj.reshape(1, t, -1)
            h = functional_call(
                layer.post_norm,
                {"weight": pv[f"b{li}.post_norm.w"]}, x)
            h2 = h.reshape(t, -1)
            f1 = _jax.nn.silu(h2 @ pv[f"b{li}.gate.w"]) \
                * (h2 @ pv[f"b{li}.up.w"])
            f2 = f1 @ pv[f"b{li}.down.w"]
            f2 = _lax.psum(f2, axis)
            x = x + f2.reshape(1, t, -1)
        hf = functional_call(lm.norm, {"weight": pv["norm.w"]},
                             x).reshape(t, -1)
        if model.lm_head is not None:
            logits = hf @ pv["lm_head"]
        else:
            logits = hf @ pv["wte"].T
        return logits, new + new_sc

    return body


def _llama_tp_decode_body(model, tpp, ppb):
    from jax import lax as _lax

    from ..distributed.fleet.pipeline import functional_call

    lm = model.llama
    meta = tpp.meta
    names = tpp.names
    n_p = len(names)
    L = len(lm.layers)
    axis = meta["axis"]

    def body(tok, pos, bt, *flat):
        import jax as _jax
        pv = dict(zip(names, flat[:n_p]))
        caches = list(flat[n_p:])
        data, scales = _split_caches(caches, L)
        b = tok.shape[0]
        x = functional_call(lm.embed_tokens, {"weight": pv["wte"]},
                            tok)
        new, new_sc = [], []
        for li, layer in enumerate(lm.layers):
            a = layer.attn
            h = functional_call(
                layer.input_norm,
                {"weight": pv[f"b{li}.in_norm.w"]}, x)
            h2 = h.reshape(b, -1)
            wqq = pv[f"b{li}.q.w"]
            wkk = pv[f"b{li}.k.w"]
            wvv = pv[f"b{li}.v.w"]
            q = (h2 @ wqq.reshape(wqq.shape[0], -1)).reshape(
                b, 1, wqq.shape[1], wqq.shape[2])
            k = (h2 @ wkk.reshape(wkk.shape[0], -1)).reshape(
                b, 1, wkk.shape[1], wkk.shape[2])
            v = (h2 @ wvv.reshape(wvv.shape[0], -1)).reshape(
                b, 1, wvv.shape[1], wvv.shape[2])
            q = rope_at.raw(q, pos, theta=a.rope_theta)
            k = rope_at.raw(k, pos, theta=a.rope_theta)
            ks = scales[2 * li] if scales else None
            vs = scales[2 * li + 1] if scales else None
            att, kc, vc, ks, vs = _tp_attend_decode(
                meta, q, k, v, data[2 * li], data[2 * li + 1], pos,
                bt, ppb, ks, vs)
            new.extend([kc, vc])
            if ks is not None:
                new_sc.extend([ks, vs])
            wo = pv[f"b{li}.o.w"]
            prj = att.reshape(b, -1) @ wo.reshape(-1, wo.shape[-1])
            prj = _lax.psum(prj, axis)
            x = x + prj.reshape(b, 1, -1)
            h = functional_call(
                layer.post_norm,
                {"weight": pv[f"b{li}.post_norm.w"]}, x)
            h2 = h.reshape(b, -1)
            f1 = _jax.nn.silu(h2 @ pv[f"b{li}.gate.w"]) \
                * (h2 @ pv[f"b{li}.up.w"])
            f2 = f1 @ pv[f"b{li}.down.w"]
            f2 = _lax.psum(f2, axis)
            x = x + f2.reshape(b, 1, -1)
        hf = functional_call(lm.norm, {"weight": pv["norm.w"]},
                             x).reshape(b, -1)
        if model.lm_head is not None:
            logits = hf @ pv["lm_head"]
        else:
            logits = hf @ pv["wte"].T
        return logits, new + new_sc

    return body


def _gpt_tp_decode_body_fused(model, tpp, ppb):
    """Megakernel TP decode step (ISSUE 18) — shard-local fused
    kernels inside the shard_map body, with the layer's psum contract
    UNCHANGED: the ingress kernel computes the shard's local heads and
    appends them to the shard-local pools, the attention kernel reads
    them back, and the out-projection matmul + psum + bias + residual
    stay at jnp level exactly where :func:`_gpt_tp_decode_body` puts
    them (one psum per matmul per layer — the collective schedule the
    program audit pins).  The MLP runs as a shard-local
    ``fused_decode_mlp_partial`` before its psum.  Only valid under the
    ``shard_kv`` regime (local pools hold the shard's kv heads);
    ``make_tp_window`` falls back to the unfused body otherwise.
    Signature gains ``poison``: the fused epilogue returns the guarded
    greedy pick in-graph, ``(logits, nxt, bad, new caches)``."""
    from jax import lax as _lax

    from ..distributed.fleet.pipeline import functional_call
    from ..ops.pallas.fused_decode_mlp import (fused_decode_epilogue,
                                               fused_decode_mlp_partial)
    from ..ops.pallas.fused_decode_qkv import fused_decode_qkv
    from ..ops.pallas.paged_attention import paged_decode_attention

    gpt = model.gpt
    meta = tpp.meta
    names = tpp.names
    n_p = len(names)
    L = len(gpt.blocks)
    axis = meta["axis"]

    def body(tok, pos, bt, poison, *flat):
        pv = dict(zip(names, flat[:n_p]))
        caches = list(flat[n_p:])
        data, scales = _split_caches(caches, L)
        b = tok.shape[0]
        x = functional_call(gpt.wte, {"weight": pv["wte"]}, tok) \
            + functional_call(gpt.wpe, {"weight": pv["wpe"]},
                              pos.reshape(-1, 1))
        x = x.reshape(b, -1)
        p = pos.reshape(-1).astype(jnp.int32)
        bt_i = bt.astype(jnp.int32)
        new, new_sc = [], []
        for li, blk in enumerate(gpt.blocks):
            wq = pv[f"b{li}.qkv.w"]          # [h, 3, nh_loc, hd]
            nh_loc, hd = wq.shape[2], wq.shape[3]
            ks = scales[2 * li] if scales else None
            vs = scales[2 * li + 1] if scales else None
            outs = fused_decode_qkv(
                x, pv[f"b{li}.ln1.w"], pv[f"b{li}.ln1.b"],
                [wq.reshape(wq.shape[0], -1)],
                [pv[f"b{li}.qkv.b"].reshape(-1)], p, bt_i,
                data[2 * li], data[2 * li + 1], k_scales=ks,
                v_scales=vs, norm="layer", eps=blk.ln1._epsilon,
                n_heads=nh_loc, n_kv_heads=nh_loc, head_dim=hd)
            q, kc, vc = outs[0], outs[1], outs[2]
            ks2 = vs2 = None
            if ks is not None:
                ks2, vs2 = outs[3], outs[4]
                new_sc.extend([ks2, vs2])
            new.extend([kc, vc])
            att = paged_decode_attention(
                q, kc, vc, bt_i, p + 1, pages_per_block=ppb,
                k_scales=ks2, v_scales=vs2).astype(q.dtype)
            wp = pv[f"b{li}.proj.w"]         # [nh_loc, hd, h]
            prj = att.reshape(b, -1) @ wp.reshape(-1, wp.shape[-1])
            prj = _lax.psum(prj, axis) + pv[f"b{li}.proj.b"]
            y1 = x + prj
            f2 = fused_decode_mlp_partial(
                y1, pv[f"b{li}.ln2.w"], pv[f"b{li}.ln2.b"],
                pv[f"b{li}.fc1.w"], pv[f"b{li}.fc1.b"],
                pv[f"b{li}.fc2.w"], arch="gpt", norm="layer",
                eps=blk.ln2._epsilon)
            f2 = _lax.psum(f2, axis) + pv[f"b{li}.fc2.b"]
            x = y1 + f2
        if model.lm_head is not None:
            w_lm, tr = pv["lm_head"], False
        else:
            w_lm, tr = pv["wte"], True
        logits, nxt, bad = fused_decode_epilogue(
            x, pv["ln_f.w"], pv["ln_f.b"], w_lm, None,
            poison.reshape(-1), norm="layer", eps=gpt.ln_f._epsilon,
            transpose_lm=tr)
        return logits, nxt, bad, new + new_sc

    return body


def _llama_tp_decode_body_fused(model, tpp, ppb):
    """LLaMA analog of :func:`_gpt_tp_decode_body_fused` (rope in the
    ingress kernel, SwiGLU partial in the egress)."""
    from jax import lax as _lax

    from ..distributed.fleet.pipeline import functional_call
    from ..ops.pallas.fused_decode_mlp import (fused_decode_epilogue,
                                               fused_decode_mlp_partial)
    from ..ops.pallas.fused_decode_qkv import fused_decode_qkv
    from ..ops.pallas.paged_attention import paged_decode_attention

    lm = model.llama
    meta = tpp.meta
    names = tpp.names
    n_p = len(names)
    L = len(lm.layers)
    axis = meta["axis"]

    def body(tok, pos, bt, poison, *flat):
        pv = dict(zip(names, flat[:n_p]))
        caches = list(flat[n_p:])
        data, scales = _split_caches(caches, L)
        b = tok.shape[0]
        x = functional_call(lm.embed_tokens, {"weight": pv["wte"]},
                            tok).reshape(b, -1)
        p = pos.reshape(-1).astype(jnp.int32)
        bt_i = bt.astype(jnp.int32)
        new, new_sc = [], []
        for li, layer in enumerate(lm.layers):
            a = layer.attn
            wqq = pv[f"b{li}.q.w"]           # [h, nh_loc, hd]
            wkk = pv[f"b{li}.k.w"]           # [h, nhk_loc, hd]
            wvv = pv[f"b{li}.v.w"]
            ks = scales[2 * li] if scales else None
            vs = scales[2 * li + 1] if scales else None
            outs = fused_decode_qkv(
                x, pv[f"b{li}.in_norm.w"], None,
                [wqq.reshape(wqq.shape[0], -1),
                 wkk.reshape(wkk.shape[0], -1),
                 wvv.reshape(wvv.shape[0], -1)], [], p, bt_i,
                data[2 * li], data[2 * li + 1], k_scales=ks,
                v_scales=vs, norm="rms",
                eps=layer.input_norm._epsilon, n_heads=wqq.shape[1],
                n_kv_heads=wkk.shape[1], head_dim=wqq.shape[2],
                rope_theta=a.rope_theta)
            q, kc, vc = outs[0], outs[1], outs[2]
            ks2 = vs2 = None
            if ks is not None:
                ks2, vs2 = outs[3], outs[4]
                new_sc.extend([ks2, vs2])
            new.extend([kc, vc])
            att = paged_decode_attention(
                q, kc, vc, bt_i, p + 1, pages_per_block=ppb,
                k_scales=ks2, v_scales=vs2).astype(q.dtype)
            wo = pv[f"b{li}.o.w"]            # [nh_loc, hd, h]
            prj = att.reshape(b, -1) @ wo.reshape(-1, wo.shape[-1])
            prj = _lax.psum(prj, axis)
            y1 = x + prj
            f2 = fused_decode_mlp_partial(
                y1, pv[f"b{li}.post_norm.w"], None,
                pv[f"b{li}.gate.w"], None, pv[f"b{li}.down.w"],
                w_up=pv[f"b{li}.up.w"], arch="llama", norm="rms",
                eps=layer.post_norm._epsilon)
            f2 = _lax.psum(f2, axis)
            x = y1 + f2
        if model.lm_head is not None:
            w_lm, tr = pv["lm_head"], False
        else:
            w_lm, tr = pv["wte"], True
        logits, nxt, bad = fused_decode_epilogue(
            x, pv["norm.w"], None, w_lm, None, poison.reshape(-1),
            norm="rms", eps=lm.norm._epsilon, transpose_lm=tr)
        return logits, nxt, bad, new + new_sc

    return body


def _tp_body_fns(model):
    from .gpt import GPTForCausalLM
    from .llama import LlamaForCausalLM
    if isinstance(model, GPTForCausalLM):
        return _gpt_tp_body, _gpt_tp_decode_body
    if isinstance(model, LlamaForCausalLM):
        return _llama_tp_body, _llama_tp_decode_body
    raise TypeError(
        f"serving TP: unsupported model {type(model).__name__}")


def _tp_decode_fused_fns(model):
    """Megakernel analog of :func:`_tp_body_fns` (decode half only —
    the mixed/spec programs keep the unfused body)."""
    from .gpt import GPTForCausalLM
    from .llama import LlamaForCausalLM
    if isinstance(model, GPTForCausalLM):
        return _gpt_tp_decode_body_fused
    if isinstance(model, LlamaForCausalLM):
        return _llama_tp_decode_body_fused
    raise TypeError(
        f"serving TP: unsupported model {type(model).__name__}")


def make_tp_mixed(model, tpp, jmesh, q_block, ppb, n_caches):
    """The TP MIXED serving program: same call signature as the
    single-device engine's compiled mixed step (packing vectors +
    poison + block tables + cache pools), jitted over a fully-manual
    shard_map of the TP forward, ``guarded_argmax`` running replicated
    after the final psum so every shard returns the identical token
    and bad-flag vectors."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    from ..core.meshutil import shard_map
    meta = tpp.meta
    axis = meta["axis"]
    ragged_body, _ = _tp_body_fns(model)
    body = ragged_body(model, tpp, q_block, ppb)
    cspec = tp_cache_spec(meta, axis)

    def mixed(ids, tok_pos, tok_slot, tok_valid, kv_lens, q_lens,
              last_idx, poison, bt, *flat):
        logits, new = body(ids, tok_pos, tok_slot, tok_valid, kv_lens,
                           q_lens, bt, *flat)
        lg = logits[last_idx]                         # [B, V]
        nxt, bad = guarded_argmax.raw(lg, poison)
        return (nxt, bad) + tuple(new)

    rep = P()
    in_specs = (rep,) * 9 + tuple(tpp.specs) \
        + (cspec,) * n_caches
    out_specs = (rep, rep) + (cspec,) * n_caches
    return _jax.jit(shard_map(mixed, jmesh, in_specs=in_specs,
                              out_specs=out_specs))


def make_tp_spec(model, tpp, jmesh, q_block, ppb, n_caches,
                 need_logits):
    """The TP speculative VERIFY program (``verify_argmax`` over the
    packed logits; ``need_logits`` adds the gathered per-slot logits
    rows the sampling acceptance rule consumes)."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    from ..core.meshutil import shard_map
    meta = tpp.meta
    axis = meta["axis"]
    ragged_body, _ = _tp_body_fns(model)
    body = ragged_body(model, tpp, q_block, ppb)
    cspec = tp_cache_spec(meta, axis)
    rep = P()

    if need_logits:
        def spec(ids, tok_pos, tok_slot, tok_valid, kv_lens, q_lens,
                 poison, gather_idx, bt, *flat):
            logits, new = body(ids, tok_pos, tok_slot, tok_valid,
                               kv_lens, q_lens, bt, *flat)
            toks, bad = verify_argmax.raw(logits, tok_slot, tok_valid,
                                          poison)
            return (toks, bad, logits[gather_idx]) + tuple(new)
        n_in, n_head = 9, 3
    else:
        def spec(ids, tok_pos, tok_slot, tok_valid, kv_lens, q_lens,
                 poison, bt, *flat):
            logits, new = body(ids, tok_pos, tok_slot, tok_valid,
                               kv_lens, q_lens, bt, *flat)
            toks, bad = verify_argmax.raw(logits, tok_slot, tok_valid,
                                          poison)
            return (toks, bad) + tuple(new)
        n_in, n_head = 8, 2

    in_specs = (rep,) * n_in + tuple(tpp.specs) + (cspec,) * n_caches
    out_specs = (rep,) * n_head + (cspec,) * n_caches
    return _jax.jit(shard_map(spec, jmesh, in_specs=in_specs,
                              out_specs=out_specs))


def make_tp_window(model, tpp, jmesh, ppb, n_caches, K,
                   megakernel=False):
    """K scanned TP decode steps in ONE dispatch — the
    ``_make_slot_window`` analog with explicit params instead of
    captured executable state.  Same carry (token, position, finished,
    guard-bad per slot + caches), same freeze rule, same stacked
    per-step bad flags; cache pools are donated.

    ``megakernel`` (ISSUE 18) swaps the scan body for the fused
    ``_*_tp_decode_body_fused`` step: ~3 fused dispatches per layer,
    the guarded greedy pick fused into the epilogue kernel, identical
    token/bad streams.  The fused TP step needs shard-local KV pools,
    so the non-``shard_kv`` regime (GQA ``Hk < tp``, replicated pools)
    silently keeps the unfused body — correctness first, fusion where
    the layout allows it."""
    import jax as _jax
    from jax import lax as _lax
    from jax.sharding import PartitionSpec as P

    from ..core.meshutil import shard_map
    meta = tpp.meta
    axis = meta["axis"]
    use_mk = bool(megakernel) and bool(meta["shard_kv"])
    if use_mk:
        step_body = _tp_decode_fused_fns(model)(model, tpp, ppb)
    else:
        _, decode_body_fn = _tp_body_fns(model)
        step_body = decode_body_fn(model, tpp, ppb)
    cspec = tp_cache_spec(meta, axis)
    rep = P()
    n_p = len(tpp.names)

    def window(tok, pos, fin, bad, eos_ids, stop_lens, poison, bt,
               *flat):
        params = flat[:n_p]
        caches = list(flat[n_p:])

        def body(c, _):
            tok, pos, fin, bad, caches = c
            if use_mk:
                _, nxt_raw, row_bad, new_caches = step_body(
                    tok, pos, bt, poison, *params, *caches)
            else:
                lg, new_caches = step_body(tok, pos, bt, *params,
                                           *caches)
                lg = lg.astype(jnp.float32)
                nxt_raw, row_bad = guarded_argmax.raw(lg, poison)
            bad2 = bad | (row_bad & jnp.logical_not(fin))
            adv = jnp.logical_not(fin | bad2)
            nxt = jnp.where(adv, nxt_raw, tok[:, 0])
            pos2 = jnp.where(adv, pos + 1, pos)
            fin2 = fin | bad2 | ((eos_ids >= 0) & (nxt == eos_ids)) \
                | (pos2 + 1 >= stop_lens)
            return (nxt[:, None], pos2, fin2, bad2,
                    list(new_caches)), (nxt, bad2)

        (tok, pos, fin, bad, caches), (toks, bads) = _lax.scan(
            body, (tok, pos, fin, bad, caches), None, length=K)
        return (toks, bads, tok, pos, fin, bad) + tuple(caches)

    in_specs = (rep,) * 8 + tuple(tpp.specs) + (cspec,) * n_caches
    out_specs = (rep,) * 6 + (cspec,) * n_caches
    fn = shard_map(window, jmesh, in_specs=in_specs,
                   out_specs=out_specs)
    # donate the cache pools (the last n_caches positional args)
    donate = tuple(range(8 + n_p, 8 + n_p + n_caches))
    return _jax.jit(fn, donate_argnums=donate)

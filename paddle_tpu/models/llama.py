"""LLaMA-family decoder model (BASELINE config 4: "GPT-1.3B/LLaMA-7B
TP+PP+recompute+flash-attn").

Capability analog of the LLaMA configs the reference trains through fleet
(model defs live in PaddleNLP; the mechanics are in-tree: rms_norm + rope
fused kernels ``paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu``,
``rms_norm_kernel``, flash attention with GQA
``python/paddle/nn/functional/flash_attention.py:147``, mp_layers TP).

Same TPU-native shape as ``gpt.py``: one model class, parallelism applied
afterwards (``shard_llama``); the compute path rides the Pallas tier
(flash attention with grouped-query heads, fused rms_norm, rope).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.layers import Embedding, Linear, RMSNorm


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 0          # 0 -> num_heads (MHA); < heads = GQA
    max_seq_len: int = 2048
    intermediate_size: int = 0     # 0 -> the LLaMA 8/3 * hidden rule
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_embeddings: bool = False
    use_flash_attention: bool = True
    recompute: bool = False
    recompute_policy: str = "full"

    def __post_init__(self):
        if self.num_kv_heads == 0:
            self.num_kv_heads = self.num_heads
        if self.intermediate_size == 0:
            # LLaMA sizing: 2/3 * 4h rounded up to a multiple of 256
            raw = int(8 * self.hidden_size / 3)
            self.intermediate_size = 256 * ((raw + 255) // 256)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


def _init(std=0.02):
    return I.Normal(mean=0.0, std=std)


def _glue_fusion() -> bool:
    from ..core import state
    return bool(state.get_flag("train_glue_fusion"))


def rope_angles(positions, d, theta):
    """Half-rotation rope tables: (cos, sin) [..., d] for ``positions``
    (numpy or traced jnp values). SINGLE home of the LLaMA rope
    convention — the training path (_rope_tables) and the KV-cache decode
    path (generation.rope_at) both read it.

    Concrete positions compute in float64 (f32 loses ~1e-4 rad at
    position 2048 — enough to drift checkpoints); traced positions (the
    decode path) necessarily stay f32, still within the cache/full parity
    tolerance."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    if not isinstance(positions, jax.core.Tracer):
        inv = 1.0 / theta ** (np.arange(0, d // 2) * 2.0 / d)
        ang = np.asarray(positions, np.float64)[..., None] * inv
        ang = np.concatenate([ang, ang], axis=-1)
        return (jnp.asarray(np.cos(ang), jnp.float32),
                jnp.asarray(np.sin(ang), jnp.float32))
    inv = 1.0 / theta ** (jnp.arange(0, d // 2) * 2.0 / d)
    ang = positions[..., None].astype(jnp.float32) * inv
    ang = jnp.concatenate([ang, ang], axis=-1)
    return jnp.cos(ang), jnp.sin(ang)


class LlamaAttention(Layer):
    """Rope + grouped-query flash attention. KV projections emit
    ``num_kv_heads`` heads; the Pallas kernel maps q-head -> kv-head
    (the reference's GQA flash_attn path)."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        h, d = cfg.hidden_size, cfg.head_dim
        self.num_heads = cfg.num_heads
        self.num_kv_heads = cfg.num_kv_heads
        self.head_dim = d
        self.rope_theta = cfg.rope_theta
        self.q_proj = Linear(h, cfg.num_heads * d, bias_attr=False,
                             weight_attr=_init())
        self.k_proj = Linear(h, cfg.num_kv_heads * d, bias_attr=False,
                             weight_attr=_init())
        self.v_proj = Linear(h, cfg.num_kv_heads * d, bias_attr=False,
                             weight_attr=_init())
        self.o_proj = Linear(cfg.num_heads * d, h, bias_attr=False,
                             weight_attr=_init(0.02 / math.sqrt(
                                 2 * cfg.num_layers)))

    def _rope_tables(self, s):
        """cos/sin [s, head_dim] for this config's rope_theta."""
        import numpy as np
        cos, sin = rope_angles(np.arange(s), self.head_dim,
                               self.rope_theta)
        return Tensor(cos), Tensor(sin)

    def forward(self, x):
        from .. import ops
        from ..incubate.nn.functional import \
            fused_rotary_position_embedding as rope
        b, s, h = x.shape
        q = ops.reshape(self.q_proj(x), [b, s, self.num_heads,
                                         self.head_dim])
        k = ops.reshape(self.k_proj(x), [b, s, self.num_kv_heads,
                                         self.head_dim])
        v = ops.reshape(self.v_proj(x), [b, s, self.num_kv_heads,
                                         self.head_dim])
        # half-rotation convention (LLaMA/HF); explicit tables carry
        # this config's rope_theta (the kernel default is base 10000)
        cos, sin = self._rope_tables(s)
        q, k, _ = rope(q, k, sin=sin, cos=cos,
                       use_neox_rotary_style=False)
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return self.o_proj(ops.reshape(out, [b, s, -1]))


class LlamaMLP(Layer):
    """SwiGLU FFN (gate/up/down), the reference's fused swiglu path."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.gate_proj = Linear(cfg.hidden_size, cfg.intermediate_size,
                                bias_attr=False, weight_attr=_init())
        self.up_proj = Linear(cfg.hidden_size, cfg.intermediate_size,
                              bias_attr=False, weight_attr=_init())
        self.down_proj = Linear(
            cfg.intermediate_size, cfg.hidden_size, bias_attr=False,
            weight_attr=_init(0.02 / math.sqrt(2 * cfg.num_layers)))

    def forward(self, x):
        from ..incubate.nn.functional import swiglu
        return self.down_proj(swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_norm = RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)
        self.attn = LlamaAttention(cfg)
        self.post_norm = RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)
        self.mlp = LlamaMLP(cfg)
        self._recompute = cfg.recompute
        self._policy = (cfg.recompute_policy
                        if cfg.recompute_policy != "full" else None)

    def _inner(self, x):
        x = x + self.attn(self.input_norm(x))
        return x + self.mlp(self.post_norm(x))

    def forward(self, x):
        if self._recompute and self.training:
            from ..distributed.fleet.recompute import recompute
            return recompute(self._inner, x, policy=self._policy)
        return self._inner(x)

    def _inner_fused(self, x, pending=None):
        """Glue-fused twin of ``_inner`` (train_glue_fusion, ISSUE 19):
        same pending-branch threading as GPTBlock._inner_fused — the
        previous layer's un-added MLP branch fuses with this layer's
        input_norm, the attention branch with post_norm; the RMS pair
        (add, norm) runs as one fused dispatch each."""
        if pending is None:
            h1 = self.input_norm(x)
        else:
            x, h1 = F.fused_residual_norm(
                x, pending, self.input_norm.weight, norm="rms",
                epsilon=self.input_norm._epsilon)
        a = self.attn(h1)
        x, h2 = F.fused_residual_norm(
            x, a, self.post_norm.weight, norm="rms",
            epsilon=self.post_norm._epsilon)
        return x, self.mlp(h2)

    def forward_fused(self, x, pending=None):
        if self._recompute and self.training:
            from ..distributed.fleet.recompute import recompute
            if pending is None:
                return recompute(self._inner_fused, x,
                                 policy=self._policy)
            return recompute(self._inner_fused, x, pending,
                             policy=self._policy)
        return self._inner_fused(x, pending)


class LlamaModel(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.embed_tokens = Embedding(cfg.vocab_size, cfg.hidden_size,
                                      weight_attr=_init())
        self.layers = [LlamaDecoderLayer(cfg) for _ in range(cfg.num_layers)]
        for i, l in enumerate(self.layers):
            self.add_sublayer(f"layer_{i}", l)
        self.norm = RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)

    def forward(self, input_ids):
        x = self.embed_tokens(input_ids)
        if self.training and self.layers and _glue_fusion():
            pending = None
            for l in self.layers:
                x, pending = l.forward_fused(x, pending)
            _, h = F.fused_residual_norm(
                x, pending, self.norm.weight, norm="rms",
                epsilon=self.norm._epsilon)
            return h
        for l in self.layers:
            x = l(x)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    """LM head on top; ``forward(ids, labels)`` = mean next-token CE."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.llama = LlamaModel(cfg)
        if cfg.tie_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size,
                                  bias_attr=False, weight_attr=_init())

    def logits(self, input_ids) -> Tensor:
        from .. import ops
        h = self.llama(input_ids)
        if self.lm_head is not None:
            return self.lm_head(h)
        return ops.matmul(h, self.llama.embed_tokens.weight,
                          transpose_y=True)

    def forward(self, input_ids, labels=None):
        from .. import ops
        logits = self.logits(input_ids)
        if labels is None:
            return logits
        return F.cross_entropy(
            ops.reshape(logits, [-1, self.cfg.vocab_size]),
            ops.reshape(labels, [-1]))

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def flops_per_token(self, seq_len: int) -> float:
        n = self.num_params()
        attn = 12 * self.cfg.num_layers * self.cfg.hidden_size * seq_len
        return 6.0 * n + attn


def shard_llama(model: LlamaForCausalLM, mesh, dp_axis="dp", mp_axis="mp"):
    """Megatron TP recipe: column-parallel q/k/v/gate/up (output dim over
    mp), row-parallel o/down (input dim over mp), vocab-parallel
    embedding + head. KV heads shard over mp too — valid while
    ``num_kv_heads % mp == 0`` (the reference's GQA TP constraint)."""
    from ..distributed.auto_parallel.api import (Replicate, Shard,
                                                 shard_parameter)

    names = mesh.dim_names
    if mp_axis not in names:
        return model
    mp = dict(zip(getattr(mesh, "jmesh", mesh).axis_names,
                  getattr(mesh, "jmesh", mesh).devices.shape))[mp_axis]
    if model.cfg.num_kv_heads % mp:
        raise ValueError(f"num_kv_heads {model.cfg.num_kv_heads} not "
                         f"divisible by mp degree {mp}")
    mp_dim = names.index(mp_axis)

    def pl(tensor_dim):
        p = [Replicate()] * mesh.ndim
        p[mp_dim] = Shard(tensor_dim)
        return p

    shard_parameter(model.llama.embed_tokens.weight, mesh, pl(0))
    for l in model.llama.layers:
        shard_parameter(l.attn.q_proj.weight, mesh, pl(1))
        shard_parameter(l.attn.k_proj.weight, mesh, pl(1))
        shard_parameter(l.attn.v_proj.weight, mesh, pl(1))
        shard_parameter(l.attn.o_proj.weight, mesh, pl(0))
        shard_parameter(l.mlp.gate_proj.weight, mesh, pl(1))
        shard_parameter(l.mlp.up_proj.weight, mesh, pl(1))
        shard_parameter(l.mlp.down_proj.weight, mesh, pl(0))
    if model.lm_head is not None:
        shard_parameter(model.lm_head.weight, mesh, pl(1))
    return model

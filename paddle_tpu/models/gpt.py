"""GPT-family decoder-only language model — the flagship trainable.

Capability analog of the GPT/LLaMA configs the reference trains through
fleet hybrid parallelism (SURVEY §6 configs 4-5; the reference keeps model
defs downstream in PaddleNLP, e.g. its ``GPTForPretraining``, but the
training mechanics — VocabParallelEmbedding / Column-RowParallelLinear
sharding, flash attention, recompute — are reference in-tree features:
``python/paddle/distributed/fleet/layers/mpu/mp_layers.py:47,333,540``,
``python/paddle/nn/functional/flash_attention.py:147``,
``python/paddle/distributed/fleet/recompute/recompute.py:404``).

TPU-native: one model class, parallelism applied *afterwards* as GSPMD
sharding (``shard_gpt``) instead of swapping layer classes — the mesh axes
decide dp/tp/sp; XLA's partitioner emits the Megatron collectives.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.layers import Dropout, Embedding, LayerNorm, Linear


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    intermediate_size: int = 0  # 0 -> 4*hidden
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    tie_embeddings: bool = True
    use_flash_attention: bool = True
    recompute: bool = False  # activation recompute per block (jax.checkpoint)
    recompute_policy: str = "full"  # or "dots_saveable" (keep matmul outs)
    # MoE (0 = dense FFN). Experts shard over the ep axis via shard_gpt.
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


def _init_normal(std):
    return I.Normal(mean=0.0, std=std)


def _glue_fusion() -> bool:
    """train_glue_fusion flag (ISSUE 19): fused residual+norm glue
    kernels in the TRAINING forward. Read per forward — one dict
    lookup; eval/serving paths never consult it (callers also gate on
    ``self.training``)."""
    from ..core import state
    return bool(state.get_flag("train_glue_fusion"))


class GPTAttention(Layer):
    """Causal self-attention with a fused qkv projection (the shape the
    reference fuses in ``fused_attention``-family kernels, SURVEY C12)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.head_dim
        self.qkv = Linear(h, 3 * h, weight_attr=_init_normal(0.02))
        self.proj = Linear(
            h, h, weight_attr=_init_normal(0.02 / math.sqrt(2 * cfg.num_layers)))
        self.dropout = cfg.dropout
        self.use_flash = cfg.use_flash_attention
        # context parallelism (ring attention over an sp mesh axis) —
        # wired by shard_gpt(..., context_parallel=True)
        self._cp_mesh = None
        self._cp_axes = (None, None, None)  # (sp, dp, mp)

    def forward(self, x):
        from .. import ops
        b, s, h = x.shape
        qkv = self.qkv(x)
        qkv = ops.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = ops.unbind(qkv, axis=2)  # each [b, s, heads, head_dim]
        if self._cp_mesh is not None:
            sp, dp, mp = self._cp_axes
            out = F.ring_flash_attention(
                q, k, v, mesh=self._cp_mesh, sp_axis=sp, batch_axes=dp,
                head_axis=mp, is_causal=True)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=self.dropout if self.training else 0.0,
                backend=None if self.use_flash else "xla")
        out = ops.reshape(out, [b, s, h])
        return self.proj(out)


class GPTMLP(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.fc1 = Linear(cfg.hidden_size, cfg.intermediate_size,
                          weight_attr=_init_normal(0.02))
        self.fc2 = Linear(
            cfg.intermediate_size, cfg.hidden_size,
            weight_attr=_init_normal(0.02 / math.sqrt(2 * cfg.num_layers)))

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTBlock(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.attn = GPTAttention(cfg)
        self.ln2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        if cfg.num_experts > 0:
            from ..incubate.distributed.models.moe import MoEMLP
            self.mlp = MoEMLP(cfg.hidden_size, cfg.intermediate_size,
                              cfg.num_experts, top_k=cfg.moe_top_k,
                              capacity_factor=cfg.moe_capacity_factor)
        else:
            self.mlp = GPTMLP(cfg)
        self.drop = Dropout(cfg.dropout)
        self._recompute = cfg.recompute
        self._recompute_policy = (cfg.recompute_policy
                                  if cfg.recompute_policy != "full"
                                  else None)

    def _inner(self, x):
        x = x + self.drop(self.attn(self.ln1(x)))
        x = x + self.drop(self.mlp(self.ln2(x)))
        return x

    def forward(self, x):
        if self._recompute and self.training:
            from ..distributed.fleet.recompute import recompute
            return recompute(self._inner, x, policy=self._recompute_policy)
        return self._inner(x)

    def _inner_fused(self, x, pending=None):
        """Glue-fused twin of ``_inner`` (train_glue_fusion, ISSUE 19).
        Pre-norm blocks can't fuse their OWN ln1 with a residual add —
        the add that feeds ln1 belongs to the previous block — so the
        model loop threads the previous block's un-added MLP branch in
        as ``pending``: (x+pending -> ln1) and (x+attn -> ln2) each run
        as ONE fused dispatch, and the block returns its own MLP branch
        un-added for the next block (the final add fuses with ln_f).
        Four glue dispatches per layer (add, ln1, add, ln2) become
        two."""
        if pending is None:
            h1 = self.ln1(x)
        else:
            x, h1 = F.fused_residual_norm(
                x, pending, self.ln1.weight, self.ln1.bias,
                epsilon=self.ln1._epsilon)
        a = self.drop(self.attn(h1))
        x, h2 = F.fused_residual_norm(
            x, a, self.ln2.weight, self.ln2.bias,
            epsilon=self.ln2._epsilon)
        return x, self.drop(self.mlp(h2))

    def forward_fused(self, x, pending=None):
        """(x, pending) -> (x, pending') for the glue-fused train loop;
        composes with block recompute (the pending branch rides as an
        extra checkpointed tensor arg)."""
        if self._recompute and self.training:
            from ..distributed.fleet.recompute import recompute
            if pending is None:
                return recompute(self._inner_fused, x,
                                 policy=self._recompute_policy)
            return recompute(self._inner_fused, x, pending,
                             policy=self._recompute_policy)
        return self._inner_fused(x, pending)


class GPTModel(Layer):
    """Embeddings + transformer stack + final norm -> hidden states."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = Embedding(cfg.vocab_size, cfg.hidden_size,
                             weight_attr=_init_normal(0.02))
        self.wpe = Embedding(cfg.max_seq_len, cfg.hidden_size,
                             weight_attr=_init_normal(0.02))
        self.drop = Dropout(cfg.dropout)
        self.blocks = [GPTBlock(cfg) for _ in range(cfg.num_layers)]
        for i, blk in enumerate(self.blocks):
            self.add_sublayer(f"block_{i}", blk)
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)

    def forward(self, input_ids):
        from .. import ops
        s = input_ids.shape[1]
        pos = ops.arange(0, s, dtype="int32")
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        if self.training and self.blocks and _glue_fusion():
            pending = None
            for blk in self.blocks:
                x, pending = blk.forward_fused(x, pending)
            # the last block's MLP branch fuses into the final norm
            _, h = F.fused_residual_norm(
                x, pending, self.ln_f.weight, self.ln_f.bias,
                epsilon=self.ln_f._epsilon)
            return h
        for blk in self.blocks:
            x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    """LM head on top; ``forward(ids, labels)`` returns mean next-token
    cross-entropy (labels already shifted by the data pipeline, as in the
    reference pretrain loaders)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if cfg.tie_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size,
                                  weight_attr=_init_normal(0.02),
                                  bias_attr=False)

    def logits(self, input_ids) -> Tensor:
        from .. import ops
        h = self.gpt(input_ids)
        if self.lm_head is not None:
            return self.lm_head(h)
        return ops.matmul(h, self.gpt.wte.weight, transpose_y=True)

    def forward(self, input_ids, labels=None):
        logits = self.logits(input_ids)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            ops_reshape(logits, [-1, self.cfg.vocab_size]),
            ops_reshape(labels, [-1]))
        if self.cfg.num_experts > 0 and self.cfg.moe_aux_weight:
            from .. import ops
            for blk in self.gpt.blocks:
                aux = getattr(blk.mlp, "aux_loss", None)
                if aux is not None:
                    loss = loss + self.cfg.moe_aux_weight * aux
        return loss

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def flops_per_token(self, seq_len: int) -> float:
        """Standard 6N + attention estimate (per trained token)."""
        n = self.num_params()
        c = self.cfg
        attn = 12 * c.num_layers * c.hidden_size * seq_len
        return 6.0 * n + attn


def ops_reshape(x, shape):
    from .. import ops
    return ops.reshape(x, shape)


class GPTForCausalLMPipe(Layer):
    """Pipeline-parallel GPT (analog of the reference trainers'
    ``GPTForCausalLMPipe`` built on ``PipelineLayer``, and of SURVEY
    D15-D17). The transformer stack runs as an SPMD GPipe over the
    ``pp_axis`` (see ``fleet/pipeline.py``); embeddings, final norm and
    the tied LM head stay outside the pipelined region on their own
    shardings (dp over batch)."""

    # canonical Megatron TP split of a STACKED [L, ...] GPT block
    # (column-parallel qkv/fc1, row-parallel proj/fc2) — the tp_rules
    # PipelinedBlocks.shard consumes for the pp x mp hybrid
    TP_RULES = {
        "attn.qkv.weight": 2, "attn.qkv.bias": 1,
        "mlp.fc1.weight": 2, "mlp.fc1.bias": 1,
        "attn.proj.weight": 1, "mlp.fc2.weight": 1,
    }

    def __init__(self, cfg: GPTConfig, mesh, pp_axis: str = "pp",
                 dp_axis=None, num_microbatches: int = 1, interleave=1,
                 tp_axis=None, tp_rules=None):
        super().__init__()
        if cfg.dropout:
            raise NotImplementedError(
                "pipelined GPT requires dropout=0 (single-program "
                "pipelining threads parameters, not RNG state)")
        from dataclasses import replace

        from ..distributed.fleet.pipeline import PipelinedBlocks

        self.cfg = cfg
        self.dp_axis = dp_axis
        blk_cfg = replace(cfg, recompute=False)  # pipeline owns remat
        self.wte = Embedding(cfg.vocab_size, cfg.hidden_size,
                             weight_attr=_init_normal(0.02))
        self.wpe = Embedding(cfg.max_seq_len, cfg.hidden_size,
                             weight_attr=_init_normal(0.02))
        self.blocks = PipelinedBlocks(lambda: GPTBlock(blk_cfg),
                                      cfg.num_layers, mesh=mesh,
                                      pp_axis=pp_axis,
                                      num_microbatches=num_microbatches,
                                      interleave=interleave)
        if tp_axis is not None:
            # Megatron TP inside the pipeline (pp x mp hybrid): re-shard
            # the stacked leaves with the tensor-split placements; the
            # pipeline's shard_map leaves tp_axis to GSPMD
            self.blocks.shard(mesh, pp_axis, tp_axis=tp_axis,
                              tp_rules=tp_rules or self.TP_RULES)
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)

    def logits(self, input_ids) -> Tensor:
        from .. import ops
        s = input_ids.shape[1]
        pos = ops.arange(0, s, dtype="int32")
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.blocks(x, batch_axes=self.dp_axis)
        h = self.ln_f(x)
        return ops.matmul(h, self.wte.weight, transpose_y=True)

    def forward(self, input_ids, labels=None):
        logits = self.logits(input_ids)
        if labels is None:
            return logits
        return F.cross_entropy(
            ops_reshape(logits, [-1, self.cfg.vocab_size]),
            ops_reshape(labels, [-1]))

    def train_batch(self, input_ids, labels):
        """Fused 1F1B step (reference ``pipeline_parallel.py:663``):
        the epilogue (final norm + tied LM head + CE) runs INSIDE the
        schedule on the last stage via ``post_params``, so ln_f and the
        tied embedding get their head-path grads; the embedding path's
        grads arrive through ``x``'s cotangent. ``loss.backward()``
        then ``optimizer.step()`` as usual."""
        import jax
        import jax.numpy as jnp

        from .. import ops
        from ..distributed.fleet.pipeline import functional_call

        def loss_fn(y, tgt, post_vals):
            w_ln, b_ln, wte = post_vals
            # run the real ln_f purely on the traced values (no drift
            # from a hand-rolled copy of LayerNorm's math)
            h = functional_call(self.ln_f,
                                {"weight": w_ln, "bias": b_ln}, y)
            logits = jnp.einsum("bsh,vh->bsv", h, wte)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            ll = jnp.take_along_axis(logp, tgt[..., None].astype(
                jnp.int32), axis=-1)
            return -jnp.mean(ll)

        s = input_ids.shape[1]
        pos = ops.arange(0, s, dtype="int32")
        x = self.wte(input_ids) + self.wpe(pos)
        return self.blocks.train_batch(
            x, labels, loss_fn, batch_axes=self.dp_axis,
            post_params=[self.ln_f.weight, self.ln_f.bias,
                         self.wte.weight])

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())


# --- GSPMD sharding recipe (the fleet-TP analog for this model) ------------

def shard_gpt(model: GPTForCausalLM, mesh, dp_axis="dp", mp_axis="mp",
              sp_axis=None, context_parallel=False, ep_axis=None):
    """Pin Megatron-style shardings over ``mesh`` (a ProcessMesh).

    Column-parallel: qkv / fc1 weights shard output dim over mp.
    Row-parallel: proj / fc2 weights shard input dim over mp.
    Vocab-parallel: wte shards vocab dim over mp.
    XLA's SPMD partitioner then inserts the identity/allreduce pairs the
    reference hand-codes in ``mp_ops.py`` (SURVEY D14). dp/sp axes shard the
    *data* (batch/sequence), applied by the caller on inputs; parameters
    stay replicated over dp/sp (pure DP; use fleet sharding stages for ZeRO).

    ``context_parallel=True`` (requires ``sp_axis``) switches every attention
    layer to ring attention over the sp axis — K/V blocks rotate on ICI and
    the [S, S] score matrix never materializes, the long-context mode (the
    reference's sep/segment-parallel axis, ``fleet/base/topology.py:65``).
    """
    from ..distributed.auto_parallel.api import (Replicate, Shard,
                                                 shard_parameter)

    names = mesh.dim_names
    if ep_axis is not None and ep_axis in names:
        from ..incubate.distributed.models.moe import MoEMLP
        for blk in model.gpt.blocks:
            if isinstance(blk.mlp, MoEMLP):
                blk.mlp.shard(mesh, ep_axis)
    if context_parallel:
        if sp_axis not in names:
            raise ValueError("context_parallel requires sp_axis in the mesh")
        for blk in model.gpt.blocks:
            blk.attn._cp_mesh = mesh
            blk.attn._cp_axes = (
                sp_axis,
                dp_axis if dp_axis in names else None,
                mp_axis if mp_axis in names else None)
    if mp_axis not in names:
        return model
    mp_dim = names.index(mp_axis)

    def pl(tensor_dim):
        p = [Replicate()] * mesh.ndim
        p[mp_dim] = Shard(tensor_dim)
        return p

    rep = [Replicate()] * mesh.ndim
    shard_parameter(model.gpt.wte.weight, mesh, pl(0))
    shard_parameter(model.gpt.wpe.weight, mesh, rep)
    for blk in model.gpt.blocks:
        shard_parameter(blk.attn.qkv.weight, mesh, pl(1))
        shard_parameter(blk.attn.qkv.bias, mesh, pl(0))
        shard_parameter(blk.attn.proj.weight, mesh, pl(0))
        shard_parameter(blk.attn.proj.bias, mesh, rep)
        if hasattr(blk.mlp, "fc1"):  # dense FFN (MoE shards over ep above)
            shard_parameter(blk.mlp.fc1.weight, mesh, pl(1))
            shard_parameter(blk.mlp.fc1.bias, mesh, pl(0))
            shard_parameter(blk.mlp.fc2.weight, mesh, pl(0))
            shard_parameter(blk.mlp.fc2.bias, mesh, rep)
    if model.lm_head is not None:
        shard_parameter(model.lm_head.weight, mesh, pl(1))
    return model

"""BERT-family encoder model (BASELINE config 3: BERT-base sharding-2).

Capability analog of the BERT configs the reference trains through fleet
(model defs live downstream in PaddleNLP — ``BertForPretraining`` — but the
mechanics are reference in-tree: mp_layers TP shardings
``python/paddle/distributed/fleet/layers/mpu/mp_layers.py:47,333,540``,
sharding stages ``dygraph_sharding_optimizer.py:49``, flash attention
``python/paddle/nn/functional/flash_attention.py:147``).

Same TPU-native shape as ``gpt.py``: one model class; parallelism applied
afterwards as GSPMD sharding (``shard_bert``) — mesh axes decide dp/tp and
XLA's partitioner emits the Megatron collectives.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.layers import Dropout, Embedding, LayerNorm, Linear


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 512
    type_vocab_size: int = 2
    intermediate_size: int = 0  # 0 -> 4*hidden
    dropout: float = 0.0
    layer_norm_eps: float = 1e-12
    use_flash_attention: bool = True
    recompute: bool = False
    recompute_policy: str = "full"
    # When > 0, the MLM head gathers (at most) this many masked positions
    # per sequence BEFORE the vocab projection, so the [*, vocab] GEMM and
    # loss run over ~15% of positions instead of all of them — the
    # standard BERT-pretrain optimization (the reference data pipeline
    # guarantees <= max_predictions_per_seq masked tokens per sequence;
    # positions beyond the cap are dropped, matching that contract).
    max_predictions: int = 0

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


def _init(std=0.02):
    return I.Normal(mean=0.0, std=std)


def _glue_fusion() -> bool:
    from ..core import state
    return bool(state.get_flag("train_glue_fusion"))


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word = Embedding(cfg.vocab_size, cfg.hidden_size,
                              weight_attr=_init())
        self.position = Embedding(cfg.max_seq_len, cfg.hidden_size,
                                  weight_attr=_init())
        self.token_type = Embedding(cfg.type_vocab_size, cfg.hidden_size,
                                    weight_attr=_init())
        self.ln = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.drop = Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None):
        from .. import ops
        s = input_ids.shape[1]
        pos = ops.arange(0, s, dtype="int32")
        if token_type_ids is None:
            # reference BERT substitutes zeros: the learned segment-0 row
            # is always added, keeping model(ids) == model(ids, zeros)
            token_type_ids = ops.zeros_like(input_ids)
        x = (self.word(input_ids) + self.position(pos)
             + self.token_type(token_type_ids))
        return self.drop(self.ln(x))


class BertAttention(Layer):
    """Bidirectional self-attention, fused qkv (same layout as
    ``GPTAttention`` minus the causal mask)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.head_dim
        self.qkv = Linear(h, 3 * h, weight_attr=_init())
        self.proj = Linear(
            h, h, weight_attr=_init(0.02 / math.sqrt(2 * cfg.num_layers)))
        self.dropout = cfg.dropout
        self.use_flash = cfg.use_flash_attention

    def forward(self, x):
        from .. import ops
        b, s, h = x.shape
        qkv = self.qkv(x)
        qkv = ops.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = ops.unbind(qkv, axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=False,
            dropout_p=self.dropout if self.training else 0.0,
            backend=None if self.use_flash else "xla")
        return self.proj(ops.reshape(out, [b, s, h]))


class BertLayer(Layer):
    """Post-LN encoder block (the original BERT arrangement)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attn = BertAttention(cfg)
        self.ln1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.fc1 = Linear(cfg.hidden_size, cfg.intermediate_size,
                          weight_attr=_init())
        self.fc2 = Linear(
            cfg.intermediate_size, cfg.hidden_size,
            weight_attr=_init(0.02 / math.sqrt(2 * cfg.num_layers)))
        self.ln2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.drop = Dropout(cfg.dropout)
        self._recompute = cfg.recompute
        self._policy = (cfg.recompute_policy
                        if cfg.recompute_policy != "full" else None)

    def _inner(self, x):
        x = self.ln1(x + self.drop(self.attn(x)))
        y = self.fc2(F.gelu(self.fc1(x), approximate=True))
        return self.ln2(x + self.drop(y))

    def _inner_fused(self, x):
        """Glue-fused twin of ``_inner`` (train_glue_fusion, ISSUE 19).
        Post-LN fuses in place — each (add, norm) pair becomes one
        dispatch, no cross-block pending branch to thread."""
        _, x = F.fused_residual_norm(
            x, self.drop(self.attn(x)), self.ln1.weight, self.ln1.bias,
            epsilon=self.ln1._epsilon)
        y = self.fc2(F.gelu(self.fc1(x), approximate=True))
        _, x = F.fused_residual_norm(
            x, self.drop(y), self.ln2.weight, self.ln2.bias,
            epsilon=self.ln2._epsilon)
        return x

    def forward(self, x):
        inner = (self._inner_fused
                 if self.training and _glue_fusion() else self._inner)
        if self._recompute and self.training:
            from ..distributed.fleet.recompute import recompute
            return recompute(inner, x, policy=self._policy)
        return inner(x)


class BertModel(Layer):
    """Embeddings + encoder stack (+ [CLS] pooler)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.layers = [BertLayer(cfg) for _ in range(cfg.num_layers)]
        for i, l in enumerate(self.layers):
            self.add_sublayer(f"layer_{i}", l)
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size,
                             weight_attr=_init())

    def forward(self, input_ids, token_type_ids=None):
        x = self.embeddings(input_ids, token_type_ids)
        for l in self.layers:
            x = l(x)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(Layer):
    """MLM (decoder tied to word embeddings) + NSP heads.
    ``forward(ids, token_type_ids, mlm_labels, nsp_labels)`` returns the
    summed mean loss; mlm positions with label -100 are ignored."""

    IGNORE = -100

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                weight_attr=_init())
        self.transform_ln = LayerNorm(cfg.hidden_size,
                                      epsilon=cfg.layer_norm_eps)
        self.nsp = Linear(cfg.hidden_size, 2, weight_attr=_init())

    def mlm_logits(self, hidden) -> Tensor:
        from .. import ops
        h = self.transform_ln(F.gelu(self.transform(hidden),
                                     approximate=True))
        return ops.matmul(h, self.bert.embeddings.word.weight,
                          transpose_y=True)

    def forward(self, input_ids, token_type_ids=None, mlm_labels=None,
                nsp_labels=None):
        from .. import ops
        hidden, pooled = self.bert(input_ids, token_type_ids)
        if mlm_labels is None:
            return self.mlm_logits(hidden)
        k = self.cfg.max_predictions
        if k and k < hidden.shape[1]:
            # gather the (<= k per sequence) masked positions first:
            # the vocab projection + loss then run over [B, k] instead
            # of [B, S]. top-k on the mask flag returns each row's
            # masked positions (ties keep ascending index order);
            # un-masked filler slots keep label IGNORE. The hidden-state
            # selection is a one-hot MATMUL, not a gather: on TPU the
            # gather's backward is a scatter-add over [B, S, H] (measured
            # +12 ms/step on the b16/s512 bench), while the one-hot
            # contraction's backward is another matmul on the MXU.
            flags = ops.cast(mlm_labels != self.IGNORE, "int32")
            flag_k, pos = ops.topk(flags, k, axis=-1)
            sel_labels = ops.take_along_axis(mlm_labels, pos, axis=-1)
            sel_labels = ops.where(
                flag_k > 0, sel_labels,
                ops.full_like(sel_labels, self.IGNORE))
            onehot = ops.cast(F.one_hot(pos, hidden.shape[1]),
                              hidden.dtype)                  # [B, k, S]
            sel_hidden = ops.matmul(onehot, hidden)          # [B, k, H]
            logits = self.mlm_logits(sel_hidden)
            loss = F.cross_entropy(
                ops.reshape(logits, [-1, self.cfg.vocab_size]),
                ops.reshape(sel_labels, [-1]), ignore_index=self.IGNORE)
        else:
            logits = self.mlm_logits(hidden)
            loss = F.cross_entropy(
                ops.reshape(logits, [-1, self.cfg.vocab_size]),
                ops.reshape(mlm_labels, [-1]), ignore_index=self.IGNORE)
        if nsp_labels is not None:
            loss = loss + F.cross_entropy(self.nsp(pooled), nsp_labels)
        return loss

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())


class BertForSequenceClassification(Layer):
    def __init__(self, cfg: BertConfig, num_classes: int = 2):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.drop = Dropout(cfg.dropout)
        self.classifier = Linear(cfg.hidden_size, num_classes,
                                 weight_attr=_init())

    def forward(self, input_ids, token_type_ids=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids)
        logits = self.classifier(self.drop(pooled))
        if labels is None:
            return logits
        return F.cross_entropy(logits, labels)


def shard_bert(model, mesh, dp_axis="dp", mp_axis="mp"):
    """Megatron TP shardings for the encoder (column-parallel qkv/fc1,
    row-parallel proj/fc2, vocab-parallel word embedding) — the
    ``shard_gpt`` recipe for the encoder family; dp shards the batch at
    the input (pure DP; fleet sharding stages provide ZeRO on top)."""
    from ..distributed.auto_parallel.api import (Replicate, Shard,
                                                 shard_parameter)

    names = mesh.dim_names
    if mp_axis not in names:
        return model
    mp_dim = names.index(mp_axis)

    def pl(tensor_dim):
        p = [Replicate()] * mesh.ndim
        p[mp_dim] = Shard(tensor_dim)
        return p

    bert = model.bert if hasattr(model, "bert") else model
    shard_parameter(bert.embeddings.word.weight, mesh, pl(0))
    for l in bert.layers:
        shard_parameter(l.attn.qkv.weight, mesh, pl(1))
        shard_parameter(l.attn.qkv.bias, mesh, pl(0))
        shard_parameter(l.attn.proj.weight, mesh, pl(0))
        shard_parameter(l.fc1.weight, mesh, pl(1))
        shard_parameter(l.fc1.bias, mesh, pl(0))
        shard_parameter(l.fc2.weight, mesh, pl(0))
    return model

"""Model zoo.

The reference ships vision models in ``python/paddle/vision/models`` and
leaves LLMs to PaddleNLP; this framework's flagship trainables live here so
benchmarks (BASELINE.md configs 3-5) and the driver entry hooks have a
canonical model family to exercise.
"""
from . import gpt  # noqa: F401
from .gpt import GPTConfig, GPTModel, GPTForCausalLM  # noqa: F401
from . import bert  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForPretraining,
    BertForSequenceClassification)
from . import llama  # noqa: F401
from .llama import LlamaConfig, LlamaModel, LlamaForCausalLM  # noqa: F401
from . import generation  # noqa: F401
from .generation import generate  # noqa: F401

"""Oxford Flowers-102 (reference
``python/paddle/vision/datasets/flowers.py:34``): images tarball +
``imagelabels.mat`` + ``setid.mat``. No network egress here, so the three
files must be local (download=False semantics)."""
from __future__ import annotations

import io
import os
import tarfile

import numpy as np

from ...io import Dataset
from . import _require

MODE_KEYS = {"train": "trnid", "valid": "valid", "test": "tstid"}


class Flowers(Dataset):
    """Items are (image HWC uint8, label int64 in [0, 102))."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None):
        if mode not in MODE_KEYS:
            raise ValueError(f"mode must be one of {sorted(MODE_KEYS)}, "
                             f"got {mode!r}")
        self.mode = mode
        self.transform = transform
        data_file = _require(data_file, "flowers images (102flowers.tgz)")
        label_file = _require(label_file, "flowers imagelabels.mat")
        setid_file = _require(setid_file, "flowers setid.mat")

        from scipy.io import loadmat
        self.labels = loadmat(label_file)["labels"][0]  # 1-based, per file
        self.indexes = loadmat(setid_file)[MODE_KEYS[mode]][0]  # 1-based

        # keep the tar handle; images decode lazily per access
        self.data_tar = tarfile.open(data_file)
        self._members = {os.path.basename(m.name): m
                         for m in self.data_tar.getmembers()
                         if m.name.endswith(".jpg")}

    def __getitem__(self, idx):
        from PIL import Image
        index = int(self.indexes[idx])
        fname = f"image_{index:05d}.jpg"
        with self.data_tar.extractfile(self._members[fname]) as f:
            img = np.asarray(Image.open(io.BytesIO(f.read()))
                             .convert("RGB"))
        if self.transform is not None:
            img = self.transform(img)
        label = np.int64(self.labels[index - 1] - 1)
        return img, label

    def __len__(self):
        return len(self.indexes)

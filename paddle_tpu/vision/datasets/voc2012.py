"""VOC2012 segmentation dataset (reference
``python/paddle/vision/datasets/voc2012.py:30``): items are
(image HWC uint8, segmentation mask HW uint8) read from the standard
VOCtrainval tar. No network egress: the tar must be local."""
from __future__ import annotations

import io

import numpy as np

from ...io import Dataset
from . import _require

_VOC_ROOT = "VOCdevkit/VOC2012/"
_SETS = {"train": "train", "valid": "val", "test": "trainval"}


class VOC2012(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if mode not in _SETS:
            raise ValueError(f"mode must be one of {sorted(_SETS)}, "
                             f"got {mode!r}")
        self.mode = mode
        self.transform = transform
        data_file = _require(data_file, "VOC2012 tar (VOCtrainval)")

        import tarfile
        self.data_tar = tarfile.open(data_file)
        self._members = {m.name: m for m in self.data_tar.getmembers()}
        setfile = (_VOC_ROOT + "ImageSets/Segmentation/"
                   + _SETS[mode] + ".txt")
        with self.data_tar.extractfile(self._members[setfile]) as f:
            self.names = [ln.strip() for ln in
                          f.read().decode().splitlines() if ln.strip()]

    def _read(self, path):
        from PIL import Image
        with self.data_tar.extractfile(self._members[path]) as f:
            return Image.open(io.BytesIO(f.read()))

    def __getitem__(self, idx):
        name = self.names[idx]
        img = np.asarray(self._read(
            _VOC_ROOT + f"JPEGImages/{name}.jpg").convert("RGB"))
        mask = np.asarray(self._read(
            _VOC_ROOT + f"SegmentationClass/{name}.png"))
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self.names)

"""``paddle.vision.datasets`` parity (reference
``python/paddle/vision/datasets/mnist.py:29``, ``cifar.py:33``).

No network egress in this environment, so datasets read standard local
files (MNIST idx / CIFAR pickle formats) from ``image_path``/``data_file``
and raise a clear error when absent instead of downloading.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset


def _require(path, what):
    if path is None or not os.path.exists(path):
        raise RuntimeError(
            f"{what} not found at {path!r}. This environment has no "
            f"network access: place the standard dataset files locally and "
            f"pass their path (download=False semantics).")
    return path


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


class MNIST(Dataset):
    """reference ``mnist.py:29``: items are (image HW1 float32-able, label).
    ``image_path``/``label_path`` point at the idx(.gz) files."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend="cv2"):
        self.mode = mode.lower()
        self.transform = transform
        image_path = _require(image_path, f"{self.NAME} images")
        label_path = _require(label_path, f"{self.NAME} labels")
        self.images = _read_idx(image_path)        # [N, 28, 28] uint8
        self.labels = _read_idx(label_path).astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx][:, :, None]          # HWC
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype("float32")
        return img, np.asarray([self.labels[idx]], dtype="int64")

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """reference ``cifar.py:33``: reads the python-pickle tar.gz batches."""

    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        self.mode = mode.lower()
        self.transform = transform
        data_file = _require(data_file, "cifar archive")
        members = (self._train_members if self.mode == "train"
                   else self._test_members)
        images, labels = [], []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if base in members:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    images.append(np.asarray(d[b"data"], dtype=np.uint8))
                    labels.extend(d.get(b"labels", d.get(b"fine_labels")))
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.images = self.images.transpose(0, 2, 3, 1)  # HWC
        self.labels = np.asarray(labels, dtype="int64")

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype("float32")
        return img, np.asarray([self.labels[idx]], dtype="int64")

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    _train_members = ["train"]
    _test_members = ["test"]


from .folder import DatasetFolder, ImageFolder  # noqa: E402,F401
from .flowers import Flowers  # noqa: E402,F401
from .voc2012 import VOC2012  # noqa: E402,F401

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]

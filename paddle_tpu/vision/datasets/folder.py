"""``DatasetFolder`` / ``ImageFolder`` (reference
``python/paddle/vision/datasets/folder.py:41,274``): directory-tree image
datasets — one class per subdirectory (DatasetFolder) or a flat unlabeled
listing (ImageFolder). Decoding via PIL (no cv2 in this environment)."""
from __future__ import annotations

import os

import numpy as np

from ...io import Dataset

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                  ".tif", ".tiff", ".webp")


def default_loader(path):
    from PIL import Image
    with Image.open(path) as img:
        return np.asarray(img.convert("RGB"))


def has_valid_extension(filename, extensions=IMG_EXTENSIONS):
    return filename.lower().endswith(tuple(extensions))


def make_dataset(directory, class_to_idx, extensions=None,
                 is_valid_file=None):
    """(path, class_index) samples for every valid file, reference
    ``folder.py`` make_dataset semantics."""
    if (extensions is None) == (is_valid_file is None):
        raise ValueError("exactly one of extensions / is_valid_file "
                         "must be given")
    if is_valid_file is None:
        def is_valid_file(p):
            return has_valid_extension(p, extensions)
    samples = []
    for cls in sorted(class_to_idx):
        d = os.path.join(directory, cls)
        if not os.path.isdir(d):
            continue
        for root, _, names in sorted(os.walk(d, followlinks=True)):
            for name in sorted(names):
                path = os.path.join(root, name)
                if is_valid_file(path):
                    samples.append((path, class_to_idx[cls]))
    return samples


class DatasetFolder(Dataset):
    """Reference ``folder.py:41``: root/<class_x>/xxx.png layout; items
    are (image, class_index); ``classes``/``class_to_idx`` exposed."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
        if not classes:
            raise RuntimeError(f"no class folders found in {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = make_dataset(root, self.class_to_idx, extensions,
                                    is_valid_file)
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root!r}")
        self.targets = [t for _, t in self.samples]

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Reference ``folder.py:274``: flat recursive listing, items are
    [image] (no labels)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if is_valid_file is None:
            def is_valid_file(p):
                return has_valid_extension(p, extensions)
        samples = []
        for r, _, names in sorted(os.walk(root, followlinks=True)):
            for name in sorted(names):
                p = os.path.join(r, name)
                if is_valid_file(p):
                    samples.append(p)
        if not samples:
            raise RuntimeError(f"no valid files found under {root!r}")
        self.samples = samples

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)

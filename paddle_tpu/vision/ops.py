"""``paddle.vision.ops`` — detection ops.

Analog of ``python/paddle/vision/ops.py`` (nms :1586, roi_align :1081,
roi_pool, box_coder; CUDA kernels ``paddle/phi/kernels/gpu/nms_kernel.cu``,
``roi_align_kernel.cu``). TPU split: roi_align/roi_pool/box_coder are
dense gather/interpolate math (jit-fusible, differentiable); nms is a
host-side op (data-dependent output size — the reference's GPU kernel
also serializes on a bitmask reduction), run where detection
postprocessing lives.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive, unwrap
from ..core.tensor import Tensor


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Reference ``vision/ops.py nms``: returns kept box indices. With
    ``scores`` sorts descending first; with categories runs per-class."""
    b = np.asarray(unwrap(boxes))
    n = len(b)
    if scores is not None:
        order = np.argsort(-np.asarray(unwrap(scores)))
    else:
        order = np.arange(n)

    def iou(a, rest):
        x1 = np.maximum(a[0], rest[:, 0])
        y1 = np.maximum(a[1], rest[:, 1])
        x2 = np.minimum(a[2], rest[:, 2])
        y2 = np.minimum(a[3], rest[:, 3])
        inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
        area_a = (a[2] - a[0]) * (a[3] - a[1])
        area_r = (rest[:, 2] - rest[:, 0]) * (rest[:, 3] - rest[:, 1])
        return inter / np.maximum(area_a + area_r - inter, 1e-10)

    if category_idxs is not None:
        cats = np.asarray(unwrap(category_idxs))
        keep_all = []
        for c in (categories if categories is not None
                  else np.unique(cats)):
            sel = order[cats[order] == c]
            keep_all.extend(_nms_greedy(b, sel, iou, iou_threshold))
        keep = np.asarray(sorted(
            keep_all,
            key=lambda i: -np.asarray(unwrap(scores))[i]
            if scores is not None else i), np.int64)
    else:
        keep = np.asarray(_nms_greedy(b, order, iou, iou_threshold),
                          np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def _nms_greedy(boxes, order, iou, thr):
    keep = []
    order = list(order)
    while order:
        i = order.pop(0)
        keep.append(i)
        if not order:
            break
        rest = np.asarray(order)
        ious = iou(boxes[i], boxes[rest])
        order = [j for j, v in zip(order, ious) if v <= thr]
    return keep


@primitive("roi_align")
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """x [N,C,H,W]; boxes [R,4] (x1,y1,x2,y2); boxes_num [N] rois per
    image. Bilinear average pooling per output bin (reference
    ``roi_align_kernel``)."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    n, c, h, w = x.shape
    r = boxes.shape[0]
    # map each roi to its image
    counts = boxes_num.astype(jnp.int32)
    img_idx = jnp.repeat(jnp.arange(n), counts, total_repeat_length=r)

    off = 0.5 if aligned else 0.0
    bx = boxes * spatial_scale
    x1, y1, x2, y2 = bx[:, 0] - off, bx[:, 1] - off, bx[:, 2] - off, \
        bx[:, 3] - off
    rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
    bin_w = rw / ow
    bin_h = rh / oh
    s = sampling_ratio if sampling_ratio > 0 else 2
    # sample points per bin: [oh*s] x [ow*s] grid per roi
    gy = (jnp.arange(oh * s) + 0.5) / s  # in bin units
    gx = (jnp.arange(ow * s) + 0.5) / s
    ys = y1[:, None] + gy[None, :] * bin_h[:, None]  # [R, oh*s]
    xs = x1[:, None] + gx[None, :] * bin_w[:, None]  # [R, ow*s]

    def bilinear(img, yy, xx):
        # img [C,H,W]; yy [P], xx [Q] -> [C,P,Q]
        y0 = jnp.clip(jnp.floor(yy), 0, h - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(xx), 0, w - 1).astype(jnp.int32)
        y1_ = jnp.clip(y0 + 1, 0, h - 1)
        x1_ = jnp.clip(x0 + 1, 0, w - 1)
        wy = jnp.clip(yy - y0, 0, 1)[None, :, None]
        wx = jnp.clip(xx - x0, 0, 1)[None, None, :]
        g = lambda yi, xi: img[:, yi][:, :, xi]
        return (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1_, x0) * wy * (1 - wx)
                + g(y0, x1_) * (1 - wy) * wx + g(y1_, x1_) * wy * wx)

    def per_roi(i):
        img = x[img_idx[i]]
        samp = bilinear(img, ys[i], xs[i])          # [C, oh*s, ow*s]
        samp = samp.reshape(c, oh, s, ow, s)
        return samp.mean(axis=(2, 4))               # [C, oh, ow]

    return jax.vmap(per_roi)(jnp.arange(r))


@primitive("roi_pool")
def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Max pooling per bin (reference roi_pool) via dense sampling max."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    n, c, h, w = x.shape
    r = boxes.shape[0]
    counts = boxes_num.astype(jnp.int32)
    img_idx = jnp.repeat(jnp.arange(n), counts, total_repeat_length=r)
    bx = jnp.round(boxes * spatial_scale).astype(jnp.int32)
    s = 4  # samples per bin side

    def per_roi(i):
        x1, y1, x2, y2 = bx[i, 0], bx[i, 1], bx[i, 2], bx[i, 3]
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        gy = y1 + (jnp.arange(oh * s) + 0.5) / (oh * s) * rh
        gx = x1 + (jnp.arange(ow * s) + 0.5) / (ow * s) * rw
        yi = jnp.clip(gy.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(gx.astype(jnp.int32), 0, w - 1)
        img = x[img_idx[i]]
        samp = img[:, yi][:, :, xi].reshape(c, oh, s, ow, s)
        return samp.max(axis=(2, 4))

    return jax.vmap(per_roi)(jnp.arange(r))


@primitive("box_coder")
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0):
    """Reference ``vision/ops box_coder`` (SSD-style box transforms)."""
    pb = prior_box
    pw = pb[:, 2] - pb[:, 0] + (0.0 if box_normalized else 1.0)
    ph = pb[:, 3] - pb[:, 1] + (0.0 if box_normalized else 1.0)
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    var = prior_box_var if prior_box_var is not None else \
        jnp.ones_like(pb)
    if code_type == "encode_center_size":
        tb = target_box
        tw = tb[:, 2] - tb[:, 0] + (0.0 if box_normalized else 1.0)
        th = tb[:, 3] - tb[:, 1] + (0.0 if box_normalized else 1.0)
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        out = jnp.stack([
            (tcx[:, None] - pcx[None]) / pw[None] / var[None, :, 0],
            (tcy[:, None] - pcy[None]) / ph[None] / var[None, :, 1],
            jnp.log(tw[:, None] / pw[None]) / var[None, :, 2],
            jnp.log(th[:, None] / ph[None]) / var[None, :, 3],
        ], axis=-1)
        return out
    # decode_center_size: target [R, P, 4] deltas -> boxes
    tb = target_box
    dcx = tb[..., 0] * var[None, :, 0] * pw[None] + pcx[None]
    dcy = tb[..., 1] * var[None, :, 1] * ph[None] + pcy[None]
    dw = jnp.exp(tb[..., 2] * var[None, :, 2]) * pw[None]
    dh = jnp.exp(tb[..., 3] * var[None, :, 3]) * ph[None]
    return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                      dcx + dw * 0.5 - (0.0 if box_normalized else 1.0),
                      dcy + dh * 0.5 - (0.0 if box_normalized else 1.0)],
                     axis=-1)


__all__ = ["nms", "roi_align", "roi_pool", "box_coder"]

"""``paddle.vision.ops`` — detection ops.

Analog of ``python/paddle/vision/ops.py`` (nms :1586, roi_align :1081,
roi_pool, box_coder; CUDA kernels ``paddle/phi/kernels/gpu/nms_kernel.cu``,
``roi_align_kernel.cu``). TPU split: roi_align/roi_pool/box_coder are
dense gather/interpolate math (jit-fusible, differentiable); nms is a
host-side op (data-dependent output size — the reference's GPU kernel
also serializes on a bitmask reduction), run where detection
postprocessing lives.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive, unwrap
from ..core.tensor import Tensor


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Reference ``vision/ops.py nms``: returns kept box indices. With
    ``scores`` sorts descending first; with categories runs per-class."""
    b = np.asarray(unwrap(boxes))
    n = len(b)
    if scores is not None:
        order = np.argsort(-np.asarray(unwrap(scores)))
    else:
        order = np.arange(n)

    def iou(a, rest):
        x1 = np.maximum(a[0], rest[:, 0])
        y1 = np.maximum(a[1], rest[:, 1])
        x2 = np.minimum(a[2], rest[:, 2])
        y2 = np.minimum(a[3], rest[:, 3])
        inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
        area_a = (a[2] - a[0]) * (a[3] - a[1])
        area_r = (rest[:, 2] - rest[:, 0]) * (rest[:, 3] - rest[:, 1])
        return inter / np.maximum(area_a + area_r - inter, 1e-10)

    if category_idxs is not None:
        cats = np.asarray(unwrap(category_idxs))
        keep_all = []
        for c in (categories if categories is not None
                  else np.unique(cats)):
            sel = order[cats[order] == c]
            keep_all.extend(_nms_greedy(b, sel, iou, iou_threshold))
        keep = np.asarray(sorted(
            keep_all,
            key=lambda i: -np.asarray(unwrap(scores))[i]
            if scores is not None else i), np.int64)
    else:
        keep = np.asarray(_nms_greedy(b, order, iou, iou_threshold),
                          np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def _nms_greedy(boxes, order, iou, thr):
    keep = []
    order = list(order)
    while order:
        i = order.pop(0)
        keep.append(i)
        if not order:
            break
        rest = np.asarray(order)
        ious = iou(boxes[i], boxes[rest])
        order = [j for j, v in zip(order, ious) if v <= thr]
    return keep


@primitive("roi_align")
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """x [N,C,H,W]; boxes [R,4] (x1,y1,x2,y2); boxes_num [N] rois per
    image. Bilinear average pooling per output bin (reference
    ``roi_align_kernel``)."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    n, c, h, w = x.shape
    r = boxes.shape[0]
    # map each roi to its image
    counts = boxes_num.astype(jnp.int32)
    img_idx = jnp.repeat(jnp.arange(n), counts, total_repeat_length=r)

    off = 0.5 if aligned else 0.0
    bx = boxes * spatial_scale
    x1, y1, x2, y2 = bx[:, 0] - off, bx[:, 1] - off, bx[:, 2] - off, \
        bx[:, 3] - off
    rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
    bin_w = rw / ow
    bin_h = rh / oh
    s = sampling_ratio if sampling_ratio > 0 else 2
    # sample points per bin: [oh*s] x [ow*s] grid per roi
    gy = (jnp.arange(oh * s) + 0.5) / s  # in bin units
    gx = (jnp.arange(ow * s) + 0.5) / s
    ys = y1[:, None] + gy[None, :] * bin_h[:, None]  # [R, oh*s]
    xs = x1[:, None] + gx[None, :] * bin_w[:, None]  # [R, ow*s]

    def bilinear(img, yy, xx):
        # img [C,H,W]; yy [P], xx [Q] -> [C,P,Q]
        y0 = jnp.clip(jnp.floor(yy), 0, h - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(xx), 0, w - 1).astype(jnp.int32)
        y1_ = jnp.clip(y0 + 1, 0, h - 1)
        x1_ = jnp.clip(x0 + 1, 0, w - 1)
        wy = jnp.clip(yy - y0, 0, 1)[None, :, None]
        wx = jnp.clip(xx - x0, 0, 1)[None, None, :]
        g = lambda yi, xi: img[:, yi][:, :, xi]
        return (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1_, x0) * wy * (1 - wx)
                + g(y0, x1_) * (1 - wy) * wx + g(y1_, x1_) * wy * wx)

    def per_roi(i):
        img = x[img_idx[i]]
        samp = bilinear(img, ys[i], xs[i])          # [C, oh*s, ow*s]
        samp = samp.reshape(c, oh, s, ow, s)
        return samp.mean(axis=(2, 4))               # [C, oh, ow]

    return jax.vmap(per_roi)(jnp.arange(r))


@primitive("roi_pool")
def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Max pooling per bin (reference roi_pool) via dense sampling max."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    n, c, h, w = x.shape
    r = boxes.shape[0]
    counts = boxes_num.astype(jnp.int32)
    img_idx = jnp.repeat(jnp.arange(n), counts, total_repeat_length=r)
    bx = jnp.round(boxes * spatial_scale).astype(jnp.int32)
    s = 4  # samples per bin side

    def per_roi(i):
        x1, y1, x2, y2 = bx[i, 0], bx[i, 1], bx[i, 2], bx[i, 3]
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        gy = y1 + (jnp.arange(oh * s) + 0.5) / (oh * s) * rh
        gx = x1 + (jnp.arange(ow * s) + 0.5) / (ow * s) * rw
        yi = jnp.clip(gy.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(gx.astype(jnp.int32), 0, w - 1)
        img = x[img_idx[i]]
        samp = img[:, yi][:, :, xi].reshape(c, oh, s, ow, s)
        return samp.max(axis=(2, 4))

    return jax.vmap(per_roi)(jnp.arange(r))


@primitive("box_coder")
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0):
    """Reference ``vision/ops box_coder`` (SSD-style box transforms)."""
    pb = prior_box
    pw = pb[:, 2] - pb[:, 0] + (0.0 if box_normalized else 1.0)
    ph = pb[:, 3] - pb[:, 1] + (0.0 if box_normalized else 1.0)
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    var = prior_box_var if prior_box_var is not None else \
        jnp.ones_like(pb)
    if code_type == "encode_center_size":
        tb = target_box
        tw = tb[:, 2] - tb[:, 0] + (0.0 if box_normalized else 1.0)
        th = tb[:, 3] - tb[:, 1] + (0.0 if box_normalized else 1.0)
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        out = jnp.stack([
            (tcx[:, None] - pcx[None]) / pw[None] / var[None, :, 0],
            (tcy[:, None] - pcy[None]) / ph[None] / var[None, :, 1],
            jnp.log(tw[:, None] / pw[None]) / var[None, :, 2],
            jnp.log(th[:, None] / ph[None]) / var[None, :, 3],
        ], axis=-1)
        return out
    # decode_center_size: target [R, P, 4] deltas -> boxes
    tb = target_box
    dcx = tb[..., 0] * var[None, :, 0] * pw[None] + pcx[None]
    dcy = tb[..., 1] * var[None, :, 1] * ph[None] + pcy[None]
    dw = jnp.exp(tb[..., 2] * var[None, :, 2]) * pw[None]
    dh = jnp.exp(tb[..., 3] * var[None, :, 3]) * ph[None]
    return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                      dcx + dw * 0.5 - (0.0 if box_normalized else 1.0),
                      dcy + dh * 0.5 - (0.0 if box_normalized else 1.0)],
                     axis=-1)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Reference ``yolo_box``: decode YOLOv3 head output [N, C, H, W]
    into (boxes [N, A*H*W, 4], scores [N, A*H*W, class_num]) —
    anchor-major flattening, matching the reference kernel's
    ``box_idx = i*box_num + j*stride + k*w + l``."""
    import jax.numpy as jnp

    from ..core.dispatch import apply, unwrap
    import numpy as np

    anchors = np.asarray(unwrap(anchors)).reshape(-1, 2)
    A = len(anchors)

    def impl(xv, img):
        n, c, h, w = xv.shape
        if iou_aware:
            # layout [N, A*(6+cls), H, W]: first A channels predict IoU
            ioup = jax.nn.sigmoid(xv[:, :A].reshape(n, A, h, w))
            xv = xv[:, A:]
        pred = xv.reshape(n, A, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        sig = jax.nn.sigmoid
        bx = (sig(pred[:, :, 0]) * scale_x_y
              - 0.5 * (scale_x_y - 1.0) + gx) / w
        by = (sig(pred[:, :, 1]) * scale_x_y
              - 0.5 * (scale_x_y - 1.0) + gy) / h
        aw = jnp.asarray(anchors[:, 0], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anchors[:, 1], jnp.float32)[None, :, None, None]
        bw = jnp.exp(pred[:, :, 2]) * aw / (w * downsample_ratio)
        bh = jnp.exp(pred[:, :, 3]) * ah / (h * downsample_ratio)
        conf = sig(pred[:, :, 4])
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor) * \
                ioup ** iou_aware_factor
        probs = sig(pred[:, :, 5:]) * conf[:, :, None]
        # below-threshold predictions are zeroed (reference semantics)
        keep = (conf >= conf_thresh).astype(jnp.float32)
        imh = img[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = img[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
        scores = probs * keep[:, :, None]
        boxes = boxes.reshape(n, -1, 4)           # [n, A, h, w, 4]
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(
            n, -1, class_num)
        return boxes, scores

    import jax
    return apply("yolo_box", impl, x, img_size)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """Reference ``prior_box``: SSD anchor generation over the feature
    map grid. Host-side (static given shapes; anchors are data-prep)."""
    import numpy as np

    from ..core.dispatch import unwrap
    from ..core.tensor import Tensor

    fh, fw = unwrap(input).shape[2:4]
    ih, iw = unwrap(image).shape[2:4]
    sh = steps[1] or ih / fh
    sw = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes, vars_ = [], []
    for y in range(fh):
        for x in range(fw):
            cx = (x + offset) * sw
            cy = (y + offset) * sh
            cell = []
            for i, ms in enumerate(min_sizes):
                ms = float(ms)
                for ar in ars:
                    w = ms * np.sqrt(ar) / 2
                    h = ms / np.sqrt(ar) / 2
                    cell.append([(cx - w) / iw, (cy - h) / ih,
                                 (cx + w) / iw, (cy + h) / ih])
                if max_sizes:
                    bs = np.sqrt(ms * float(max_sizes[i])) / 2
                    cell.append([(cx - bs) / iw, (cy - bs) / ih,
                                 (cx + bs) / iw, (cy + bs) / ih])
            boxes.extend(cell)
            vars_.extend([list(variance)] * len(cell))
    out = np.asarray(boxes, np.float32).reshape(fh, fw, -1, 4)
    var = np.asarray(vars_, np.float32).reshape(fh, fw, -1, 4)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    return Tensor(out), Tensor(var)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Reference ``matrix_nms`` (SOLOv2): parallel soft-NMS — every box's
    score decays by its worst overlap with a higher-scored same-class box.
    Host numpy (data-dependent output size, like ``nms``)."""
    import numpy as np

    from ..core.dispatch import unwrap
    from ..core.tensor import Tensor

    b = np.asarray(unwrap(bboxes))      # [N, M, 4]
    s = np.asarray(unwrap(scores))      # [N, C, M]
    outs, idxs, nums = [], [], []
    for n in range(b.shape[0]):
        dets = []
        det_idx = []
        for c in range(s.shape[1]):
            if c == background_label:
                continue
            sc = s[n, c]
            keep = np.flatnonzero(sc > score_threshold)
            if keep.size == 0:
                continue
            keep = keep[np.argsort(-sc[keep])][:nms_top_k]
            bb, sS = b[n, keep], sc[keep]
            x1, y1, x2, y2 = bb.T
            off = 0.0 if normalized else 1.0
            area = (x2 - x1 + off) * (y2 - y1 + off)
            ix1 = np.maximum(x1[:, None], x1[None])
            iy1 = np.maximum(y1[:, None], y1[None])
            ix2 = np.minimum(x2[:, None], x2[None])
            iy2 = np.minimum(y2[:, None], y2[None])
            inter = (np.clip(ix2 - ix1 + off, 0, None)
                     * np.clip(iy2 - iy1 + off, 0, None))
            iou = inter / (area[:, None] + area[None] - inter)
            iou = np.triu(iou, 1)                 # [i, j]: i suppresses j
            # compensation: how much suppressor i was itself suppressed
            iou_cmax = iou.max(axis=0)[:, None]   # per ROW i
            if use_gaussian:
                # reference matrix_nms_kernel.cc:70 multiplies by sigma:
                # exp((max_iou^2 - iou^2) * sigma)
                decay = np.exp((iou_cmax ** 2 - iou ** 2)
                               * gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - iou)
                         / (1 - iou_cmax + 1e-10)).min(axis=0)
            dec = sS * decay
            ok = dec >= post_threshold
            for j in np.flatnonzero(ok):
                dets.append([c, dec[j], *bb[j]])
                det_idx.append(keep[j])
        if dets:
            dets = np.asarray(dets, np.float32)
            order = np.argsort(-dets[:, 1])[:keep_top_k]
            dets = dets[order]
            det_idx = np.asarray(det_idx)[order]
        else:
            dets = np.zeros((0, 6), np.float32)
            det_idx = np.zeros((0,), np.int64)
        outs.append(dets)
        idxs.append(det_idx + n * b.shape[1])
        nums.append(len(dets))
    out = Tensor(np.concatenate(outs) if outs
                 else np.zeros((0, 6), np.float32))
    res = [out]
    if return_index:
        res.append(Tensor(np.concatenate(idxs).astype(np.int64)))
    if return_rois_num:
        res.append(Tensor(np.asarray(nums, np.int32)))
    return tuple(res) if len(res) > 1 else out


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Reference ``psroi_pool``: position-sensitive RoI average pooling —
    output channel (c, i, j) pools input channel c*k*k + i*k + j over
    bin (i, j) of the RoI. Vectorized over boxes (vmap) with masked bin
    averages; trace size is constant in the number of boxes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..core.dispatch import apply, unwrap

    k = output_size if isinstance(output_size, int) else output_size[0]
    nboxes = np.asarray(unwrap(boxes_num))
    batch_of_box = jnp.asarray(
        np.repeat(np.arange(len(nboxes)), nboxes).astype(np.int32))

    def impl(xv, bx):
        n, c, h, w = xv.shape
        oc = c // (k * k)
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        gi = jnp.arange(k, dtype=jnp.float32)

        def one_box(box, img_idx):
            img = xv[img_idx]                       # [c, h, w]
            x1, y1, x2, y2 = (box[0] * spatial_scale,
                              box[1] * spatial_scale,
                              box[2] * spatial_scale,
                              box[3] * spatial_scale)
            bh = jnp.maximum(y2 - y1, 0.1) / k
            bw = jnp.maximum(x2 - x1, 0.1) / k
            # [k, h] / [k, w] bin-membership masks
            my = ((ys[None] >= jnp.floor(y1 + gi[:, None] * bh))
                  & (ys[None] < jnp.ceil(y1 + (gi[:, None] + 1) * bh)))
            mx = ((xs[None] >= jnp.floor(x1 + gi[:, None] * bw))
                  & (xs[None] < jnp.ceil(x1 + (gi[:, None] + 1) * bw)))
            m = (my[:, None, :, None] & mx[None, :, None, :]) \
                .astype(xv.dtype)                   # [k, k, h, w]
            cnt = jnp.maximum(m.sum((-2, -1)), 1.0)  # [k, k]
            chans = img.reshape(oc, k, k, h, w)      # channel (c, i, j)
            return jnp.einsum("oijhw,ijhw->oij", chans, m) / cnt

        return jax.vmap(one_box)(bx, batch_of_box)

    return apply("psroi_pool", impl, x, boxes)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Reference ``deformable_conv`` (v1/v2): sample the input at
    offset-shifted taps (bilinear), then convolve. Implemented as
    gather + einsum — the MXU-friendly formulation (im2col with learned
    coordinates)."""
    import jax.numpy as jnp

    from ..core.dispatch import apply

    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int)         else tuple(dilation)
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("deform_conv2d: groups == 1 only")

    def impl(xv, off, w, *rest):
        n, c, h, wd = xv.shape
        co, ci, kh, kw = w.shape
        oh = (h + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        ow = (wd + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        base_y = (jnp.arange(oh) * st[0] - pd[0])[:, None, None]
        base_x = (jnp.arange(ow) * st[1] - pd[1])[None, :, None]
        ky = (jnp.arange(kh) * dl[0])[None, None, :, None]
        kx = (jnp.arange(kw) * dl[1])[None, None, None, :]
        off = off.reshape(n, kh, kw, 2, oh, ow)
        oy = off[:, :, :, 0].transpose(0, 3, 4, 1, 2)  # [n,oh,ow,kh,kw]
        ox = off[:, :, :, 1].transpose(0, 3, 4, 1, 2)
        py = base_y[None, :, :, :, None] + ky[None] + oy
        px = base_x[None, :, :, None, :] + kx[None] + ox

        y0 = jnp.floor(py); x0 = jnp.floor(px)
        wy = py - y0; wx = px - x0

        def sample(yy, xx):
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, wd - 1).astype(jnp.int32)
            valid = ((yy >= 0) & (yy <= h - 1)
                     & (xx >= 0) & (xx <= wd - 1))
            flat = xv.reshape(n, c, -1)
            idx = (yi * wd + xi).reshape(n, 1, -1)
            g = jnp.take_along_axis(
                flat, jnp.broadcast_to(idx, (n, c, idx.shape[-1])),
                axis=2)
            g = g.reshape((n, c) + yy.shape[1:])
            return g * valid[:, None].astype(xv.dtype)

        v = (sample(y0, x0) * ((1 - wy) * (1 - wx))[:, None]
             + sample(y0, x0 + 1) * ((1 - wy) * wx)[:, None]
             + sample(y0 + 1, x0) * (wy * (1 - wx))[:, None]
             + sample(y0 + 1, x0 + 1) * (wy * wx)[:, None])
        if mask is not None:
            mk = rest[-1].reshape(n, kh, kw, oh, ow)                 .transpose(0, 3, 4, 1, 2)
            v = v * mk[:, None]
        out = jnp.einsum("nchwij,ocij->nohw", v, w)
        if bias is not None:
            out = out + rest[0].reshape(1, -1, 1, 1)
        return out

    args = [x, offset, weight]
    if bias is not None:
        args.append(bias)
    if mask is not None:
        args.append(mask)
    return apply("deform_conv2d", impl, *args)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Reference ``distribute_fpn_proposals``: route each RoI to an FPN
    level by its scale. Host numpy (data-dependent splits)."""
    import numpy as np

    from ..core.dispatch import unwrap
    from ..core.tensor import Tensor

    rois = np.asarray(unwrap(fpn_rois))
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.clip((rois[:, 2] - rois[:, 0] + off)
                            * (rois[:, 3] - rois[:, 1] + off), 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, index = [], []
    for l in range(min_level, max_level + 1):
        sel = np.flatnonzero(lvl == l)
        outs.append(Tensor(rois[sel]))
        index.append(sel)
    restore = np.argsort(np.concatenate(index)) if index else np.array([])
    res_num = [Tensor(np.asarray([len(i)], np.int32)) for i in index]
    out = (outs, Tensor(restore.astype(np.int64)))
    if rois_num is not None:
        return outs, Tensor(restore.astype(np.int64)), res_num
    return out


__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "yolo_box",
           "prior_box", "matrix_nms", "psroi_pool", "deform_conv2d",
           "distribute_fpn_proposals"]


def read_file(filename, name=None):
    """Reference ``read_file`` op (``python/paddle/vision/ops.py``): read
    raw bytes into a 1-D uint8 tensor."""
    import numpy as np

    from ..core.tensor import Tensor

    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return Tensor(data)


def decode_jpeg(x, mode="unchanged", name=None):
    """Reference ``decode_jpeg`` op (nvjpeg-backed CUDA kernel,
    ``paddle/phi/kernels/gpu/decode_jpeg_kernel.cu``): decode an encoded
    JPEG byte tensor to CHW uint8. Host-side PIL decode here — image IO is
    input-pipeline work that belongs on CPU feeding the TPU."""
    import io

    import numpy as np

    from ..core.dispatch import unwrap
    from ..core.tensor import Tensor

    raw = np.asarray(unwrap(x)).astype(np.uint8).tobytes()
    from PIL import Image
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    elif mode != "unchanged":
        raise ValueError(f"decode_jpeg: unsupported mode {mode!r}")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]            # [1, H, W]
    else:
        arr = arr.transpose(2, 0, 1)  # [C, H, W]
    return Tensor(arr)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """Reference ``yolo_loss`` (YOLOv3 head loss,
    ``python/paddle/vision/ops.py:58``; CPU kernel
    ``paddle/phi/kernels/cpu/yolo_loss_kernel.cc``): per-image sum of
    coordinate (x,y: BCE; w,h: L1), objectness (BCE, with ignore region
    above ``ignore_thresh`` IoU) and classification (BCE) losses.

    x: [N, A*(5+C), H, W] raw head output for the anchors in
    ``anchor_mask``; gt_box [N, B, 4] (cx, cy, w, h, image-normalized);
    gt_label [N, B] int; returns [N] loss.
    """
    from ..core.dispatch import apply

    anchors_np = np.asarray(unwrap(anchors), np.float32).reshape(-1, 2)
    mask = [int(m) for m in (anchor_mask if not hasattr(
        anchor_mask, "numpy") else unwrap(anchor_mask))]
    a_used = anchors_np[mask]                   # [A, 2] in input pixels
    na = len(mask)

    args = [x, gt_box, gt_label] + ([gt_score] if gt_score is not None
                                    else [])

    def impl(xv, gb, gl, *gs):
        gs = gs[0] if gs else None
        n, ch, h, w = xv.shape
        assert ch == na * (5 + class_num), (
            f"yolo_loss: channel {ch} != A*(5+C)={na * (5 + class_num)}")
        in_w = w * downsample_ratio
        in_h = h * downsample_ratio
        pred = xv.reshape(n, na, 5 + class_num, h, w)
        sig = jax.nn.sigmoid

        # decoded box centers/sizes in image-normalized units
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        px = (sig(pred[:, :, 0]) * scale_x_y
              - 0.5 * (scale_x_y - 1.0) + gx) / w
        py = (sig(pred[:, :, 1]) * scale_x_y
              - 0.5 * (scale_x_y - 1.0) + gy) / h
        pw = jnp.exp(pred[:, :, 2]) * a_used[None, :, 0, None, None] / in_w
        ph = jnp.exp(pred[:, :, 3]) * a_used[None, :, 1, None, None] / in_h

        def iou_cwh(boxes_a, boxes_b):
            # [..., (cx,cy,w,h)] pairwise-free elementwise IoU
            ax1 = boxes_a[..., 0] - boxes_a[..., 2] / 2
            ay1 = boxes_a[..., 1] - boxes_a[..., 3] / 2
            ax2 = boxes_a[..., 0] + boxes_a[..., 2] / 2
            ay2 = boxes_a[..., 1] + boxes_a[..., 3] / 2
            bx1 = boxes_b[..., 0] - boxes_b[..., 2] / 2
            by1 = boxes_b[..., 1] - boxes_b[..., 3] / 2
            bx2 = boxes_b[..., 0] + boxes_b[..., 2] / 2
            by2 = boxes_b[..., 1] + boxes_b[..., 3] / 2
            ix = jnp.clip(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1),
                          0, None)
            iy = jnp.clip(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1),
                          0, None)
            inter = ix * iy
            ua = (ax2 - ax1) * (ay2 - ay1) + (bx2 - bx1) * (by2 - by1) \
                - inter
            return inter / jnp.maximum(ua, 1e-10)

        # objectness ignore mask: max IoU of each prediction vs any gt
        pb = jnp.stack([px, py, pw, ph], axis=-1)  # [N,A,H,W,4]
        gb_e = gb[:, None, None, None]             # [N,1,1,1,B,4]
        ious = iou_cwh(pb[..., None, :], gb_e)     # [N,A,H,W,B]
        gt_valid = (gb[..., 2] > 0)[:, None, None, None]   # w>0 marks real
        best_iou = jnp.max(jnp.where(gt_valid, ious, 0.0), axis=-1)
        ignore = best_iou > ignore_thresh

        # responsible anchor per gt: best IoU among the masked anchors at
        # (0,0) center (shape-only match, the YOLOv3 assignment)
        awh = jnp.asarray(a_used) / jnp.asarray([in_w, in_h],
                                                jnp.float32)[None]
        shape_a = jnp.concatenate([jnp.zeros_like(awh), awh], -1)
        g_shape = jnp.concatenate(
            [jnp.zeros_like(gb[..., :2]), gb[..., 2:4]], -1)
        sim = iou_cwh(g_shape[:, :, None, :], shape_a[None, None])
        best_a = jnp.argmax(sim, axis=-1)          # [N, B]

        gi = jnp.clip((gb[..., 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gb[..., 1] * h).astype(jnp.int32), 0, h - 1)
        valid = gb[..., 2] > 0                     # [N, B]

        def bce(logit, target):
            return (jax.nn.softplus(logit) - logit * target)

        # gather predictions at assigned cells: [N, B, ...]
        bi = jnp.arange(n)[:, None]
        p_at = pred[bi, best_a, :, gj, gi]         # [N, B, 5+C]
        tx = gb[..., 0] * w - gi
        ty = gb[..., 1] * h - gj
        tw = jnp.log(jnp.maximum(
            gb[..., 2] * in_w / jnp.maximum(
                jnp.asarray(a_used)[best_a][..., 0], 1e-9), 1e-9))
        th = jnp.log(jnp.maximum(
            gb[..., 3] * in_h / jnp.maximum(
                jnp.asarray(a_used)[best_a][..., 1], 1e-9), 1e-9))
        box_scale = 2.0 - gb[..., 2] * gb[..., 3]  # small boxes weigh more
        score = (gs if gs is not None
                 else jnp.ones(gl.shape, jnp.float32))
        wloc = jnp.where(valid, box_scale * score, 0.0)
        loss_xy = (bce(p_at[..., 0], tx) + bce(p_at[..., 1], ty)) * wloc
        loss_wh = (jnp.abs(p_at[..., 2] - tw)
                   + jnp.abs(p_at[..., 3] - th)) * wloc

        # objectness: positives at assigned cells carry the gt score as
        # target (mixup support, reference kernel obj = score), negatives
        # elsewhere unless ignored
        obj_logit = pred[:, :, 4]                  # [N,A,H,W]
        pos = jnp.zeros((n, na, h, w), bool)
        pos = pos.at[bi, best_a, gj, gi].set(valid, mode="drop")
        obj_t = jnp.zeros((n, na, h, w), jnp.float32)
        obj_t = obj_t.at[bi, best_a, gj, gi].set(
            jnp.where(valid, score, 0.0), mode="drop")
        l_obj = bce(obj_logit, obj_t)
        neg_mask = (~pos) & (~ignore)
        loss_obj = jnp.sum(
            jnp.where(pos | neg_mask, l_obj, 0.0), axis=(1, 2, 3))

        # classification at positives
        smooth = 1.0 / class_num if (use_label_smooth
                                     and class_num > 1) else 0.0
        onehot = jax.nn.one_hot(gl.astype(jnp.int32), class_num)
        tcls = onehot * (1 - smooth) + smooth * (1 - onehot) \
            if smooth else onehot
        l_cls = jnp.sum(bce(p_at[..., 5:], tcls), axis=-1)
        l_cls = jnp.where(valid, l_cls * score, 0.0)

        per_img = (jnp.sum(loss_xy + loss_wh, axis=1) + loss_obj
                   + jnp.sum(l_cls, axis=1))
        return per_img

    return apply("yolo_loss", impl, *args)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """Reference ``generate_proposals`` (RPN head postprocess,
    ``python/paddle/vision/ops.py:2038``; CUDA kernel
    ``paddle/phi/kernels/gpu/generate_proposals_kernel.cu``): decode
    anchor deltas, clip to the image, drop boxes below ``min_size``,
    keep ``pre_nms_top_n`` by score, NMS, keep ``post_nms_top_n``.

    Host-side like ``nms`` (data-dependent output sizes). Returns
    (rois [R,4], roi_probs [R,1][, rois_num [N]]).
    """
    sc = np.asarray(unwrap(scores), np.float32)       # [N, A, H, W]
    bd = np.asarray(unwrap(bbox_deltas), np.float32)  # [N, A*4, H, W]
    ims = np.asarray(unwrap(img_size), np.float32)    # [N, 2] (h, w)
    an = np.asarray(unwrap(anchors), np.float32).reshape(-1, 4)
    var = np.asarray(unwrap(variances), np.float32).reshape(-1, 4)
    n, a, h, w = sc.shape
    off = 1.0 if pixel_offset else 0.0

    all_rois, all_probs, nums = [], [], []
    for i in range(n):
        s = sc[i].transpose(1, 2, 0).reshape(-1)          # [H*W*A]
        d = bd[i].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s_i, d_i, an_i, var_i = s[order], d[order], an[order], var[order]

        aw = an_i[:, 2] - an_i[:, 0] + off
        ah = an_i[:, 3] - an_i[:, 1] + off
        acx = an_i[:, 0] + aw * 0.5
        acy = an_i[:, 1] + ah * 0.5
        cx = var_i[:, 0] * d_i[:, 0] * aw + acx
        cy = var_i[:, 1] * d_i[:, 1] * ah + acy
        bw = np.exp(np.minimum(var_i[:, 2] * d_i[:, 2], 10.0)) * aw
        bh = np.exp(np.minimum(var_i[:, 3] * d_i[:, 3], 10.0)) * ah
        boxes = np.stack([cx - bw * 0.5, cy - bh * 0.5,
                          cx + bw * 0.5 - off, cy + bh * 0.5 - off], -1)
        ih, iw = ims[i, 0], ims[i, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - off)
        # reference FilterBoxes clamps min_size up to 1.0
        msz = max(float(min_size), 1.0)
        keep = ((boxes[:, 2] - boxes[:, 0] + off >= msz)
                & (boxes[:, 3] - boxes[:, 1] + off >= msz))
        boxes, s_i = boxes[keep], s_i[keep]

        # greedy NMS with the reference's adaptive threshold (eta < 1
        # decays the threshold as selections accumulate)
        order2 = np.argsort(-s_i)
        sel = []
        thresh = nms_thresh
        while len(order2) and len(sel) < post_nms_top_n:
            j = order2[0]
            sel.append(j)
            if len(order2) == 1:
                break
            rest = order2[1:]
            x1 = np.maximum(boxes[j, 0], boxes[rest, 0])
            y1 = np.maximum(boxes[j, 1], boxes[rest, 1])
            x2 = np.minimum(boxes[j, 2], boxes[rest, 2])
            y2 = np.minimum(boxes[j, 3], boxes[rest, 3])
            inter = (np.clip(x2 - x1 + off, 0, None)
                     * np.clip(y2 - y1 + off, 0, None))
            area_j = ((boxes[j, 2] - boxes[j, 0] + off)
                      * (boxes[j, 3] - boxes[j, 1] + off))
            area_r = ((boxes[rest, 2] - boxes[rest, 0] + off)
                      * (boxes[rest, 3] - boxes[rest, 1] + off))
            iou = inter / np.maximum(area_j + area_r - inter, 1e-10)
            order2 = rest[iou <= thresh]
            if eta < 1.0 and thresh * eta > 0.5:
                thresh *= eta
        all_rois.append(boxes[sel])
        all_probs.append(s_i[sel, None])
        nums.append(len(sel))

    rois = Tensor(jnp.asarray(np.concatenate(all_rois)
                              if all_rois else np.zeros((0, 4), "f4")))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs)
                               if all_probs else np.zeros((0, 1), "f4")))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    return rois, probs


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=-1, return_index=False,
                   return_rois_num=True, rois_num=None, name=None):
    """Reference ``multiclass_nms3`` (``python/paddle/vision/ops.py``;
    kernel ``paddle/phi/kernels/cpu/multiclass_nms3_kernel.cc``): per-class
    greedy NMS over [N, M, 4] boxes / [N, C, M] scores, then a cross-class
    keep_top_k. Host-side like ``nms``/``matrix_nms`` (data-dependent
    output counts). Returns (out [R, 6] = (class, score, x1, y1, x2, y2),
    [index [R, 1],] nms_rois_num [N])."""
    b = np.asarray(unwrap(bboxes), np.float32)
    s = np.asarray(unwrap(scores), np.float32)
    off = 0.0 if normalized else 1.0

    outs, idxs, nums = [], [], []
    for n in range(b.shape[0]):
        dets, det_idx = [], []
        for c in range(s.shape[1]):
            if c == background_label:
                continue
            sc = s[n, c]
            keep = np.flatnonzero(sc > score_threshold)
            if keep.size == 0:
                continue
            keep = keep[np.argsort(-sc[keep])]
            if nms_top_k > 0:
                keep = keep[:nms_top_k]
            boxes_c, sc_c = b[n, keep], sc[keep]
            order = np.arange(len(keep))
            sel = []
            thresh = nms_threshold
            while len(order):
                j = order[0]
                sel.append(j)
                if len(order) == 1:
                    break
                rest = order[1:]
                x1 = np.maximum(boxes_c[j, 0], boxes_c[rest, 0])
                y1 = np.maximum(boxes_c[j, 1], boxes_c[rest, 1])
                x2 = np.minimum(boxes_c[j, 2], boxes_c[rest, 2])
                y2 = np.minimum(boxes_c[j, 3], boxes_c[rest, 3])
                inter = (np.clip(x2 - x1 + off, 0, None)
                         * np.clip(y2 - y1 + off, 0, None))
                area_j = ((boxes_c[j, 2] - boxes_c[j, 0] + off)
                          * (boxes_c[j, 3] - boxes_c[j, 1] + off))
                area_r = ((boxes_c[rest, 2] - boxes_c[rest, 0] + off)
                          * (boxes_c[rest, 3] - boxes_c[rest, 1] + off))
                iou = inter / np.maximum(area_j + area_r - inter, 1e-10)
                order = rest[iou <= thresh]
                if nms_eta < 1.0 and thresh * nms_eta > 0.5:
                    thresh *= nms_eta
            for j in sel:
                dets.append([c, sc_c[j], *boxes_c[j]])
                det_idx.append(keep[j])
        if dets:
            dets = np.asarray(dets, np.float32)
            order = np.argsort(-dets[:, 1])
            if keep_top_k > 0:
                order = order[:keep_top_k]
            dets = dets[order]
            det_idx = np.asarray(det_idx)[order]
        else:
            dets = np.zeros((0, 6), np.float32)
            det_idx = np.zeros((0,), np.int64)
        outs.append(dets)
        idxs.append(det_idx + n * b.shape[1])
        nums.append(len(dets))

    out = Tensor(jnp.asarray(np.concatenate(outs) if outs
                             else np.zeros((0, 6), np.float32)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(
            np.concatenate(idxs)[:, None].astype(np.int64))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(nums, np.int32))))
    return tuple(res) if len(res) > 1 else out

"""ShuffleNetV2 (reference ``python/paddle/vision/models/shufflenetv2.py``:
channel_shuffle/InvertedResidual/InvertedResidualDS/ShuffleNetV2 +
shufflenet_v2_x0_25..x2_0, shufflenet_v2_swish)."""
from __future__ import annotations

from ... import nn, ops

_STAGE_REPEATS = (4, 8, 4)
_STAGE_CHANNELS = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}


def channel_shuffle(x, groups):
    """Reference ``shufflenetv2.py`` channel_shuffle: interleave channel
    groups so information crosses the split branches."""
    n, c, h, w = x.shape
    x = ops.reshape(x, [n, groups, c // groups, h, w])
    x = ops.transpose(x, [0, 2, 1, 3, 4])
    return ops.reshape(x, [n, c, h, w])


def _act(act):
    if act == "swish":
        return nn.Swish()
    return nn.ReLU()


class _ConvBNAct(nn.Sequential):
    def __init__(self, cin, cout, k, stride=1, pad=0, groups=1,
                 act="relu"):
        layers = [nn.Conv2D(cin, cout, k, stride=stride, padding=pad,
                            groups=groups, bias_attr=False),
                  nn.BatchNorm2D(cout)]
        if act is not None:
            layers.append(_act(act))
        super().__init__(*layers)


class InvertedResidual(nn.Layer):
    """stride-1 unit: split channels, transform one half, shuffle."""

    def __init__(self, ch, act):
        super().__init__()
        mid = ch // 2
        self.branch = nn.Sequential(
            _ConvBNAct(mid, mid, 1, act=act),
            _ConvBNAct(mid, mid, 3, stride=1, pad=1, groups=mid, act=None),
            _ConvBNAct(mid, mid, 1, act=act))

    def forward(self, x):
        x1, x2 = ops.split(x, 2, axis=1)
        out = ops.concat([x1, self.branch(x2)], axis=1)
        return channel_shuffle(out, 2)


class InvertedResidualDS(nn.Layer):
    """stride-2 (downsample) unit: both branches transform."""

    def __init__(self, cin, cout, act):
        super().__init__()
        mid = cout // 2
        self.branch1 = nn.Sequential(
            _ConvBNAct(cin, cin, 3, stride=2, pad=1, groups=cin, act=None),
            _ConvBNAct(cin, mid, 1, act=act))
        self.branch2 = nn.Sequential(
            _ConvBNAct(cin, mid, 1, act=act),
            _ConvBNAct(mid, mid, 3, stride=2, pad=1, groups=mid, act=None),
            _ConvBNAct(mid, mid, 1, act=act))

    def forward(self, x):
        out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    """Reference ShuffleNetV2(scale, act, num_classes, with_pool)."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        if scale not in _STAGE_CHANNELS:
            raise ValueError(f"supported scales are "
                             f"{sorted(_STAGE_CHANNELS)}, got {scale}")
        chans = _STAGE_CHANNELS[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = _ConvBNAct(3, chans[0], 3, stride=2, pad=1, act=act)
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        cin = chans[0]
        for i, reps in enumerate(_STAGE_REPEATS):
            cout = chans[i + 1]
            stages.append(InvertedResidualDS(cin, cout, act))
            stages += [InvertedResidual(cout, act) for _ in range(reps - 1)]
            cin = cout
        self.stages = nn.Sequential(*stages)
        self.conv_last = _ConvBNAct(cin, chans[-1], 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chans[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.max_pool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.fc(x)
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load them "
                         "with paddle.load + set_state_dict")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", pretrained, **kwargs)

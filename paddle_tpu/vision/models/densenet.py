"""DenseNet (reference ``python/paddle/vision/models/densenet.py``:
DenseLayer/DenseBlock/TransitionLayer/DenseNet + densenet121..264).
Dense connectivity: each layer consumes the concat of all earlier feature
maps in its block — the concat-heavy pattern XLA fuses well on TPU."""
from __future__ import annotations

from ... import nn, ops

_CONFIGS = {
    121: ((6, 12, 24, 16), 32),
    161: ((6, 12, 36, 24), 48),
    169: ((6, 12, 32, 32), 32),
    201: ((6, 12, 48, 32), 32),
    264: ((6, 12, 64, 48), 32),
}


class _BNReLUConv(nn.Sequential):
    def __init__(self, cin, cout, k, stride=1, pad=0):
        super().__init__(
            nn.BatchNorm2D(cin), nn.ReLU(),
            nn.Conv2D(cin, cout, k, stride=stride, padding=pad,
                      bias_attr=False))


class DenseLayer(nn.Layer):
    def __init__(self, cin, growth_rate, bn_size, dropout):
        super().__init__()
        self.bottleneck = _BNReLUConv(cin, bn_size * growth_rate, 1)
        self.conv = _BNReLUConv(bn_size * growth_rate, growth_rate, 3,
                                pad=1)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        y = self.conv(self.bottleneck(x))
        if self.dropout is not None:
            y = self.dropout(y)
        return ops.concat([x, y], axis=1)


class DenseBlock(nn.Layer):
    def __init__(self, cin, num_layers, growth_rate, bn_size, dropout):
        super().__init__()
        self.layers = nn.LayerList([
            DenseLayer(cin + i * growth_rate, growth_rate, bn_size,
                       dropout) for i in range(num_layers)])
        self.out_channels = cin + num_layers * growth_rate

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class TransitionLayer(nn.Layer):
    def __init__(self, cin, cout):
        super().__init__()
        self.conv = _BNReLUConv(cin, cout, 1)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(x))


class DenseNet(nn.Layer):
    """Reference ``densenet.py`` DenseNet(layers, bn_size, dropout,
    num_classes, with_pool)."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        if layers not in _CONFIGS:
            raise ValueError(
                f"supported layers are {sorted(_CONFIGS)}, got {layers}")
        block_cfg, growth = _CONFIGS[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool

        c = 2 * growth
        self.stem = nn.Sequential(
            nn.Conv2D(3, c, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(c), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        blocks = []
        for i, n in enumerate(block_cfg):
            blk = DenseBlock(c, n, growth, bn_size, dropout)
            blocks.append(blk)
            c = blk.out_channels
            if i != len(block_cfg) - 1:
                blocks.append(TransitionLayer(c, c // 2))
                c //= 2
        self.blocks = nn.Sequential(*blocks)
        self.final = nn.Sequential(nn.BatchNorm2D(c), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.final(self.blocks(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.classifier(x)
        return x


def _densenet(layers, pretrained, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load them "
                         "with paddle.load + set_state_dict")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)

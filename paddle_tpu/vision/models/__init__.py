"""``paddle.vision.models`` parity (reference ``python/paddle/vision/models/``:
all 12 in-tree families). Same architectures and constructor surfaces;
``pretrained=True`` is rejected (no weight hub in this environment — load
weights with ``paddle.load``/``set_state_dict`` instead).
"""
from .lenet import LeNet
from .resnet import (ResNet, BasicBlock, BottleneckBlock, resnet18,
                     resnet34, resnet50, resnet101, resnet152)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .alexnet import AlexNet, alexnet
from .mobilenetv1 import MobileNetV1, mobilenet_v1
from .mobilenetv2 import MobileNetV2, mobilenet_v2
from .mobilenetv3 import (MobileNetV3Small, MobileNetV3Large,
                          mobilenet_v3_small, mobilenet_v3_large)
from .densenet import (DenseNet, densenet121, densenet161, densenet169,
                       densenet201, densenet264)
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1
from .shufflenetv2 import (ShuffleNetV2, shufflenet_v2_x0_25,
                           shufflenet_v2_x0_33, shufflenet_v2_x0_5,
                           shufflenet_v2_x1_0, shufflenet_v2_x1_5,
                           shufflenet_v2_x2_0, shufflenet_v2_swish)
from .googlenet import GoogLeNet, googlenet
from .inceptionv3 import InceptionV3, inception_v3

__all__ = [
    "LeNet", "ResNet", "BasicBlock", "BottleneckBlock", "resnet18",
    "resnet34", "resnet50", "resnet101", "resnet152", "VGG", "vgg11",
    "vgg13", "vgg16", "vgg19", "AlexNet", "alexnet",
    "MobileNetV1", "mobilenet_v1", "MobileNetV2", "mobilenet_v2",
    "MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
    "mobilenet_v3_large", "DenseNet", "densenet121", "densenet161",
    "densenet169", "densenet201", "densenet264", "SqueezeNet",
    "squeezenet1_0", "squeezenet1_1", "ShuffleNetV2",
    "shufflenet_v2_x0_25", "shufflenet_v2_x0_33", "shufflenet_v2_x0_5",
    "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
    "shufflenet_v2_swish", "GoogLeNet", "googlenet", "InceptionV3",
    "inception_v3",
]

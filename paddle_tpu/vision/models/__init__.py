"""``paddle.vision.models`` parity (reference ``python/paddle/vision/models/``:
lenet.py, resnet.py, vgg.py, alexnet.py, mobilenetv2.py). Same
architectures and constructor surfaces; ``pretrained=True`` is rejected
(no weight hub in this environment — load weights with
``paddle.load``/``set_state_dict`` instead).
"""
from .lenet import LeNet
from .resnet import (ResNet, BasicBlock, BottleneckBlock, resnet18,
                     resnet34, resnet50, resnet101, resnet152)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .alexnet import AlexNet, alexnet
from .mobilenetv2 import MobileNetV2, mobilenet_v2

__all__ = [
    "LeNet", "ResNet", "BasicBlock", "BottleneckBlock", "resnet18",
    "resnet34", "resnet50", "resnet101", "resnet152", "VGG", "vgg11",
    "vgg13", "vgg16", "vgg19", "AlexNet", "alexnet", "MobileNetV2",
    "mobilenet_v2",
]

"""MobileNetV3 (reference
``python/paddle/vision/models/mobilenetv3.py``: SqueezeExcitation /
InvertedResidual / MobileNetV3Small / MobileNetV3Large +
mobilenet_v3_small / mobilenet_v3_large)."""
from __future__ import annotations

from ... import nn, ops
from .mobilenetv2 import _make_divisible

# (kernel, expanded, out, use_se, activation, stride)
_LARGE = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


def _act(name):
    return nn.Hardswish() if name == "hardswish" else nn.ReLU()


class _ConvBNAct(nn.Sequential):
    def __init__(self, cin, cout, k, stride=1, groups=1, act=None):
        layers = [nn.Conv2D(cin, cout, k, stride=stride,
                            padding=(k - 1) // 2, groups=groups,
                            bias_attr=False),
                  nn.BatchNorm2D(cout)]
        if act is not None:
            layers.append(_act(act))
        super().__init__(*layers)


class SqueezeExcitation(nn.Layer):
    """Reference ``mobilenetv3.py:52``: avgpool -> fc(relu) ->
    fc(hardsigmoid) channel gate."""

    def __init__(self, channels, squeeze):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(channels, squeeze, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze, channels, 1)
        self.gate = nn.Hardsigmoid()

    def forward(self, x):
        s = self.gate(self.fc2(self.relu(self.fc1(self.avgpool(x)))))
        return x * s


class InvertedResidual(nn.Layer):
    def __init__(self, cin, expanded, cout, k, use_se, act, stride):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expanded != cin:
            layers.append(_ConvBNAct(cin, expanded, 1, act=act))
        layers.append(_ConvBNAct(expanded, expanded, k, stride=stride,
                                 groups=expanded, act=act))
        if use_se:
            layers.append(SqueezeExcitation(
                expanded, _make_divisible(expanded // 4)))
        layers.append(_ConvBNAct(expanded, cout, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_channel, scale, num_classes, with_pool):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return _make_divisible(ch * scale)

        cin = c(16)
        layers = [_ConvBNAct(3, cin, 3, stride=2, act="hardswish")]
        for k, exp, out, se, act, stride in cfg:
            layers.append(InvertedResidual(
                cin, c(exp), c(out), k, se, act, stride))
            cin = c(out)
        lastconv = 6 * cin
        layers.append(_ConvBNAct(cin, lastconv, 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(lastconv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.classifier(x)
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale, num_classes, with_pool)


def _v3(cls, pretrained, scale, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load them "
                         "with paddle.load + set_state_dict")
    return cls(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return _v3(MobileNetV3Small, pretrained, scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return _v3(MobileNetV3Large, pretrained, scale, **kwargs)

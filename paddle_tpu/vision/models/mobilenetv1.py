"""MobileNetV1 (reference ``python/paddle/vision/models/mobilenetv1.py``:
ConvBNLayer/DepthwiseSeparable/MobileNetV1 + mobilenet_v1). Depthwise
convs lower to XLA grouped convolutions (feature_group_count)."""
from __future__ import annotations

from ... import nn, ops


class ConvBNReLU(nn.Sequential):
    def __init__(self, cin, cout, k, stride=1, padding=0, groups=1):
        super().__init__(
            nn.Conv2D(cin, cout, k, stride=stride, padding=padding,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(cout), nn.ReLU())


class DepthwiseSeparable(nn.Sequential):
    def __init__(self, cin, cout, stride):
        super().__init__(
            ConvBNReLU(cin, cin, 3, stride=stride, padding=1, groups=cin),
            ConvBNReLU(cin, cout, 1))


class MobileNetV1(nn.Layer):
    """Reference MobileNetV1(scale, num_classes, with_pool)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return int(ch * scale)

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + \
              [(512, 512, 1)] * 5 + [(512, 1024, 2), (1024, 1024, 1)]
        layers = [ConvBNReLU(3, c(32), 3, stride=2, padding=1)]
        layers += [DepthwiseSeparable(c(i), c(o), s) for i, o, s in cfg]
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load them "
                         "with paddle.load + set_state_dict")
    return MobileNetV1(scale=scale, **kwargs)

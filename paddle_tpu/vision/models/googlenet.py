"""GoogLeNet / Inception v1 (reference
``python/paddle/vision/models/googlenet.py``: Inception/GoogLeNet +
googlenet). Forward returns (out, aux1, aux2) like the reference (the aux
classifiers feed the deep-supervision loss during training)."""
from __future__ import annotations

from ... import nn, ops


class _ConvReLU(nn.Sequential):
    def __init__(self, cin, cout, k, stride=1, pad=0):
        super().__init__(
            nn.Conv2D(cin, cout, k, stride=stride, padding=pad),
            nn.ReLU())


class Inception(nn.Layer):
    def __init__(self, cin, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _ConvReLU(cin, c1, 1)
        self.b3 = nn.Sequential(_ConvReLU(cin, c3r, 1),
                                _ConvReLU(c3r, c3, 3, pad=1))
        self.b5 = nn.Sequential(_ConvReLU(cin, c5r, 1),
                                _ConvReLU(c5r, c5, 5, pad=2))
        self.bp = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _ConvReLU(cin, proj, 1))

    def forward(self, x):
        return ops.concat(
            [self.b1(x), self.b3(x), self.b5(x), self.bp(x)], axis=1)


class _AuxHead(nn.Layer):
    def __init__(self, cin, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(4)
        self.conv = _ConvReLU(cin, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.relu = nn.ReLU()
        self.drop = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x))
        x = self.relu(self.fc1(ops.flatten(x, 1)))
        return self.fc2(self.drop(x))


class GoogLeNet(nn.Layer):
    """Reference GoogLeNet(num_classes, with_pool); forward returns
    (main_logits, aux1_logits, aux2_logits)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        pool = lambda: nn.MaxPool2D(3, stride=2, padding=1)  # noqa: E731

        self.stem = nn.Sequential(
            _ConvReLU(3, 64, 7, stride=2, pad=3), pool(),
            _ConvReLU(64, 64, 1), _ConvReLU(64, 192, 3, pad=1), pool())
        self.i3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = pool()
        self.i4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = pool()
        self.i5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.i3b(self.i3a(self.stem(x)))
        x = self.i4a(self.pool3(x))
        aux1 = self.aux1(x) if self.num_classes > 0 else None
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.fc(self.drop(x))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load them "
                         "with paddle.load + set_state_dict")
    return GoogLeNet(**kwargs)

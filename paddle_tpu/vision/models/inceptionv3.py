"""Inception v3 (reference
``python/paddle/vision/models/inceptionv3.py``: InceptionStem /
InceptionA-E / InceptionV3 + inception_v3). Factorized convolutions
(1xN / Nx1 pairs) — all dense convs, MXU-friendly."""
from __future__ import annotations

from ... import nn, ops


class _ConvBN(nn.Sequential):
    def __init__(self, cin, cout, k, stride=1, pad=0):
        super().__init__(
            nn.Conv2D(cin, cout, k, stride=stride, padding=pad,
                      bias_attr=False),
            nn.BatchNorm2D(cout), nn.ReLU())


class InceptionStem(nn.Sequential):
    def __init__(self):
        super().__init__(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, pad=1), nn.MaxPool2D(3, stride=2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3),
            nn.MaxPool2D(3, stride=2))


class InceptionA(nn.Layer):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.b1 = _ConvBN(cin, 64, 1)
        self.b5 = nn.Sequential(_ConvBN(cin, 48, 1),
                                _ConvBN(48, 64, 5, pad=2))
        self.b3 = nn.Sequential(_ConvBN(cin, 64, 1),
                                _ConvBN(64, 96, 3, pad=1),
                                _ConvBN(96, 96, 3, pad=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(cin, pool_features, 1))

    def forward(self, x):
        return ops.concat(
            [self.b1(x), self.b5(x), self.b3(x), self.bp(x)], axis=1)


class InceptionB(nn.Layer):
    """Grid reduction 35 -> 17."""

    def __init__(self, cin):
        super().__init__()
        self.b3 = _ConvBN(cin, 384, 3, stride=2)
        self.b3d = nn.Sequential(_ConvBN(cin, 64, 1),
                                 _ConvBN(64, 96, 3, pad=1),
                                 _ConvBN(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class InceptionC(nn.Layer):
    """Factorized 7x7 branches."""

    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = _ConvBN(cin, 192, 1)
        self.b7 = nn.Sequential(
            _ConvBN(cin, c7, 1),
            _ConvBN(c7, c7, (1, 7), pad=(0, 3)),
            _ConvBN(c7, 192, (7, 1), pad=(3, 0)))
        self.b7d = nn.Sequential(
            _ConvBN(cin, c7, 1),
            _ConvBN(c7, c7, (7, 1), pad=(3, 0)),
            _ConvBN(c7, c7, (1, 7), pad=(0, 3)),
            _ConvBN(c7, c7, (7, 1), pad=(3, 0)),
            _ConvBN(c7, 192, (1, 7), pad=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(cin, 192, 1))

    def forward(self, x):
        return ops.concat(
            [self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], axis=1)


class InceptionD(nn.Layer):
    """Grid reduction 17 -> 8."""

    def __init__(self, cin):
        super().__init__()
        self.b3 = nn.Sequential(_ConvBN(cin, 192, 1),
                                _ConvBN(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _ConvBN(cin, 192, 1),
            _ConvBN(192, 192, (1, 7), pad=(0, 3)),
            _ConvBN(192, 192, (7, 1), pad=(3, 0)),
            _ConvBN(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class InceptionE(nn.Layer):
    """Expanded-filter-bank output blocks."""

    def __init__(self, cin):
        super().__init__()
        self.b1 = _ConvBN(cin, 320, 1)
        self.b3_stem = _ConvBN(cin, 384, 1)
        self.b3_a = _ConvBN(384, 384, (1, 3), pad=(0, 1))
        self.b3_b = _ConvBN(384, 384, (3, 1), pad=(1, 0))
        self.b3d_stem = nn.Sequential(_ConvBN(cin, 448, 1),
                                      _ConvBN(448, 384, 3, pad=1))
        self.b3d_a = _ConvBN(384, 384, (1, 3), pad=(0, 1))
        self.b3d_b = _ConvBN(384, 384, (3, 1), pad=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(cin, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return ops.concat(
            [self.b1(x),
             ops.concat([self.b3_a(s), self.b3_b(s)], axis=1),
             ops.concat([self.b3d_a(d), self.b3d_b(d)], axis=1),
             self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    """Reference InceptionV3(num_classes, with_pool); input 299x299."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = InceptionStem()
        self.blocks = nn.Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160),
            InceptionC(768, 160), InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout(0.2)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.fc(self.drop(x))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load them "
                         "with paddle.load + set_state_dict")
    return InceptionV3(**kwargs)

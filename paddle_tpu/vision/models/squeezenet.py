"""SqueezeNet (reference ``python/paddle/vision/models/squeezenet.py``:
MakeFire/SqueezeNet + squeezenet1_0/1_1). Fire modules: 1x1 squeeze then
parallel 1x1/3x3 expands concatenated on channels."""
from __future__ import annotations

from ... import nn, ops


class Fire(nn.Layer):
    def __init__(self, cin, squeeze, expand1, expand3):
        super().__init__()
        self.squeeze = nn.Sequential(
            nn.Conv2D(cin, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(
            nn.Conv2D(squeeze, expand1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(
            nn.Conv2D(squeeze, expand3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        s = self.squeeze(x)
        return ops.concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    """Reference SqueezeNet(version, num_classes, with_pool)."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        if version not in ("1.0", "1.1"):
            raise ValueError(f"version must be '1.0' or '1.1', "
                             f"got {version!r}")
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool
        pool = lambda: nn.MaxPool2D(3, stride=2, padding=0)  # noqa: E731
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(), pool(),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128), pool(),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256), pool(),
                Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2, padding=1), nn.ReLU(),
                pool(),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64), pool(),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128), pool(),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1),
                nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return ops.flatten(x, 1)


def _squeezenet(version, pretrained, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled; load them "
                         "with paddle.load + set_state_dict")
    return SqueezeNet(version=version, **kwargs)


def squeezenet1_0(pretrained=False, **kwargs):
    return _squeezenet("1.0", pretrained, **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return _squeezenet("1.1", pretrained, **kwargs)

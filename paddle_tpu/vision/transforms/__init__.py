"""``paddle.vision.transforms`` parity (reference
``python/paddle/vision/transforms/transforms.py`` Compose :150, ToTensor
:295, Resize :370, RandomHorizontalFlip :789, Normalize :886, Transpose
:978, RandomCrop :620, CenterCrop :750, Pad :1025).

Numpy/PIL-free implementation: images are HWC uint8/float numpy arrays (the
DataLoader collates numpy anyway); interpolation is nearest/bilinear via
vectorized numpy — host-side preprocessing stays off the TPU.
"""
from __future__ import annotations

import random

import numpy as np


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def to_tensor(img, data_format="CHW"):
    img = _as_hwc(img)
    if img.dtype == np.uint8:
        img = img.astype("float32") / 255.0
    else:
        img = img.astype("float32")
    if data_format == "CHW":
        img = np.transpose(img, (2, 0, 1))
    return img


class ToTensor(BaseTransform):
    """HWC [0,255] -> CHW float32 [0,1] (reference ``ToTensor:295``).
    Returns numpy (collated to device tensors by the DataLoader)."""

    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h <= w:
            oh, ow = size, max(1, int(size * w / h))
        else:
            oh, ow = max(1, int(size * h / w)), size
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return img
    if interpolation == "nearest":
        ry = (np.arange(oh) * (h / oh)).astype(int).clip(0, h - 1)
        rx = (np.arange(ow) * (w / ow)).astype(int).clip(0, w - 1)
        return img[ry][:, rx]
    # bilinear
    y = (np.arange(oh) + 0.5) * h / oh - 0.5
    x = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(y).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(x).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(y - y0, 0, 1)[:, None, None]
    wx = np.clip(x - x0, 0, 1)[None, :, None]
    im = img.astype("float32")
    out = (im[y0][:, x0] * (1 - wy) * (1 - wx) +
           im[y1][:, x0] * wy * (1 - wx) +
           im[y0][:, x1] * (1 - wy) * wx +
           im[y1][:, x1] * wy * wx)
    if img.dtype == np.uint8:
        return np.rint(out).clip(0, 255).astype(np.uint8)
    return out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = img.shape[:2]
    th, tw = output_size
    i = max(0, (h - th) // 2)
    j = max(0, (w - tw) // 2)
    return img[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding is not None:
            img = pad(img, self.padding, self.fill, self.padding_mode)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (max(0, (tw - w)), max(0, (th - h))), self.fill,
                      self.padding_mode)
            h, w = img.shape[:2]
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[:, ::-1].copy()
        return _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[::-1].copy()
        return _as_hwc(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    img = np.asarray(img, dtype="float32")
    mean = np.asarray(mean, dtype="float32")
    std = np.asarray(std, dtype="float32")
    if data_format == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    return (img - mean.reshape(shape)) / std.reshape(shape)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, (int, float)):
            mean = [mean] * 3
        if isinstance(std, (int, float)):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class BatchNormalize:
    """Batched uint8 [N,H,W,C] -> normalized float32 [N,C,H,W] through the
    native IO runtime (``io/native/loader.cc``): multithreaded, GIL-free —
    the collate-side hot path of an image input pipeline. Falls back to
    numpy when the native library is unavailable."""

    def __init__(self, mean, std, to_chw=True):
        self.mean = mean
        self.std = std
        self.to_chw = to_chw

    def __call__(self, batch):
        import numpy as _np

        from ...io import native as _native
        batch = _np.asarray(batch)
        if batch.ndim != 4 or batch.dtype != _np.uint8:
            raise ValueError("BatchNormalize expects a uint8 NHWC batch")
        return _native.normalize_batch(batch, self.mean, self.std,
                                       to_chw=self.to_chw)


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _as_hwc(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:
        pl = pr = padding[0]
        pt = pb = padding[1]
    else:
        pl, pt, pr, pb = padding
    widths = [(pt, pb), (pl, pr), (0, 0)]
    if padding_mode == "constant":
        return np.pad(img, widths, constant_values=fill)
    return np.pad(img, widths, mode={"reflect": "reflect",
                                     "edge": "edge",
                                     "symmetric": "symmetric"}[padding_mode])


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(_as_hwc(img), self.order)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                crop = img[i:i + th, j:j + tw]
                return resize(crop, self.size, self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = random.uniform(max(0, 1 - self.value), 1 + self.value)
        img = _as_hwc(img)
        out = img.astype("float32") * alpha
        if img.dtype == np.uint8:
            return out.clip(0, 255).astype("uint8")
        return out


__all__ = [
    "Compose", "BaseTransform", "ToTensor", "to_tensor", "Resize", "resize",
    "CenterCrop", "center_crop", "RandomCrop", "RandomHorizontalFlip",
    "RandomVerticalFlip", "Normalize", "normalize", "Pad", "pad",
    "Transpose", "RandomResizedCrop", "BrightnessTransform",
]

"""Diagnostics engine: runs the registered checks, applies suppression,
and reports findings according to ``FLAGS_analysis``
(``PDTPU_ANALYSIS=off|warn|error``).

Entry points:

- :func:`analyze_source` / :func:`analyze_file` — AST front-end over
  source text (the CLI and the pre-conversion lint).
- :func:`check_function` — AST front-end over a live callable.
- :func:`check_jaxpr` / :func:`check_traced` / :func:`check_executable`
  — IR front-end over a traced program.
- :func:`report` / :func:`report_runtime` — route findings per the mode
  flag: ``off`` drops them, ``warn`` emits :class:`LintWarning`
  (notes go to the module logger), ``error`` raises
  :class:`~paddle_tpu.core.errors.StaticAnalysisError` on any finding of
  warn severity or above.
- :func:`collect` — context manager capturing findings into a list
  instead of reporting (tests, tooling).
"""
from __future__ import annotations

import ast
import inspect
import logging
import textwrap
import warnings
from dataclasses import dataclass, field
from typing import Iterable, Optional

from ..core import state
from .registry import (REGISTRY, CheckSpec, Diagnostic, Severity,
                       active_suppressions, decorator_name,
                       pragma_suppressed)

logger = logging.getLogger("paddle_tpu.analysis")

_MODES = ("off", "warn", "error")


class LintWarning(UserWarning):
    """Category for analyzer findings reported in ``warn`` mode."""


def mode() -> str:
    try:
        m = str(state.get_flag("analysis")).lower()
    except KeyError:
        return "warn"
    return m if m in _MODES else "warn"


# --------------------------------------------------------------------------
# collection sink (tests/tooling) + session-level dedup
# --------------------------------------------------------------------------

# Process-global like the suppression stack (registry._SuppressState):
# runtime reports may arrive from a jax callback thread.
class _Sinks:
    def __init__(self):
        self.stack: list[list] = []


_sinks = _Sinks()
_reported: set[tuple] = set()


class collect:
    """``with analysis.collect() as diags:`` captures every finding that
    would have been reported (regardless of mode) into ``diags`` —
    process-wide, so callback-thread runtime reports land too."""

    def __enter__(self):
        self._sink: list[Diagnostic] = []
        _sinks.stack.append(self._sink)
        return self._sink

    def __exit__(self, *exc):
        stack = _sinks.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self._sink:
                del stack[i]
                break
        return False


def reset_reported():
    """Clear the session dedup set (one report per (code, site))."""
    _reported.clear()


# --------------------------------------------------------------------------
# AST front-end
# --------------------------------------------------------------------------

@dataclass
class _AstCtx:
    filename: str
    lines: list[str]
    line_offset: int = 0
    decorated: bool = False


def _is_to_static_decorator(dec) -> bool:
    return decorator_name(dec) == "to_static"


def _iter_functions(tree, force_jit):
    """(fndef, decorated, in_jit) for EVERY function: in_jit when
    decorated with ``to_static``, forced, or NESTED inside a jit
    function (inline helpers are traced too). Each nested def is
    yielded as its own scope — the AST checks do not descend into
    nested defs — so per-function suppression binds to the right
    function. Checks with scope "jit" run on in-jit functions, scope
    "eager" on the rest."""
    def visit(node, in_jit):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                decorated = any(_is_to_static_decorator(d)
                                for d in child.decorator_list)
                jit = decorated or force_jit or in_jit
                yield child, decorated, jit
                yield from visit(child, jit)
            else:
                yield from visit(child, in_jit)

    yield from visit(tree, False)


def _decorator_suppressions(fndef):
    """Codes silenced by ``@analysis.suppress("PDT1xx", ...)`` decorators,
    read syntactically so source-only analysis (the CLI) matches the
    runtime tag the decorator sets. ``None`` means suppress everything
    (a bare ``@suppress()``)."""
    out: set[str] = set()
    for dec in fndef.decorator_list:
        if decorator_name(dec) != "suppress" or not isinstance(dec, ast.Call):
            continue
        if not dec.args:
            return None
        for a in dec.args:
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                out.add(a.value.upper())
    return out


def _span_suppressed(lines: list[str], lo: int, hi: int, code: str) -> bool:
    """True when any line of the 1-based inclusive span ``lo..hi``
    carries a noqa pragma covering ``code``. Suppression anchors to the
    STATEMENT's full line span, not a single line — a pragma anywhere on
    a decorated def (decorator lines included) or a multiline statement
    suppresses findings anchored anywhere in it."""
    lo = max(1, lo)
    hi = min(len(lines), hi)
    return any(pragma_suppressed(lines[i - 1], code)
               for i in range(lo, hi + 1))


def _def_span(fndef) -> tuple[int, int]:
    """Line span of a function's HEADER: first decorator line through
    the end of the signature (the line before the first body
    statement). A pragma anywhere in it opts the whole function out."""
    lo = min([d.lineno for d in fndef.decorator_list] + [fndef.lineno])
    hi = fndef.body[0].lineno - 1 if fndef.body else fndef.lineno
    return lo, max(lo, hi)


def analyze_source(source: str, filename: str = "<string>", *,
                   force_jit: bool = False, line_offset: int = 0,
                   extra_suppress: frozenset = frozenset()
                   ) -> list[Diagnostic]:
    """Run every AST check over ``source``; returns surviving findings.

    Only functions in a jit context are checked: decorated with
    ``to_static`` (any dotted spelling), or all of them under
    ``force_jit``. Suppression (pragma, active ``suppress`` contexts,
    ``extra_suppress``) is applied here."""
    try:
        tree = ast.parse(textwrap.dedent(source))
    except SyntaxError:
        return []
    lines = textwrap.dedent(source).splitlines()
    suppressed = active_suppressions() | extra_suppress
    out: list[Diagnostic] = []
    seen: set[tuple] = set()
    for fndef, decorated, in_jit in _iter_functions(tree, force_jit):
        ctx = _AstCtx(filename=filename, lines=lines,
                      line_offset=line_offset, decorated=decorated)
        def_lo, def_hi = _def_span(fndef)
        dec_sup = _decorator_suppressions(fndef)
        if dec_sup is None:
            continue  # bare @suppress(): whole function opted out
        for spec in REGISTRY.values():
            if spec.frontend != "ast" or spec.func is None:
                continue
            if spec.scope != "any" and (spec.scope == "jit") != in_jit:
                continue
            if spec.code in suppressed or spec.code in dec_sup:
                continue
            for node, message in spec.func(fndef, ctx):
                rel = getattr(node, "lineno", fndef.lineno)
                col = getattr(node, "col_offset", 0)
                key = (spec.code, rel, col, message)
                if key in seen:
                    continue
                seen.add(key)
                end = getattr(node, "end_lineno", None) or rel
                if _span_suppressed(lines, rel, max(rel, end),
                                    spec.code) or \
                        _span_suppressed(lines, def_lo, def_hi,
                                         spec.code):
                    continue
                out.append(Diagnostic(
                    code=spec.code, severity=spec.severity,
                    message=message, file=filename,
                    line=rel + line_offset, col=col))
    out.sort(key=lambda d: (d.line, d.col, d.code))
    return out


def analyze_file(path: str, *, force_jit: bool = False) -> list[Diagnostic]:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        src = f.read()
    return analyze_source(src, filename=str(path), force_jit=force_jit)


def _unwrap_callable(fn):
    for attr in ("fn", "__func__", "__wrapped_original__"):
        inner = getattr(fn, attr, None)
        if inner is not None and callable(inner):
            fn = inner
    return fn


def check_function(fn, *, jit: bool = True) -> list[Diagnostic]:
    """AST-lint a live callable (methods/StaticFunctions unwrapped).
    Returns [] when source is unavailable."""
    fn = _unwrap_callable(fn)
    extra = frozenset(getattr(fn, "__pdtpu_suppress__", frozenset()))
    try:
        src_lines, start = inspect.getsourcelines(fn)
        filename = inspect.getsourcefile(fn) or "<unknown>"
    except (OSError, TypeError):
        return []
    return analyze_source("".join(src_lines), filename=filename,
                          force_jit=jit, line_offset=start - 1,
                          extra_suppress=extra)


# --------------------------------------------------------------------------
# IR front-end
# --------------------------------------------------------------------------

@dataclass
class _IrCtx:
    donated: frozenset = frozenset()
    n_explicit_args: int = 0
    where: str = "<jaxpr>"


def _eqn_site(eqn):
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name, frame.start_line
    except Exception:
        pass
    return None


def check_jaxpr(closed, *, donated: Iterable[int] = (),
                n_explicit_args: int = 0, where: str = "<jaxpr>",
                extra_suppress: frozenset = frozenset()
                ) -> list[Diagnostic]:
    """Run every IR check over a ClosedJaxpr; returns surviving
    findings. ``donated`` are invar indices the program donates;
    ``n_explicit_args`` marks the leading caller-owned inputs."""
    suppressed = active_suppressions() | frozenset(extra_suppress)
    ctx = _IrCtx(donated=frozenset(donated),
                 n_explicit_args=int(n_explicit_args), where=where)
    out: list[Diagnostic] = []
    for spec in REGISTRY.values():
        if spec.frontend != "ir" or spec.func is None:
            continue
        if spec.code in suppressed:
            continue
        try:
            findings = list(spec.func(closed, ctx))
        except Exception:  # a broken check must never break the build
            logger.debug("IR check %s failed", spec.code, exc_info=True)
            continue
        for message, eqn in findings:
            site = _eqn_site(eqn) if eqn is not None else None
            file, line = site if site else (where, 0)
            out.append(Diagnostic(code=spec.code, severity=spec.severity,
                                  message=message, file=file, line=line))
    return out


def check_traced(fn, *args, **kwargs) -> list[Diagnostic]:
    """Trace ``fn`` with jax.make_jaxpr and IR-lint the result."""
    import jax
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return check_jaxpr(closed, where=getattr(fn, "__name__", "<fn>"))


def check_executable(exe, where: str = "<to_static>",
                     extra_suppress: frozenset = frozenset()
                     ) -> list[Diagnostic]:
    """IR-lint a built ``jit._Executable`` (uses the jaxpr and donation
    info captured at build time; [] once the jaxpr has been released
    after the post-capture lint)."""
    closed = getattr(exe, "jaxpr", None)
    if closed is None:
        return []
    return check_jaxpr(
        closed, donated=getattr(exe, "donate_idx", ()),
        n_explicit_args=getattr(exe, "n_explicit_args", 0), where=where,
        extra_suppress=extra_suppress)


# --------------------------------------------------------------------------
# reporting
# --------------------------------------------------------------------------

def report(diags: list[Diagnostic], *, where: str = "", dedup: bool = True,
           allow_raise: bool = True) -> None:
    """Route findings per the mode flag. With ``dedup`` (default), a
    site — (code, file, line); message ignored because the AST linter
    and dy2static's decline path can describe the same graph break
    differently — reports once per session, EXCEPT in error mode, where
    a blocking site keeps raising until it is fixed or suppressed
    (nothing is marked reported when we raise)."""
    if not diags:
        return
    if _sinks.stack:
        _sinks.stack[-1].extend(diags)
        return
    m = mode()
    if m == "off":
        return
    prefix = f"[{where}] " if where else ""
    if m == "error" and allow_raise:
        # the gate ignores the dedup set: a blocking site keeps raising
        # even if it was already surfaced as a warning in warn mode
        blocking = [d for d in diags if d.severity >= Severity.WARN]
        if blocking:
            from ..core.errors import StaticAnalysisError
            raise StaticAnalysisError(
                prefix + "static analysis found "
                f"{len(blocking)} blocking finding(s) "
                f"(PDTPU_ANALYSIS=error):\n"
                + "\n".join("  " + d.format() for d in blocking))
    fresh = [d for d in diags
             if not dedup or (d.code, d.file, d.line) not in _reported]
    if not fresh:
        return
    if dedup:
        for d in fresh:
            _reported.add((d.code, d.file, d.line))
    for d in fresh:
        if d.severity == Severity.NOTE:
            logger.info("%s%s", prefix, d.format())
        else:
            warnings.warn(prefix + d.format(), LintWarning, stacklevel=3)


def report_runtime(code: str, message: str, *, file: str = "<runtime>",
                   line: int = 0) -> None:
    """Report a runtime-produced diagnostic (e.g. PDT206 from inside a
    compiled program) through the mode/suppression funnel. Runtime
    findings are never deduped (each occurrence is a distinct event —
    two different loops truncating must both surface) and never raise
    even in error mode: they fire mid-execution, often from inside a
    ``jax.debug.callback``, where an exception would abort the step with
    a corrupted result instead of gating it."""
    spec: Optional[CheckSpec] = REGISTRY.get(code)
    if spec is None or code in active_suppressions():
        return
    diag = Diagnostic(code=code, severity=spec.severity, message=message,
                      file=file, line=line)
    if _sinks.stack or mode() != "off":
        report([diag], dedup=False, allow_raise=False)
    elif spec.severity >= Severity.WARN:
        # even with the lint off, a warn-severity runtime event (e.g. a
        # truncated while_loop = wrong numerics) must not go silent
        warnings.warn(diag.format(), LintWarning, stacklevel=2)


# --------------------------------------------------------------------------
# wiring entry points (called from jit.to_static / hapi.Model.prepare)
# --------------------------------------------------------------------------

def lint_callable(fn, *, where: str = "") -> list[Diagnostic]:
    """AST-lint ``fn`` and report. The to_static/hapi hook: a no-op when
    the flag is off; never raises except StaticAnalysisError in error
    mode."""
    if mode() == "off":
        return []
    try:
        diags = check_function(fn, jit=True)
    except Exception:
        logger.debug("lint_callable failed", exc_info=True)
        return []
    report(diags, where=where or getattr(fn, "__name__", ""))
    return diags


def lint_executable(exe, *, where: str = "", fn=None) -> list[Diagnostic]:
    """IR-lint a built executable and report (the post-capture hook).
    ``fn`` is the source function the capture came from — its
    ``@analysis.suppress`` tag covers IR findings too."""
    if mode() == "off":
        return []
    extra = frozenset()
    if fn is not None:
        extra = frozenset(getattr(_unwrap_callable(fn),
                                  "__pdtpu_suppress__", frozenset()))
    try:
        diags = check_executable(exe, where=where or "<to_static>",
                                 extra_suppress=extra)
    except Exception:
        logger.debug("lint_executable failed", exc_info=True)
        return []
    report(diags, where=where)
    return diags


# --------------------------------------------------------------------------
# registry self-exercise (the golden test and the CLI --explain both use
# this): run a spec's example / near_miss through its front-end.
# --------------------------------------------------------------------------

def exercise(spec: CheckSpec, which: str = "example") -> list[Diagnostic]:
    """Execute a registry snippet and return the diagnostics it yields.

    ``ast`` snippets are analyzed as source (every function treated per
    its decorators); ``ir`` snippets are executed and must define
    ``JAXPR`` (plus optional ``DONATED``/``N_ARGS``); ``runtime``
    snippets are executed and must define ``DIAGS`` (usually via
    ``analysis.collect``)."""
    src = textwrap.dedent(getattr(spec, which))
    if spec.frontend == "ast":
        return analyze_source(src, filename=f"<{spec.code}:{which}>")
    ns: dict = {}
    exec(compile(src, f"<{spec.code}:{which}>", "exec"), ns)  # noqa: S102
    if spec.frontend == "ir":
        return check_jaxpr(ns["JAXPR"],
                           donated=ns.get("DONATED", frozenset()),
                           n_explicit_args=ns.get("N_ARGS", 0),
                           where=f"<{spec.code}:{which}>")
    return list(ns["DIAGS"])

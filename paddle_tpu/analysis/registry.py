"""Diagnostic registry — the coded-check catalog of the graph lint.

Every check the analyzer can emit is registered here as a
:class:`CheckSpec` with a stable code, severity, front-end, docstring
and a pair of golden snippets (one minimal *triggering* example and one
non-triggering *near-miss*) that the registry self-test executes. Code
ranges mirror the two front-ends:

- ``PDT1xx`` — tracer-safety checks over the **Python AST** (run before
  ``jit.to_static`` conversion; see ``ast_checks.py``),
- ``PDT2xx`` — program-level checks over the **traced jaxpr / lowered
  IR** (run after capture; see ``ir_checks.py``). A handful of PDT2xx
  codes fire at *runtime* from inside compiled programs (frontend
  ``"runtime"``) — same registry, different reporting site.
"""
from __future__ import annotations

import ast
import dataclasses
import enum
import re
from typing import Callable, Optional


def decorator_name(dec) -> Optional[str]:
    """Best-effort name of a decorator expression: ``"to_static"`` for
    ``@to_static`` / ``@paddle.jit.to_static`` / ``@to_static(...)``;
    ``None`` when the expression is not a (dotted) name. Single source
    of truth for decorator matching across the engine, the AST checks
    and dy2static."""
    d = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(d, ast.Attribute):
        return d.attr
    if isinstance(d, ast.Name):
        return d.id
    return None


class Severity(enum.IntEnum):
    """Finding severity; ordering is meaningful (gates compare >=)."""

    NOTE = 0
    WARN = 1
    ERROR = 2

    def __str__(self):
        return self.name.lower()


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: a coded, located, human-readable lint message."""

    code: str
    severity: Severity
    message: str
    file: str = "<unknown>"
    line: int = 0
    col: int = 0

    def format(self) -> str:
        return (f"{self.file}:{self.line}:{self.col}: {self.code} "
                f"[{self.severity}] {self.message}")


@dataclasses.dataclass(frozen=True)
class CheckSpec:
    """Registry entry for one diagnostic code."""

    code: str           # e.g. "PDT101"
    name: str           # kebab-case slug, e.g. "host-sync-in-jit"
    severity: Severity
    frontend: str       # "ast" | "ir" | "runtime"
    doc: str            # what the check flags and why it matters
    example: str        # minimal source that triggers the code
    near_miss: str      # minimal source that must NOT trigger it
    func: Optional[Callable] = None  # the check (None for runtime codes)
    scope: str = "jit"  # "jit": functions in a jit context (the default)
    #                     "eager": functions NOT in a jit context (e.g.
    #                     PDT108's eager train-loop advice)
    #                     "any": both (e.g. PDT111's dequant-then-matmul
    #                     advice — the unfused pattern wastes HBM either
    #                     way)


_CODE_RE = re.compile(r"^PDT[12]\d\d$")
REGISTRY: dict[str, CheckSpec] = {}


def register(code: str, name: str, severity: Severity, frontend: str, *,
             example: str, near_miss: str, scope: str = "jit"):
    """Decorator registering a check function under ``code``.

    The function's docstring becomes the registry doc. AST checks take
    ``(fndef, ctx)`` and yield ``(node, message)``; IR checks take
    ``(closed_jaxpr, ctx)`` and yield ``(message, eqn_or_None)``.
    ``scope`` (AST checks only): "jit" runs over functions in a jit
    context, "eager" over functions outside one, "any" over both.
    """
    if not _CODE_RE.match(code):
        raise ValueError(f"diagnostic code {code!r} must match PDT[12]xx")
    if frontend not in ("ast", "ir", "runtime"):
        raise ValueError(f"unknown frontend {frontend!r}")
    if (frontend == "ast") != code.startswith("PDT1"):
        raise ValueError(f"{code}: PDT1xx codes are AST checks, "
                         f"PDT2xx are IR/runtime checks")
    if scope not in ("jit", "eager", "any"):
        raise ValueError(f"unknown scope {scope!r}")

    def deco(fn):
        if code in REGISTRY:
            raise ValueError(f"duplicate diagnostic code {code}")
        if not (fn.__doc__ or "").strip():
            raise ValueError(f"{code}: check must carry a docstring")
        REGISTRY[code] = CheckSpec(
            code=code, name=name, severity=severity, frontend=frontend,
            doc=fn.__doc__.strip(), example=example, near_miss=near_miss,
            func=fn, scope=scope)
        return fn
    return deco


def register_runtime(code: str, name: str, severity: Severity, doc: str, *,
                     example: str, near_miss: str) -> CheckSpec:
    """Register a runtime-reported code (no check function; producers
    call ``engine.report_runtime`` with this code)."""
    if code in REGISTRY:
        raise ValueError(f"duplicate diagnostic code {code}")
    if not _CODE_RE.match(code) or code.startswith("PDT1"):
        raise ValueError(f"runtime codes live in the PDT2xx range")
    spec = CheckSpec(code=code, name=name, severity=severity,
                     frontend="runtime", doc=doc.strip(),
                     example=example, near_miss=near_miss, func=None)
    REGISTRY[code] = spec
    return spec


def spec(code: str) -> CheckSpec:
    return REGISTRY[code]


# --------------------------------------------------------------------------
# suppression
#
# Three layers, all consulted at diagnostic-filter time:
#   1. the ``# pdtpu: noqa`` / ``# pdtpu: noqa[PDT101,...]`` line pragma
#      (checked against the source line a finding points at),
#   2. the dynamic ``suppress(...)`` context manager (thread-local),
#   3. the ``@suppress(...)`` decorator form, which TAGS the function
#      (``__pdtpu_suppress__``) so lint run on it later — e.g. at
#      to_static capture time — honors the codes without needing an
#      active context.
# --------------------------------------------------------------------------

_PRAGMA_RE = re.compile(
    r"#\s*pdtpu:\s*noqa(?:\[\s*([A-Za-z0-9_,\s]+?)\s*\])?")


def pragma_suppressed(source_line: str, code: str) -> bool:
    """True when ``source_line`` carries a noqa pragma covering ``code``."""
    m = _PRAGMA_RE.search(source_line or "")
    if not m:
        return False
    if m.group(1) is None:
        return True  # bare ``# pdtpu: noqa`` silences everything
    codes = {c.strip().upper() for c in m.group(1).split(",")}
    return code.upper() in codes


# Process-global, NOT thread-local: runtime diagnostics (PDT206) come
# out of ``jax.debug.callback``, which async backends may run on a
# runtime thread — a thread-local stack would make ``suppress`` (and
# ``engine.collect``) silently miss those reports.
class _SuppressState:
    def __init__(self):
        # (token, codes) frames; the token gives each entry an identity
        # so exits remove exactly their own frame
        self.stack: list[tuple[object, frozenset]] = []


_suppress_state = _SuppressState()


def active_suppressions() -> frozenset:
    out: set[str] = set()
    for _, s in _suppress_state.stack:
        out |= s
    return frozenset(out)


class suppress:
    """``with analysis.suppress("PDT101"): ...`` silences the codes for
    the dynamic extent (process-wide — see ``_SuppressState``);
    ``@analysis.suppress("PDT101")`` tags a function so any later lint
    of it skips the codes. Bare ``suppress()`` silences every code."""

    def __init__(self, *codes: str):
        self.codes = frozenset(c.upper() for c in codes) or \
            frozenset(REGISTRY)
        self._tokens: list = []

    def __enter__(self):
        # a fresh token per entry, kept in a per-instance LIFO so
        # nested re-entry of one instance pairs each exit with its own
        # frame (a single slot would leak the outer frame forever)
        token = object()
        self._tokens.append(token)
        _suppress_state.stack.append((token, self.codes))
        return self

    def __exit__(self, *exc):
        token = self._tokens.pop() if self._tokens else None
        stack = _suppress_state.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is token:
                del stack[i]
                break
        return False

    def __call__(self, fn):
        prev = getattr(fn, "__pdtpu_suppress__", frozenset())
        fn.__pdtpu_suppress__ = frozenset(prev) | self.codes
        return fn

"""IR front-end: jaxpr/lowered-program checks (``PDT2xx``).

These run over the *traced* program — the ClosedJaxpr a ``to_static``
capture produced (or any jaxpr handed to ``analysis.check_jaxpr``) —
and flag hazards only visible after tracing: dtype promotion the source
never spelled out, blocking host callbacks, buffers that could be
donated but are not, computation that is traced but never used, and
weak-typed inputs that fork the compile cache.

A check is a generator ``check(closed_jaxpr, ctx) -> (message, eqn)``
(``eqn`` may be ``None`` when the finding is program-level); ``ctx``
carries ``donated`` (invar indices), ``n_explicit_args`` and ``where``.
"""
from __future__ import annotations

from .registry import Severity, register, register_runtime

_WIDE_DTYPES = ("float64", "complex128")
_BLOCKING_CALLBACKS = {"pure_callback", "io_callback"}


def _all_eqns(jaxpr):
    """Eqns of ``jaxpr`` and every sub-jaxpr (cond/while/scan bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            sub = getattr(v, "jaxpr", None)  # ClosedJaxpr
            if sub is not None and hasattr(sub, "eqns"):
                yield from _all_eqns(sub)
            elif hasattr(v, "eqns"):         # bare Jaxpr
                yield from _all_eqns(v)
            elif isinstance(v, (list, tuple)):
                for b in v:
                    sub = getattr(b, "jaxpr", None)
                    if sub is not None and hasattr(sub, "eqns"):
                        yield from _all_eqns(sub)


def _aval_str(aval) -> str:
    try:
        return (f"{aval.dtype}[{','.join(str(d) for d in aval.shape)}]")
    except Exception:
        return str(aval)


@register(
    "PDT201", "f64-promotion", Severity.WARN, "ir",
    example="""
import jax
import jax.numpy as jnp

with jax.experimental.enable_x64():
    JAXPR = jax.make_jaxpr(
        lambda x: x.astype(jnp.float64) * 2.0)(jnp.ones((4,), jnp.float32))
""",
    near_miss="""
import jax
import jax.numpy as jnp

with jax.experimental.enable_x64():
    JAXPR = jax.make_jaxpr(lambda x: x * 2.0)(jnp.ones((4,), jnp.float32))
""")
def check_f64_promotion(closed, ctx):
    """A float64/complex128 value appearing in a program whose inputs
    are all narrower is an unintended promotion: on TPU f64 is emulated
    (~10x slower) and doubles HBM traffic. Usually a stray Python float
    interacting with x64 mode or an explicit astype."""
    jaxpr = closed.jaxpr
    if any(str(getattr(v.aval, "dtype", "")) in _WIDE_DTYPES
           for v in jaxpr.invars):
        return  # caller fed f64 in on purpose
    for eqn in _all_eqns(jaxpr):
        for v in eqn.outvars:
            if str(getattr(v.aval, "dtype", "")) in _WIDE_DTYPES:
                yield (f"{eqn.primitive} produces {_aval_str(v.aval)} "
                       f"from narrower inputs (f64 is emulated on TPU); "
                       f"check for stray Python floats or astype",
                       eqn)
                return  # promotion cascades; first site is the root


@register(
    "PDT202", "host-callback-in-program", Severity.WARN, "ir",
    example="""
import jax
import jax.numpy as jnp
import numpy as np


def f(x):
    return jax.pure_callback(
        lambda v: np.asarray(v) * 2,
        jax.ShapeDtypeStruct((4,), jnp.float32), x)


JAXPR = jax.make_jaxpr(f)(jnp.ones((4,), jnp.float32))
""",
    near_miss="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(lambda x: x * 2)(jnp.ones((4,), jnp.float32))
""")
def check_host_callback(closed, ctx):
    """A blocking host callback (``pure_callback``/``io_callback``)
    inside a compiled program forces a device->host->device round trip
    every step — on a network-attached TPU that is milliseconds per
    call. Async ``debug_callback`` is exempt."""
    for eqn in _all_eqns(closed.jaxpr):
        if str(eqn.primitive) in _BLOCKING_CALLBACKS:
            yield (f"{eqn.primitive} embeds a blocking host round trip "
                   f"in the compiled program (per-step device->host "
                   f"transfer); keep the computation on device or hoist "
                   f"the callback out of the step", eqn)


@register(
    "PDT203", "undonated-state-buffer", Severity.NOTE, "ir",
    example="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(lambda w: w + 1.0)(jnp.ones((8,), jnp.float32))
DONATED = frozenset()
N_ARGS = 0
""",
    near_miss="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(lambda w: w + 1.0)(jnp.ones((8,), jnp.float32))
DONATED = frozenset({0})
N_ARGS = 0
""")
def check_undonated_state(closed, ctx):
    """A captured state input whose shape/dtype matches an output and is
    not donated costs a full extra buffer of HBM: XLA cannot reuse the
    input allocation for the result. The jit capture donates written
    state automatically — this flags programs built outside that path."""
    jaxpr = closed.jaxpr
    out_count: dict[tuple, int] = {}
    for v in jaxpr.outvars:
        key = (tuple(getattr(v.aval, "shape", ())),
               str(getattr(v.aval, "dtype", "")))
        out_count[key] = out_count.get(key, 0) + 1
    for i in sorted(ctx.donated):
        if i < len(jaxpr.invars):
            v = jaxpr.invars[i]
            key = (tuple(getattr(v.aval, "shape", ())),
                   str(getattr(v.aval, "dtype", "")))
            if out_count.get(key, 0) > 0:
                out_count[key] -= 1
    for i, v in enumerate(jaxpr.invars):
        if i < ctx.n_explicit_args or i in ctx.donated:
            continue  # caller-owned args are never donatable
        key = (tuple(getattr(v.aval, "shape", ())),
               str(getattr(v.aval, "dtype", "")))
        if out_count.get(key, 0) > 0:
            out_count[key] -= 1
            yield (f"state input #{i} ({_aval_str(v.aval)}) matches an "
                   f"output but is not donated: one extra buffer of HBM "
                   f"held across the step", None)


@register(
    "PDT204", "dead-computation", Severity.NOTE, "ir",
    example="""
import jax
import jax.numpy as jnp


def f(x):
    unused = jnp.sin(x) @ jnp.cos(x)
    return x * 2


JAXPR = jax.make_jaxpr(f)(jnp.ones((8, 8), jnp.float32))
""",
    near_miss="""
import jax
import jax.numpy as jnp


def f(x):
    y = jnp.sin(x) @ jnp.cos(x)
    return x * 2 + y


JAXPR = jax.make_jaxpr(f)(jnp.ones((8, 8), jnp.float32))
""")
def check_dead_computation(closed, ctx):
    """Traced computation whose results never reach an output. XLA DCEs
    it before execution, so it costs compile time rather than step time
    — but it almost always marks a bug: a loss term, metric or update
    the author believes is live and is not."""
    jaxpr = closed.jaxpr
    used = set()
    for v in jaxpr.outvars:
        if hasattr(v, "count"):
            used.add(v)
    dead = []
    for eqn in reversed(jaxpr.eqns):
        effects = getattr(eqn, "effects", None)
        live = bool(effects) or any(v in used for v in eqn.outvars)
        if live:
            for v in eqn.invars:
                if hasattr(v, "count"):   # skip Literals
                    used.add(v)
        else:
            dead.append(eqn)
    for eqn in list(reversed(dead))[:5]:
        yield (f"result of {eqn.primitive} is never used (dead "
               f"computation traced into the program); a loss term or "
               f"update may be silently dropped", eqn)


@register(
    "PDT205", "weak-type-input", Severity.NOTE, "ir",
    example="""
import jax

JAXPR = jax.make_jaxpr(lambda x: x * 2.0)(3.0)
""",
    near_miss="""
import jax
import jax.numpy as jnp

JAXPR = jax.make_jaxpr(lambda x: x * 2.0)(jnp.ones((), jnp.float32))
""")
def check_weak_type(closed, ctx):
    """A weak-typed program input (a Python scalar captured as an
    operand) promotes differently from a committed dtype: the same
    function retraces — and recompiles — when the scalar later arrives
    as a real array. Commit the dtype at the boundary."""
    flagged = 0
    for i, v in enumerate(closed.jaxpr.invars):
        if getattr(v.aval, "weak_type", False):
            yield (f"program input #{i} ({_aval_str(v.aval)}) is "
                   f"weak-typed (python scalar); dtype promotion differs "
                   f"from committed arrays and forks the compile cache",
                   None)
            flagged += 1
            if flagged >= 5:
                return


# --------------------------------------------------------------------------
# runtime-reported codes: producers inside compiled programs call
# ``engine.report_runtime(code, ...)``; the registry entry gives them a
# severity, a doc, and golden snippets the self-test executes for real.
# --------------------------------------------------------------------------

register_runtime(
    "PDT206", "while-trip-bound-truncation", Severity.WARN,
    """The differentiable while_loop lowering (bounded masked scan; XLA
    has no reverse-mode while) hit its trip bound with the predicate
    still true: the result is TRUNCATED. Raise ``max_trip_count`` or
    ``FLAGS_while_grad_max_trip_count``.""",
    example="""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.static.nn import while_loop

w = paddle.to_tensor(np.array([1.0], np.float32))
w.stop_gradient = False


@paddle.jit.to_static
def fn(x):
    w.clear_grad()
    i, y = while_loop(lambda i, y: i < 100.0,
                      lambda i, y: (i + 1.0, y * w),
                      [paddle.to_tensor(np.float32(0.0)), x],
                      max_trip_count=4)
    loss = y.sum()
    loss.backward()
    return loss


with analysis.collect() as DIAGS:
    fn(paddle.to_tensor(np.array([2.0], np.float32)))
""",
    near_miss="""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.static.nn import while_loop

w = paddle.to_tensor(np.array([1.0], np.float32))
w.stop_gradient = False


@paddle.jit.to_static
def fn(x):
    w.clear_grad()
    i, y = while_loop(lambda i, y: i < 3.0,
                      lambda i, y: (i + 1.0, y * w),
                      [paddle.to_tensor(np.float32(0.0)), x],
                      max_trip_count=8)
    loss = y.sum()
    loss.backward()
    return loss


with analysis.collect() as DIAGS:
    fn(paddle.to_tensor(np.array([2.0], np.float32)))
""")

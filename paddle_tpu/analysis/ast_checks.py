"""AST front-end: tracer-safety checks (``PDT1xx``).

These run over a function's source *before* ``jit.to_static`` conversion
and flag the patterns the dy2static rewriter either silently falls back
on (graph breaks) or that trace to something the author did not mean
(host syncs baked into the compiled program, trace-time-only side
effects, host randomness captured as a constant).

A check is a generator ``check(fndef, ctx) -> (node, message)`` where
``fndef`` is the (possibly nested) ``ast.FunctionDef`` being linted in a
jit context and ``ctx`` carries filename/source. Severity and code come
from the registry entry.
"""
from __future__ import annotations

import ast
import copy

from ..core.state import MEGAKERNEL_OFF_SPELLINGS, \
    PREFIX_CACHE_OFF_SPELLINGS
from .registry import Severity, decorator_name, register

_HOST_SYNC_METHODS = {"numpy", "item", "tolist"}
_MUTATORS = {"append", "extend", "insert", "remove", "clear", "update",
             "add", "setdefault"}
_HOST_ENTROPY_ROOTS = {"random", "time"}


def _walk_fn(fndef):
    """Walk the function's own scope only — nested defs are NOT
    descended into: the engine lints every nested function as its own
    jit scope, so a nested def's suppression (decorator tag, def-line
    pragma) governs its own findings."""
    stack = [fndef]
    while stack:
        node = stack.pop()
        if node is not fndef and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _dotted(node) -> str | None:
    """``a.b.c`` attribute chain -> ``"a.b.c"`` (None if not a chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@register(
    "PDT101", "host-sync-in-jit", Severity.WARN, "ast",
    example="""
import paddle_tpu as paddle

@paddle.jit.to_static
def step(x):
    y = x * 2
    return y.numpy()
""",
    near_miss="""
def step(x):
    y = x * 2
    return y.numpy()
""")
def check_host_sync(fndef, ctx):
    """``.numpy()``/``.item()``/``.tolist()`` or ``float()``/``int()``/
    ``bool()`` on a traced value inside a jit function blocks on a
    device->host transfer and graph-breaks the capture — the single
    costliest silent hazard on a network-attached TPU."""
    for node in _walk_fn(fndef):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _HOST_SYNC_METHODS \
                and not node.args and not node.keywords:
            yield node, (f".{f.attr}() inside a jit function forces a "
                         f"device->host sync (graph break); keep the "
                         f"value on device or move the call outside "
                         f"to_static")
        elif isinstance(f, ast.Name) and f.id in ("float", "int", "bool") \
                and len(node.args) == 1 and not node.keywords \
                and isinstance(node.args[0], ast.Call) \
                and isinstance(node.args[0].func, ast.Attribute):
            # only the tensor-shaped pattern float(x.sum()): a bare
            # float(name) is usually a plain Python scalar conversion
            yield node, (f"{f.id}() on a tensor expression forces a "
                         f"device->host sync inside a jit function; use "
                         f"tensor ops (astype/comparison) instead")


@register(
    "PDT102", "print-in-traced-code", Severity.NOTE, "ast",
    example="""
from paddle_tpu.jit import to_static

@to_static
def step(x):
    print(x)
    return x * 2
""",
    near_miss="""
from paddle_tpu.jit import to_static

@to_static
def step(x):
    log(x)
    return x * 2
""")
def check_print(fndef, ctx):
    """``print`` inside traced code runs at trace time only: it fires
    once per compile, not once per step, and printing a tensor shows a
    tracer, not values. Use a host callback or move it out of jit."""
    for node in _walk_fn(fndef):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "print":
            yield node, ("print() in traced code runs once per compile, "
                         "not per step; it will show tracers, not values")


@register(
    "PDT103", "global-write-in-jit", Severity.WARN, "ast",
    example="""
import paddle_tpu as paddle

@paddle.jit.to_static
def step(x):
    global counter
    counter = counter + 1
    return x * 2
""",
    near_miss="""
import paddle_tpu as paddle

@paddle.jit.to_static
def step(x):
    counter = 1
    return x * counter
""")
def check_global_write(fndef, ctx):
    """Writing a ``global`` from a jit function is a trace-time side
    effect: the write happens once per compile, and replaying the cached
    program never updates it again."""
    for node in _walk_fn(fndef):
        if isinstance(node, ast.Global):
            yield node, (f"global write ({', '.join(node.names)}) in a "
                         f"jit function happens at trace time only; the "
                         f"cached program will not repeat it")


@register(
    "PDT104", "mutation-in-converted-branch", Severity.NOTE, "ast",
    example="""
import paddle_tpu as paddle

@paddle.jit.to_static
def step(x, acc):
    if x.mean() > 0:
        acc.append(x)
    return x * 2
""",
    near_miss="""
import paddle_tpu as paddle

@paddle.jit.to_static
def step(x, acc):
    acc.append(x)
    if x.mean() > 0:
        x = x + 1
    return x * 2
""")
def check_branch_mutation(fndef, ctx):
    """Container mutation (``.append``/``.update``/...) inside an
    ``if``/``while`` body: if the predicate is a tensor, dy2static
    traces BOTH branches, so the mutation runs even when its branch is
    not taken — and runs once per trace, not per step."""

    compound = (ast.If, ast.While, ast.For, ast.With, ast.Try,
                ast.AsyncFor, ast.AsyncWith)

    def scan(stmts, in_branch):
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if in_branch and not isinstance(s, compound):
                for node in ast.walk(s):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute) \
                            and node.func.attr in _MUTATORS:
                        yield (node,
                               f".{node.func.attr}() inside a converted "
                               f"branch replays at trace time for both "
                               f"sides of the predicate")
            branch_here = in_branch or isinstance(s, (ast.If, ast.While))
            for blk in _stmt_blocks(s):
                yield from scan(blk, branch_here)

    yield from scan(fndef.body, False)


def _stmt_blocks(s):
    for attr in ("body", "orelse", "finalbody"):
        blk = getattr(s, attr, None)
        if isinstance(blk, list) and blk and isinstance(blk[0], ast.stmt):
            yield blk
    for h in getattr(s, "handlers", []) or []:
        yield h.body


@register(
    "PDT105", "graph-break-escape", Severity.WARN, "ast",
    example="""
import paddle_tpu as paddle

@paddle.jit.to_static
def step(x):
    if x.mean() > 0:
        with open("/tmp/f") as f:
            return x * 2
    return x
""",
    near_miss="""
import paddle_tpu as paddle

@paddle.jit.to_static
def step(x):
    if x.mean() > 0:
        return x * 2
    return x
""")
def check_graph_break_escape(fndef, ctx):
    """A control-flow site dy2static cannot convert (``return``/``break``
    beyond what the escape-elimination passes handle, ``del``, ``yield``,
    loop ``else``) is silently left as plain Python: a tensor predicate
    there graph-breaks the whole capture. This check replays the real
    dy2static transformer pipeline and flags the sites that survive it
    unconverted."""
    from ..jit.dy2static import (_BreakContinueEliminator, _ForEachDesugar,
                                 _eliminate_returns, _has_escape,
                                 _is_range_for, _visit_body,
                                 _walk_in_scope)
    fd = copy.deepcopy(fndef)
    try:
        _visit_body(_ForEachDesugar(), fd)
        _eliminate_returns(fd)
        _visit_body(_BreakContinueEliminator(), fd)
        ast.fix_missing_locations(fd)
    except Exception:
        return  # conversion machinery declined outright; PDT107 covers it
    seen = set()
    for s in fd.body:
        for node in _walk_in_scope(s):
            broke = False
            if isinstance(node, ast.If):
                broke = _has_escape(node.body) or _has_escape(node.orelse)
            elif isinstance(node, ast.While):
                broke = bool(node.orelse) or _has_escape(node.body,
                                                         loop_ctx=True)
            elif isinstance(node, ast.For):
                broke = _is_range_for(node) and _has_escape(node.body,
                                                            loop_ctx=True)
            if broke and (node.lineno, node.col_offset) not in seen:
                seen.add((node.lineno, node.col_offset))
                kind = type(node).__name__.lower()
                yield node, (f"`{kind}` block contains an escape "
                             f"(return/break/del/yield past what escape "
                             f"elimination handles): dy2static leaves it "
                             f"as plain Python — a tensor predicate here "
                             f"graph-breaks the capture")


@register(
    "PDT106", "host-entropy-in-jit", Severity.WARN, "ast",
    example="""
import random
import paddle_tpu as paddle

@paddle.jit.to_static
def step(x):
    return x * random.random()
""",
    near_miss="""
import random
import paddle_tpu as paddle

def make_noise():
    return random.random()

@paddle.jit.to_static
def step(x):
    return x * 2.0
""")
def check_host_entropy(fndef, ctx):
    """``random.*`` / ``time.*`` / ``np.random.*`` in traced code is
    evaluated once at trace time and baked into the compiled program as
    a constant — every subsequent step reuses the same 'random' value.
    Use ``paddle.seed`` + framework random ops instead."""
    for node in _walk_fn(fndef):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if not dotted:
            continue
        parts = dotted.split(".")
        hostile = (parts[0] in _HOST_ENTROPY_ROOTS and len(parts) > 1) or \
            (parts[0] in ("np", "numpy") and len(parts) > 2
             and parts[1] == "random")
        if hostile:
            yield node, (f"{dotted}() runs at trace time: the value is "
                         f"baked into the compiled program as a constant "
                         f"(same 'random' number every step)")


@register(
    "PDT107", "unconvertible-function", Severity.WARN, "ast",
    example="""
import paddle_tpu as paddle

def outer():
    k = 0

    @paddle.jit.to_static
    def step(x):
        nonlocal k
        k += 1
        return x * 2
    return step
""",
    near_miss="""
import paddle_tpu as paddle

def outer():
    k = 2

    @paddle.jit.to_static
    def step(x):
        return x * k
    return step
""")
def check_unconvertible(fndef, ctx):
    """Function-level features that make dy2static decline the WHOLE
    function (``nonlocal`` writes, ``__name``-mangled attributes,
    decorators it cannot strip): tensor control flow inside then always
    falls back to eager with no conversion at all."""
    from ..jit.dy2static import _has_mangled_names
    for node in _walk_fn(fndef):
        if isinstance(node, ast.Nonlocal):
            yield node, (f"nonlocal ({', '.join(node.names)}) makes "
                         f"dy2static decline the whole function (re-exec "
                         f"cannot share closure cells for writes)")
    if _has_mangled_names(fndef):
        yield fndef, ("__name-mangled attribute access does not survive "
                      "dy2static's re-exec; the function is left "
                      "unconverted")
    if ctx.decorated:
        for dec in fndef.decorator_list:
            name = decorator_name(dec)
            if name not in ("to_static", "suppress"):
                yield dec, (f"decorator @{name or '<expr>'} prevents "
                            f"dy2static conversion (stripping it would "
                            f"change behavior)")


@register(
    "PDT108", "eager-optimizer-loop", Severity.NOTE, "ast", scope="eager",
    example="""
import paddle_tpu as paddle

def train(model, opt, batches):
    for x, y in batches:
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
""",
    near_miss="""
import paddle_tpu as paddle

@paddle.jit.to_static
def train_step(model, opt, x, y):
    loss = ((model(x) - y) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    return loss
""")
def check_eager_optimizer_loop(fndef, ctx):
    """A training loop (``backward()`` + ``.step()`` in the same loop
    body) in a function NOT under ``jit.to_static``: every iteration
    dispatches the whole step eagerly — the optimizer update alone is
    O(params) host dispatches on the per-param path and still O(buckets)
    on the fused path, vs ZERO once the step is captured (and one
    launch per K steps with ``Model.fit(window=K)`` / ``WindowRunner``).
    Note-level advice, not an error."""
    for node in _walk_fn(fndef):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        has_backward = False
        step_node = None
        # own-scope walk of the loop body: nested defs are linted as
        # their own scope (same contract as _walk_fn), so a closure
        # merely DEFINED in the loop doesn't flag the outer function
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(sub))
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute):
                if sub.func.attr == "backward":
                    has_backward = True
                elif sub.func.attr in ("step", "minimize") and \
                        step_node is None:
                    step_node = sub
        if has_backward and step_node is not None:
            yield step_node, (
                "optimizer step inside an eager Python loop: every "
                "batch pays per-step host dispatch — wrap the train "
                "step in @paddle.jit.to_static (or use "
                "Model.fit(window=K)) so the loop body compiles to one "
                "program")


# constructor kwargs that bound a serving engine's overload behavior
# (inference/engine.py): any one of them makes PDT109 stand down.
# dispatch_retries is deliberately NOT here — it bounds transient
# retry, not queue growth or request lifetime.
_ENGINE_BOUND_KWARGS = {"max_queue", "queue_policy",
                        "default_deadline_ms"}


@register(
    "PDT109", "unbounded-serving-run", Severity.NOTE, "ast",
    scope="eager",
    example="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    eng = ContinuousBatchingEngine(model, max_slots=4)
    for p in prompts:
        eng.add_request(p, 32)
    return eng.run()
""",
    near_miss="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    eng = ContinuousBatchingEngine(model, max_slots=4, max_queue=64,
                                   queue_policy="reject")
    for p in prompts:
        eng.add_request(p, 32)
    return eng.run()
""")
def check_unbounded_serving_run(fndef, ctx):
    """``ContinuousBatchingEngine.run()`` on an engine constructed with
    no overload policy (no ``max_queue``/``queue_policy`` bound, no
    ``default_deadline_ms`` TTL): fine in the lab, but under real
    traffic an unbounded queue plus deadline-free requests means
    overload shows up as unbounded memory and latency instead of
    rejections/timeouts.  Configure the bounds (or the ``serving_*``
    flags in ``core/state.py``).  Note-level advice, not an error."""
    # pass 1: every assignment to a name, in source order — a name is
    # suspect at a .run() site iff its latest PRECEDING assignment is
    # an engine constructed without any bound (so rebinding the name
    # to anything else clears it; _walk_fn order is not source order)
    assigns: dict[str, list[tuple[tuple[int, int], bool]]] = {}
    for node in _walk_fn(fndef):
        if isinstance(node, ast.Assign):
            is_engine = (isinstance(node.value, ast.Call)
                         and (_dotted(node.value.func) or "")
                         .split(".")[-1] == "ContinuousBatchingEngine")
            suspect = is_engine and not any(
                kw.arg in _ENGINE_BOUND_KWARGS
                for kw in node.value.keywords)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    assigns.setdefault(tgt.id, []).append(
                        ((node.lineno, node.col_offset), suspect))
    for hist in assigns.values():
        hist.sort()

    def _unbounded_at(name, pos):
        last = None
        for apos, suspect in assigns.get(name, ()):
            if apos > pos:
                break
            last = suspect
        return bool(last)

    for node in _walk_fn(fndef):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr != "run":
            continue
        base = node.func.value
        chained = (isinstance(base, ast.Call)
                   and (_dotted(base.func) or "").split(".")[-1]
                   == "ContinuousBatchingEngine"
                   and not any(kw.arg in _ENGINE_BOUND_KWARGS
                               for kw in base.keywords))
        named = (isinstance(base, ast.Name)
                 and _unbounded_at(base.id, (node.lineno,
                                             node.col_offset)))
        if chained or named:
            yield node, (
                "ContinuousBatchingEngine.run() with no overload "
                "policy configured: pass max_queue/queue_policy "
                "and/or default_deadline_ms (or set the serving_* "
                "flags) so heavy traffic degrades to rejections/"
                "timeouts instead of unbounded queues")


@register(
    "PDT111", "dequant-then-matmul", Severity.NOTE, "ast", scope="any",
    example="""
from paddle_tpu.quantization import weight_dequantize

def serve(x, qw, scale):
    w = weight_dequantize(qw, scale)
    return x @ w
""",
    near_miss="""
from paddle_tpu.quantization import (weight_dequantize,
                                     weight_only_linear)

def serve(x, qw, scale):
    probe = weight_dequantize(qw, scale)   # inspected, never matmul'd
    shape = probe.shape
    return weight_only_linear(x, qw, scale), shape
""")
def check_dequant_then_matmul(fndef, ctx):
    """``weight_dequantize`` whose result feeds a matmul (``@``,
    ``matmul(...)``, ``linear(...)``): the dequantized weight is
    materialized at FLOAT width before the matmul reads it — eagerly
    that is a full extra HBM round-trip at 4x the quantized bytes, and
    even under jit it gambles on XLA fusing the pair.
    ``quantization.weight_only_linear`` (the Pallas fused
    dequant-matmul, ``ops/pallas/quant_matmul.py``) reads the weights
    at int8 width and applies the scale after the K reduction.
    Note-level advice, not an error."""
    # source-position-aware name tracking (the PDT109 hardening): a
    # name is a dequant result at a use site iff its latest PRECEDING
    # assignment was a weight_dequantize call — rebinding clears it,
    # and a later dequant assignment does not taint earlier uses
    assigns: dict[str, list[tuple[tuple[int, int], bool]]] = {}
    for node in _walk_fn(fndef):
        if isinstance(node, ast.Assign):
            is_dq = (isinstance(node.value, ast.Call)
                     and (_dotted(node.value.func) or "")
                     .split(".")[-1] == "weight_dequantize")
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    assigns.setdefault(tgt.id, []).append(
                        ((node.lineno, node.col_offset), is_dq))
    for hist in assigns.values():
        hist.sort()

    def _is_dequant(arg, pos):
        if isinstance(arg, ast.Name):
            last = None
            for apos, is_dq in assigns.get(arg.id, ()):
                if apos >= pos:
                    break
                last = is_dq
            return bool(last)
        return (isinstance(arg, ast.Call)
                and (_dotted(arg.func) or "").split(".")[-1]
                == "weight_dequantize")

    msg = ("matmul over a weight_dequantize result materializes the "
           "float weights in HBM before the matmul re-reads them; "
           "weight_only_linear fuses the dequant into the matmul at "
           "int8 read width")
    for node in _walk_fn(fndef):
        if not isinstance(node, (ast.BinOp, ast.Call)):
            continue
        pos = (node.lineno, node.col_offset)
        if isinstance(node, ast.BinOp) and isinstance(node.op,
                                                      ast.MatMult):
            if _is_dequant(node.left, pos) or _is_dequant(node.right,
                                                          pos):
                yield node, msg
        elif isinstance(node, ast.Call) \
                and (_dotted(node.func) or "").split(".")[-1] \
                in ("matmul", "linear") \
                and any(_is_dequant(a, pos) for a in node.args
                        + [kw.value for kw in node.keywords]):
            yield node, msg


# call names that read as "logging": the sink whose arguments PDT112
# scans for device->host syncs. Bare names take only the unambiguous
# spellings; dotted chains match logger METHOD names on the last part
# (logger.info / self.log.debug) — deliberately NOT "log", which as an
# attribute is overwhelmingly math (math.log/np.log/jnp.log), where
# the sync is a real data dependency the check must not flag.
_LOG_SINK_BARE = {"print", "log"}
_LOG_SINK_METHODS = {"info", "debug", "warning", "error", "critical",
                     "exception"}
_HOST_SYNC_LOOP_METHODS = {"item", "numpy", "tolist"}


def _host_sync_desc(node):
    """The device->host sync expression a log-call argument performs
    (``float()`` / ``.item()`` / ``.numpy()`` / ``.tolist()``), or
    None.  Shared by PDT112 and PDT115 so the two checks can never
    disagree on what counts as a sync."""
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) \
                and f.attr in _HOST_SYNC_LOOP_METHODS \
                and not node.args and not node.keywords:
            return f".{f.attr}()"
        if isinstance(f, ast.Name) and f.id == "float" \
                and len(node.args) == 1 and not node.keywords:
            return "float()"
    return None


@register(
    "PDT112", "host-sync-in-loop", Severity.NOTE, "ast", scope="eager",
    example="""
import paddle_tpu as paddle

def train(model, batches):
    for x in batches:
        loss = model(x).mean()
        print("loss:", float(loss))
""",
    near_miss="""
import math
import paddle_tpu as paddle

def train(model, batches):
    for x in batches:
        loss = model(x).mean()
        scale = math.log(float(loss))     # math, not logging
        if float(loss) < 0.1:
            break
""")
def check_host_sync_in_loop(fndef, ctx):
    """``float(x)`` / ``x.item()`` / ``x.numpy()`` / ``x.tolist()``
    feeding a logging call (``print`` / ``log.info`` / ...) inside a
    training or serving loop body: each one blocks the host on a
    device->host transfer EVERY iteration, purely to print a number —
    on a network-attached TPU that is a full round-trip per step.
    ``paddle_tpu.observability`` gauges read LAZILY (the value is
    fetched at snapshot/render time, not in the loop), so telemetry
    costs the loop nothing; syncs that feed control flow (early
    stopping on ``float(loss)``) are real data dependencies and are
    not flagged.  Note-level advice, not an error."""
    _sync_desc = _host_sync_desc

    for loop in _walk_fn(fndef):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        # own-scope walk of the loop body (nested defs lint themselves)
        stack = list(ast.iter_child_nodes(loop))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(sub))
            if not isinstance(sub, ast.Call):
                continue
            fname = (_dotted(sub.func) or "").split(".")[-1]
            is_sink = (fname in _LOG_SINK_BARE
                       if isinstance(sub.func, ast.Name)
                       else fname in _LOG_SINK_METHODS)
            if not is_sink:
                continue
            for arg in sub.args + [kw.value for kw in sub.keywords]:
                for inner in ast.walk(arg):
                    desc = _sync_desc(inner)
                    if desc is not None:
                        yield inner, (
                            f"{desc} inside a loop body feeds only "
                            f"{fname}(): that is one device->host sync "
                            f"per iteration spent on logging — record "
                            f"into a paddle_tpu.observability gauge/"
                            f"histogram instead (gauges read lazily at "
                            f"snapshot time, so the loop pays nothing)")
                        break  # one finding per log-call argument


@register(
    "PDT113", "greedy-spec-sampling-mismatch", Severity.NOTE, "ast",
    scope="eager",
    example="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    eng = ContinuousBatchingEngine(model, max_slots=8, spec_decode=True,
                                   spec_temperature=0.8)
    for p in prompts:
        eng.add_request(p, 32)
    return eng.run()
""",
    near_miss="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    # rejection sampling keeps sampled speculative output lossless
    eng = ContinuousBatchingEngine(model, max_slots=8, spec_decode=True,
                                   spec_temperature=0.8,
                                   spec_rejection_sampling=True)
    for p in prompts:
        eng.add_request(p, 32)
    greedy = ContinuousBatchingEngine(model, max_slots=8,
                                      spec_decode=True)  # greedy: exact
    return eng.run()
""")
def check_greedy_spec_sampling_mismatch(fndef, ctx):
    """A serving engine constructed with ``spec_decode`` on and a
    non-greedy sampler (``spec_temperature > 0``) but WITHOUT
    ``spec_rejection_sampling``: token-equality acceptance against
    sampled target tokens skews the output distribution toward the
    proposer (a draft is kept whenever the sampler happens to agree,
    so proposer-favored continuations are over-represented), which
    silently changes what the model says, not just how fast.  Greedy
    speculative decoding (``spec_temperature = 0``, the default) is
    exact by construction; sampled speculative decoding is exact only
    under the rejection-sampling rule — set
    ``spec_rejection_sampling=True`` (or the
    ``serving_spec_rejection_sampling`` flag) or drop the
    temperature.  Note-level advice, not an error."""

    def _truthy(node):
        return isinstance(node, ast.Constant) and bool(node.value)

    for node in _walk_fn(fndef):
        if not isinstance(node, ast.Call) \
                or (_dotted(node.func) or "").split(".")[-1] \
                != "ContinuousBatchingEngine":
            continue
        kws = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        if _truthy(kws.get("spec_decode")) \
                and _truthy(kws.get("spec_temperature")) \
                and not _truthy(kws.get("spec_rejection_sampling")):
            yield node, (
                "spec_decode with spec_temperature but no "
                "spec_rejection_sampling: greedy token-equality "
                "acceptance under a sampling temperature biases "
                "output toward the proposer — enable "
                "spec_rejection_sampling (lossless speculative "
                "sampling) or decode greedily")


# constant values that disable the engine's prefix cache — the string
# spellings are the engine's case-insensitive parse set
_PREFIX_CACHE_OFF = (False, 0) + PREFIX_CACHE_OFF_SPELLINGS


def _prefix_cache_off(node) -> bool:
    if not isinstance(node, ast.Constant):
        return False
    v = node.value
    if isinstance(v, str):
        v = v.lower()
    return v in _PREFIX_CACHE_OFF


@register(
    "PDT110", "prefix-cache-off-under-load", Severity.NOTE, "ast",
    scope="eager",
    example="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    eng = ContinuousBatchingEngine(model, max_slots=8, max_queue=64,
                                   queue_policy="reject",
                                   prefix_cache=False)
    for p in prompts:
        eng.add_request(p, 32)
    return eng.run()
""",
    near_miss="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    # overload-bounded engine keeps the prefix cache (default on)
    eng = ContinuousBatchingEngine(model, max_slots=8, max_queue=64,
                                   queue_policy="reject")
    for p in prompts:
        eng.add_request(p, 32)
    lab = ContinuousBatchingEngine(model, max_slots=8,
                                   prefix_cache=False)  # lab parity rig
    return eng.run()
""")
def check_prefix_cache_off_under_load(fndef, ctx):
    """A serving engine constructed with the prefix cache explicitly
    DISABLED (``prefix_cache=False``/``'off'``) while overload knobs
    (``max_queue``/``queue_policy``/``default_deadline_ms``) are set:
    the high-traffic configuration those knobs exist for is exactly the
    one that most benefits from cross-request prefix caching — shared
    system prompts stop re-prefilling and preempt-requeue stops
    recomputing work the engine already did, at zero output difference
    (cache hits are bitwise-identical).  Disabling it is legitimate for
    parity rigs and memory-ceiling experiments, hence note-level
    advice, not an error."""
    for node in _walk_fn(fndef):
        if not isinstance(node, ast.Call) \
                or (_dotted(node.func) or "").split(".")[-1] \
                != "ContinuousBatchingEngine":
            continue
        kws = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        if _prefix_cache_off(kws.get("prefix_cache")) \
                and any(k in _ENGINE_BOUND_KWARGS for k in kws):
            yield node, (
                "engine bounded for overload (max_queue/queue_policy/"
                "default_deadline_ms) but built with "
                "prefix_cache=False: high-traffic serving is where the "
                "KV prefix cache pays most (shared prompts skip "
                "re-prefill; preempted requests restore instead of "
                "recomputing) and hits are bitwise-identical — drop "
                "the override or set serving_prefix_cache")


@register(
    "PDT114", "serialized-grad-sync", Severity.NOTE, "ast",
    scope="eager",
    example="""
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

def train(model, opt, batches):
    dp = dist.DataParallel(model)
    for x, y in batches:
        loss = ((dp(x) - y) ** 2).mean()
        loss.backward()
        dp.apply_collective_grads()
        opt.step()
        opt.clear_grad()
""",
    near_miss="""
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

def train(model, opt, batches):
    # overlap scheduler: bucket collectives dispatch DURING backward,
    # apply_collective_grads only drains the pending results
    dp = dist.DataParallel(model, overlap_grad_sync=True)
    for x, y in batches:
        loss = ((dp(x) - y) ** 2).mean()
        loss.backward()
        dp.apply_collective_grads()
        opt.step()
        opt.clear_grad()
""")
def check_serialized_grad_sync(fndef, ctx):
    """An explicit blocking gradient all-reduce
    (``apply_collective_grads()`` / ``all_reduce(...grad...)``) between
    ``backward()`` and ``step()`` in an eager train loop: every
    collective waits for the WHOLE backward and the step waits for
    every collective, so communication serializes with compute. The
    bucketed overlap scheduler (``DataParallel(...,
    overlap_grad_sync=True)`` or the ``dp_overlap_grad_sync`` flag)
    dispatches one psum-mean per size-capped bucket as each bucket's
    grads finalize during the backward walk — bitwise-identical
    results, collectives hidden under the remaining backward compute
    (``train.overlap_frac`` in the observability registry shows how
    much). Note-level advice, not an error."""

    def _overlap_enabled():
        # a DataParallel(...) built anywhere in this function with a
        # truthy overlap_grad_sync already overlaps: stand down
        for node in _walk_fn(fndef):
            if isinstance(node, ast.Call) \
                    and (_dotted(node.func) or "").split(".")[-1] \
                    == "DataParallel":
                for kw in node.keywords:
                    if kw.arg == "overlap_grad_sync" \
                            and isinstance(kw.value, ast.Constant) \
                            and bool(kw.value.value):
                        return True
        return False

    if _overlap_enabled():
        return
    for node in _walk_fn(fndef):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        has_backward = False
        sync_node = None
        has_step = False
        # own-scope walk (PDT108 contract): nested defs lint themselves
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(sub))
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)):
                continue
            attr = sub.func.attr
            if attr == "backward":
                has_backward = True
            elif attr == "apply_collective_grads":
                sync_node = sync_node or sub
            elif attr == "all_reduce" and sub.args:
                # all_reduce(p.grad ...) — the hand-rolled per-tensor
                # spelling of the same serialized sync
                a0 = sub.args[0]
                if isinstance(a0, ast.Attribute) and a0.attr == "grad":
                    sync_node = sync_node or sub
            elif attr in ("step", "minimize"):
                has_step = True
        if has_backward and sync_node is not None and has_step:
            yield sync_node, (
                "blocking grad all-reduce between backward() and "
                "step(): the collectives serialize after the whole "
                "backward — construct DataParallel with "
                "overlap_grad_sync=True (or set dp_overlap_grad_sync) "
                "so bucket collectives dispatch as grads finalize "
                "during backward and overlap the remaining compute; "
                "results are bitwise-identical")


# attribute/call spellings that read as "this rank's index" in a rank
# conditional (dist.get_rank() == 0, env.local_rank == 0, hcg rank
# getters) — the guard PDT115 looks for around per-rank logging
_RANK_CALL_NAMES = {"get_rank", "get_local_rank", "get_data_parallel_rank",
                    "get_model_parallel_rank", "get_stage_id"}
_RANK_ATTR_NAMES = {"rank", "local_rank"}


def _is_rank_conditional(test) -> bool:
    """True when an ``if`` test reads this process's rank: a call like
    ``dist.get_rank()`` or an attribute like ``env.local_rank``
    anywhere in the expression."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call):
            name = (_dotted(sub.func) or "").split(".")[-1]
            if name in _RANK_CALL_NAMES:
                return True
        elif isinstance(sub, ast.Attribute) \
                and sub.attr in _RANK_ATTR_NAMES:
            return True
    return False


@register(
    "PDT115", "per-rank-metrics-leak", Severity.NOTE, "ast",
    scope="eager",
    example="""
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

def train(model, batches):
    for x in batches:
        loss = model(x).mean()
        if dist.get_rank() == 0:
            print("rank0 loss:", float(loss))
""",
    near_miss="""
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

def train(model, batches):
    for step, x in enumerate(batches):
        loss = model(x).mean()
        if dist.get_rank() == 0:
            print("step", step)       # python scalar: no device sync
""")
def check_per_rank_metrics_leak(fndef, ctx):
    """``float(x)`` / ``.item()`` / ``.numpy()`` / ``.tolist()``
    feeding a logging call inside a RANK-CONDITIONAL block
    (``if dist.get_rank() == 0: print(float(loss))``) of a distributed
    loop body: beyond PDT112's per-iteration device->host sync, this
    pattern structurally LOSES the fleet view — only the printing
    rank's value ever surfaces, so the cross-rank skew that the
    conditional was hiding (the straggler, its phase) is exactly what
    never gets logged.  Record into registry gauges/histograms on
    EVERY rank (lazy reads, no loop cost) and call
    ``observability.fleet_snapshot()`` for the merged view with
    per-rank ``step_ms`` skew and slowest-rank attribution instead.
    Note-level advice, not an error."""
    for loop in _walk_fn(fndef):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        # own-scope walk (PDT108 contract): nested defs lint themselves
        stack = list(ast.iter_child_nodes(loop))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(sub))
            if not (isinstance(sub, ast.If)
                    and _is_rank_conditional(sub.test)):
                continue
            for inner in sub.body:
                for call in ast.walk(inner):
                    if not isinstance(call, ast.Call):
                        continue
                    fname = (_dotted(call.func) or "").split(".")[-1]
                    is_sink = (fname in _LOG_SINK_BARE
                               if isinstance(call.func, ast.Name)
                               else fname in _LOG_SINK_METHODS)
                    if not is_sink:
                        continue
                    for arg in call.args + [kw.value
                                            for kw in call.keywords]:
                        hit = next(
                            (n for n in ast.walk(arg)
                             if _host_sync_desc(n) is not None), None)
                        if hit is not None:
                            yield hit, (
                                f"{_host_sync_desc(hit)} logged only "
                                f"on one rank inside a distributed "
                                f"loop: the synced value costs a "
                                f"device round-trip per iteration AND "
                                f"every other rank's number is thrown "
                                f"away — record registry gauges/"
                                f"histograms on all ranks (lazy reads) "
                                f"and merge with observability."
                                f"fleet_snapshot(), which also derives "
                                f"per-rank step_ms skew and "
                                f"slowest-rank attribution")
                            break   # one finding per log call


# constructor/call names that put a multi-device mesh "in scope" for
# PDT116: a serving engine built single-device right next to one of
# these is almost always an oversight, not a lab rig
_MESH_EVIDENCE_CALLS = {"ProcessMesh", "Mesh", "device_count"}


@register(
    "PDT116", "single-device-engine-on-mesh", Severity.NOTE, "ast",
    scope="eager",
    example="""
import jax
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    mesh = dist.ProcessMesh(np.arange(jax.device_count()), ["tp"])
    eng = ContinuousBatchingEngine(model, max_slots=8)
    for p in prompts:
        eng.add_request(p, 32)
    return eng.run()
""",
    near_miss="""
import jax
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    mesh = dist.ProcessMesh(np.arange(jax.device_count()), ["tp"])
    eng = ContinuousBatchingEngine(model, max_slots=8, mesh=mesh)
    for p in prompts:
        eng.add_request(p, 32)
    return eng.run()
""")
def check_single_device_engine_on_mesh(fndef, ctx):
    """A serving engine constructed WITHOUT ``mesh=``/``tp_axis=`` in
    a function that is visibly mesh-aware (it builds a
    ``ProcessMesh``/``Mesh`` or consults ``jax.device_count()``): the
    engine will compile its two serving programs on ONE device while
    the rest of the mesh idles — weights that could column/row-split
    over the tensor-parallel axis (one psum at the attention output
    and the MLP reduce; KV pools sharded by kv-head) are replicated
    instead, capping both model size and decode throughput at a
    single chip.  Pass ``mesh=``/``tp_axis=`` (or set the
    ``serving_tp`` flag) — greedy outputs are token-identical to the
    single-device engine, so sharding is free at the output level.
    Single-device parity rigs are legitimate, hence note-level
    advice, not an error."""
    has_mesh_evidence = any(
        isinstance(node, ast.Call)
        and (_dotted(node.func) or "").split(".")[-1]
        in _MESH_EVIDENCE_CALLS
        for node in _walk_fn(fndef))
    if not has_mesh_evidence:
        return
    for node in _walk_fn(fndef):
        if not isinstance(node, ast.Call) \
                or (_dotted(node.func) or "").split(".")[-1] \
                != "ContinuousBatchingEngine":
            continue
        kws = {kw.arg for kw in node.keywords if kw.arg}
        if "mesh" not in kws and "tp_axis" not in kws:
            yield node, (
                "serving engine built single-device while a "
                "multi-device mesh is in scope (ProcessMesh/Mesh/"
                "device_count in this function): pass mesh=/tp_axis= "
                "so the serving programs shard over the "
                "tensor-parallel axis — greedy outputs stay "
                "token-identical and decode stops being capped at "
                "one chip")


# overload knobs that prove an engine expects real traffic, and the
# judgment-layer kwargs that answer them — PDT117 fires on the first
# set without the second.  dispatch_retries/prefix_cache are absent
# from the trigger set deliberately: they tune mechanics, not load.
_ENGINE_OVERLOAD_KWARGS = {"max_queue", "queue_policy",
                           "default_deadline_ms"}
_ENGINE_GUARD_KWARGS = {"slo", "watchdog_ms"}


@register(
    "PDT117", "no-slo-guard-under-load", Severity.NOTE, "ast",
    scope="eager",
    example="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    eng = ContinuousBatchingEngine(model, max_slots=8, max_queue=64,
                                   queue_policy="reject",
                                   default_deadline_ms=500.0)
    for p in prompts:
        eng.add_request(p, 32)
    return eng.run()
""",
    near_miss="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    eng = ContinuousBatchingEngine(model, max_slots=8, max_queue=64,
                                   queue_policy="reject",
                                   default_deadline_ms=500.0,
                                   slo="ttft_p95_ms=500,goodput=0.99",
                                   watchdog_ms=2000.0)
    for p in prompts:
        eng.add_request(p, 32)
    return eng.run()
""")
def check_no_slo_guard_under_load(fndef, ctx):
    """A serving engine constructed WITH overload knobs
    (``max_queue``/``queue_policy``/``default_deadline_ms`` — this
    engine clearly expects heavy traffic) but with NO judgment layer:
    no SLO spec (``slo=`` / ``serving_slo`` flag) and no stall
    watchdog (``watchdog_ms`` / ``watchdog_stall_ms`` flag).  The
    overload policies will shed and preempt correctly, but nothing
    evaluates the latency histograms against objectives (a TTFT p95
    burning its error budget is invisible until users complain) and a
    hung dispatch hangs the caller forever instead of surfacing a
    coded ``EngineStallError`` with thread stacks in a flight record.
    Arm at least one of ``slo=``/``watchdog_ms=``.  Note-level
    advice, not an error."""
    for node in _walk_fn(fndef):
        if not isinstance(node, ast.Call) \
                or (_dotted(node.func) or "").split(".")[-1] \
                != "ContinuousBatchingEngine":
            continue
        kws = {kw.arg for kw in node.keywords if kw.arg}
        if kws & _ENGINE_OVERLOAD_KWARGS \
                and not kws & _ENGINE_GUARD_KWARGS:
            yield node, (
                "engine has overload knobs (max_queue/queue_policy/"
                "default_deadline_ms) but no SLO spec or watchdog "
                "armed: pass slo= (or the serving_slo flag) so the "
                "TTFT/TPOT/goodput histograms are judged against "
                "objectives with burn-rate alerting, and watchdog_ms= "
                "(or watchdog_stall_ms) so a hung dispatch dumps "
                "stacks and fails coded instead of hanging")


# constructs that prove a TRAINING function is fleet-aware (PDT118):
# mesh/world evidence as for PDT116, plus the distributed-launch world
# probes a multi-host fit reads before sharding its data
_FLEET_EVIDENCE_CALLS = _MESH_EVIDENCE_CALLS | {
    "get_world_size", "init_parallel_env"}
# recovery arming that answers it: the elastic supervisor (buddy
# snapshots + collective watchdog + detector-driven resume) or at
# minimum the preemption hook (checkpoint-at-boundary + clean exit).
# ``install`` is matched as the dotted suffix ``preempt.install`` —
# a bare last-component match would let any unrelated ``x.install()``
# silently suppress the diagnostic
_FIT_GUARD_CALLS = {"FleetSupervisor"}


def _arms_fit_guard(dotted):
    return dotted.split(".")[-1] in _FIT_GUARD_CALLS \
        or dotted == "preempt.install" \
        or dotted.endswith(".preempt.install")


@register(
    "PDT118", "unsupervised-multihost-fit", Severity.NOTE, "ast",
    scope="eager",
    example="""
import jax
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

def train(model, data):
    world = jax.device_count()
    mesh = dist.ProcessMesh(np.arange(world), ["dp"])
    for epoch in range(10):
        model.fit(data, batch_size=32, epochs=1)
""",
    near_miss="""
import jax
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.resilience import preempt

def train(model, data):
    world = jax.device_count()
    mesh = dist.ProcessMesh(np.arange(world), ["dp"])
    with preempt.install():
        for epoch in range(10):
            model.fit(data, batch_size=32, epochs=1,
                      save_dir="ckpt", resume=True)
""")
def check_unsupervised_multihost_fit(fndef, ctx):
    """``Model.fit`` in a function that is visibly fleet-aware (it
    builds a ``ProcessMesh``/``Mesh`` or consults ``device_count``/
    ``get_world_size``/``init_parallel_env``) with NEITHER
    ``resilience.FleetSupervisor`` NOR ``preempt.install()`` armed: at
    fleet scale the dominant availability cost is the recovery, and an
    unarmed fit pays it in full — a single dead rank hangs every
    survivor inside the gradient psum (no collective watchdog, so no
    coded ``CollectiveTimeoutError``), and the only way back is a full
    restart from on-disk checkpoints instead of a buddy in-memory
    restore at the last snapshot boundary.  Wrap the loop in
    ``FleetSupervisor.fit`` (buddy snapshots + watchdog + elastic
    resume) or at minimum arm ``preempt.install()`` so preemptions
    checkpoint at a step boundary.  Single-device rigs are legitimate,
    hence note-level advice."""
    has_fleet_evidence = any(
        isinstance(node, ast.Call)
        and (_dotted(node.func) or "").split(".")[-1]
        in _FLEET_EVIDENCE_CALLS
        for node in _walk_fn(fndef))
    if not has_fleet_evidence:
        return
    armed = any(
        isinstance(node, ast.Call)
        and _arms_fit_guard(_dotted(node.func) or "")
        for node in _walk_fn(fndef))
    if armed:
        return
    for node in _walk_fn(fndef):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr != "fit":
            continue
        yield node, (
            "Model.fit in a fleet-aware function (ProcessMesh/Mesh/"
            "device_count/get_world_size in scope) with neither "
            "FleetSupervisor nor preempt.install() armed: a dead rank "
            "hangs every survivor in the gradient psum and recovery "
            "means a full on-disk restart — arm resilience."
            "FleetSupervisor (buddy in-memory snapshots, collective "
            "watchdog PDT-E021, detector-driven resume) or at least "
            "preempt.install() for checkpoint-at-boundary exits")


# replica-pool constructors PDT119 counts, and the front-end that
# proves the pool is routed.  RpcReplica is deliberately included in
# the pool set: N hand-held rpc proxies without a router have the
# same failure mode as N hand-held engines.
_REPLICA_POOL_CALLS = {"ContinuousBatchingEngine", "DisaggServer",
                       "RpcReplica"}
_ROUTER_CALLS = {"FleetRouter"}


@register(
    "PDT119", "unrouted-replica-pool", Severity.NOTE, "ast",
    scope="eager",
    example="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    engines = [ContinuousBatchingEngine(model, max_slots=8),
               ContinuousBatchingEngine(model, max_slots=8)]
    for i, p in enumerate(prompts):
        engines[i % 2].add_request(p, 32)
    return [e.run() for e in engines]
""",
    near_miss="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine, FleetRouter

def serve(model, prompts):
    router = FleetRouter(replicas=[
        ContinuousBatchingEngine(model, max_slots=8),
        ContinuousBatchingEngine(model, max_slots=8)])
    for p in prompts:
        router.add_request(p, 32)
    return router.run()
""")
def check_unrouted_replica_pool(fndef, ctx):
    """TWO OR MORE serving replicas (``ContinuousBatchingEngine`` /
    ``DisaggServer`` / ``RpcReplica``) constructed in one function
    with no ``FleetRouter`` in sight: the pool is being spread by
    hand.  Hand-spreading gets none of the fleet layer — no
    prefix-cache-aware placement (shared-prefix traffic scatters, so
    every replica re-prefills what another already cached), no
    tenant fair share, and above all no failure handling: a replica
    that dies mid-decode takes its queued and in-flight requests with
    it, where the router would requeue them to survivors
    bitwise-identically under one coded PDT-E024 flight record.
    Wrap the pool: ``FleetRouter(replicas=[...])`` — or pass
    ``replicas=N`` and let the router build them.  Note-level advice;
    deliberately independent pools (A/B harnesses, test rigs) are
    legitimate."""
    if any(isinstance(node, ast.Call)
           and (_dotted(node.func) or "").split(".")[-1]
           in _ROUTER_CALLS
           for node in _walk_fn(fndef)):
        return
    seen = 0
    for node in _walk_fn(fndef):
        if not isinstance(node, ast.Call) \
                or (_dotted(node.func) or "").split(".")[-1] \
                not in _REPLICA_POOL_CALLS:
            continue
        seen += 1
        if seen == 2:
            yield node, (
                "two or more serving replicas built here with no "
                "FleetRouter: hand-spread pools lose cache-aware "
                "placement, tenant fair share, and dead-replica "
                "requeue (a replica loss drops its in-flight "
                "requests instead of re-serving them bitwise from "
                "survivors under a coded PDT-E024 record) — wrap "
                "the pool in FleetRouter(replicas=[...])")

# constant values that off-spell the engine's decode megakernel — the
# string spellings are the engine's strict case-insensitive parse set
# (an unparseable spelling raises in the ctor, so the linter only ever
# sees these or on-spellings)
_MEGAKERNEL_OFF = (False, 0) + MEGAKERNEL_OFF_SPELLINGS


def _megakernel_off_or_absent(call) -> bool:
    for kw in call.keywords:
        if kw.arg == "megakernel":
            v = kw.value
            if not isinstance(v, ast.Constant):
                return False      # computed value: can't prove it's off
            val = v.value
            if val is None:       # None defers to the flag default: off
                return True
            if isinstance(val, str):
                val = val.lower()
            return val in _MEGAKERNEL_OFF
    return True                   # absent: serving_megakernel defaults off


@register(
    "PDT120", "unfused-decode-serving", Severity.NOTE, "ast",
    scope="eager",
    example="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    eng = ContinuousBatchingEngine(model, max_slots=8, max_queue=64,
                                   queue_policy="reject",
                                   default_deadline_ms=500.0,
                                   slo="ttft_p95_ms=500,goodput=0.99",
                                   watchdog_ms=2000.0)
    for p in prompts:
        eng.add_request(p, 32)
    return eng.run()
""",
    near_miss="""
import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousBatchingEngine

def serve(model, prompts):
    eng = ContinuousBatchingEngine(model, max_slots=8, max_queue=64,
                                   queue_policy="reject",
                                   default_deadline_ms=500.0,
                                   slo="ttft_p95_ms=500,goodput=0.99",
                                   watchdog_ms=2000.0,
                                   megakernel="on")
    for p in prompts:
        eng.add_request(p, 32)
    return eng.run()
""")
def check_unfused_decode_serving(fndef, ctx):
    """A serving engine constructed WITH overload knobs
    (``max_queue``/``queue_policy``/``default_deadline_ms`` — this
    engine clearly expects sustained traffic) but with the decode
    megakernel absent or off-spelled.  Sustained serving is
    decode-bound, and at small per-step batches the unfused decode
    chain (~13 dispatches per layer) is launch-dominated: the chip
    idles between kernels while the host feeds it one small op at a
    time.  The fused path (``megakernel="on"`` / the
    ``serving_megakernel`` flag) runs the same math as ~3 fused Pallas
    kernels per layer plus one sampling epilogue — token streams are
    bitwise-identical either way (tests/test_decode_megakernel.py
    gates this), only dispatches-per-token moves (13 -> 4 per layer,
    the serving-bench ``dispatches_per_token`` column).  Note-level
    advice, not an error: the flag defaults off until the TPU round
    re-measures, and a deliberate off-spelling on a compile-budget-
    sensitive rig is legitimate."""
    for node in _walk_fn(fndef):
        if not isinstance(node, ast.Call) \
                or (_dotted(node.func) or "").split(".")[-1] \
                != "ContinuousBatchingEngine":
            continue
        kws = {kw.arg for kw in node.keywords if kw.arg}
        if kws & _ENGINE_OVERLOAD_KWARGS \
                and _megakernel_off_or_absent(node):
            yield node, (
                "engine has overload knobs (max_queue/queue_policy/"
                "default_deadline_ms) but decodes unfused: sustained "
                "traffic is decode-bound and the ~13-dispatch-per-"
                "layer chain is launch-dominated at small batches — "
                "pass megakernel=\"on\" (or the serving_megakernel "
                "flag) for the fused ~3-kernel decode path; token "
                "streams are bitwise-identical, only "
                "dispatches-per-token moves")


# batch-staging calls a custom train loop pays synchronously per step:
# to_tensor / Tensor() host->device conversion and jax device_put. The
# .numpy() direction (device->host readback of the loss) already has
# its own coded finding (PDT101 inside jit); here it marks the loop as
# feeding the device from host data, same as the converters.
_INPUT_STAGE_CALLS = {"to_tensor", "device_put", "Tensor", "asarray"}


def _loop_stages_and_steps(loop):
    """Does ONE loop body both stage host batches and run a train
    step?  Staging = a conversion call from ``_INPUT_STAGE_CALLS``;
    a step = a ``.backward()`` call (the unambiguous train marker) or
    a ``train_batch``/``step`` method call."""
    stages = steps = False
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        name = (_dotted(node.func) or "").split(".")[-1]
        if name in _INPUT_STAGE_CALLS:
            stages = True
        elif name in ("backward", "train_batch"):
            steps = True
        if stages and steps:
            return True
    return False


@register(
    "PDT121", "eager-input-feed", Severity.NOTE, "ast",
    scope="eager",
    example="""
import paddle_tpu as paddle

def train(model, opt, loader, loss_fn):
    for batch in loader:
        ids = paddle.to_tensor(batch[0])
        lab = paddle.to_tensor(batch[1])
        loss = loss_fn(model(ids), lab)
        loss.backward()
        opt.step()
        opt.clear_grad()
""",
    near_miss="""
import paddle_tpu as paddle

def train(model, opt, loader, loss_fn):
    staged = None
    for batch in loader:
        ids, lab = staged if staged else (paddle.to_tensor(batch[0]),
                                          paddle.to_tensor(batch[1]))
        staged = None  # prefetch: next batch staged under the step
        loss = loss_fn(model(ids), lab)
        loss.backward()
        opt.step()
        opt.clear_grad()
""")
def check_eager_input_feed(fndef, ctx):
    """A hand-written train loop that stages its batches SYNCHRONOUSLY
    inside the step loop — ``to_tensor``/``device_put`` conversion in
    the same loop body as the ``backward()`` — with no prefetch knob
    anywhere in scope.  Every step then serializes host->device
    transfer with device compute: the chip idles for the full staging
    time, per step.  ``hapi.Model.fit`` double-buffers this for free
    (the ``train_prefetch`` flag: batch N+1 stages while step N is in
    flight, bitwise-identical loss trajectory, the wait surfaces as
    ``train.input_wait_ms``); custom loops can do the same by staging
    the next batch between the step's dispatch and its loss readback.
    Note-level advice: profile-time rigs that want the synchronous
    cost visible are legitimate.  Suppressed when anything named
    ``*prefetch*`` is in scope (a knob or a hand-rolled feed) or the
    loop is already double-buffered through a ``staged``/``queue``
    variable the loop consumes."""
    src_names = set()
    for node in _walk_fn(fndef):
        if isinstance(node, ast.Name):
            src_names.add(node.id.lower())
        elif isinstance(node, ast.Attribute):
            src_names.add(node.attr.lower())
        elif isinstance(node, ast.Constant) and isinstance(node.value,
                                                          str):
            src_names.add(node.value.lower())
    if any("prefetch" in n or n == "staged" for n in src_names):
        return
    for node in _walk_fn(fndef):
        if isinstance(node, (ast.For, ast.While)) \
                and _loop_stages_and_steps(node):
            yield node, (
                "batches are staged synchronously inside the step "
                "loop (to_tensor/device_put in the same body as "
                "backward()): host->device transfer serializes with "
                "device compute every step — use hapi.Model.fit's "
                "train_prefetch double-buffering (bitwise-identical "
                "loss trajectory; the residual wait surfaces as "
                "train.input_wait_ms), or stage batch N+1 between "
                "the step's dispatch and its loss readback")
            return


# router kwargs that prove the fleet is judged on latency: deadlines
# tick and SLOs burn while a cold drain waits out tail decodes
_ROUTER_SLO_KWARGS = {"fleet_slo", "default_deadline_ms",
                      "scalein_hold_s"}


def _migration_off_or_absent(call) -> bool:
    for kw in call.keywords:
        if kw.arg == "migration":
            v = kw.value
            if not isinstance(v, ast.Constant):
                return False      # computed value: can't prove it's off
            # None defers to the serving_migration flag default: off
            return v.value in (None, False, 0)
    return True                   # absent: serving_migration defaults off


@register(
    "PDT122", "cold-drain-under-load", Severity.NOTE, "ast",
    scope="eager",
    example="""
import paddle_tpu as paddle
from paddle_tpu.inference import FleetRouter

def serve_fleet(model, prompts):
    r = FleetRouter(model, replicas=4, standby=1,
                    fleet_slo="queue_p95_ms=200,goodput=0.99",
                    default_deadline_ms=500.0,
                    scalein_hold_s=30.0)
    for p in prompts:
        r.add_request(p, 32)
    return r.run()
""",
    near_miss="""
import paddle_tpu as paddle
from paddle_tpu.inference import FleetRouter

def serve_fleet(model, prompts):
    r = FleetRouter(model, replicas=4, standby=1,
                    fleet_slo="queue_p95_ms=200,goodput=0.99",
                    default_deadline_ms=500.0,
                    scalein_hold_s=30.0,
                    migration=True, lameduck_ms=2000.0)
    for p in prompts:
        r.add_request(p, 32)
    return r.run()
""")
def check_cold_drain_under_load(fndef, ctx):
    """A ``FleetRouter`` armed with latency judgment (``fleet_slo`` /
    ``default_deadline_ms`` / ``scalein_hold_s`` — scale-in and drain
    WILL happen, and deadlines tick while they do) but with live
    migration absent or off-spelled.  A cold drain waits out the tail
    decode of every resident request before the replica parks:
    under load that is seconds of deadline burn per scale-in, and a
    planned preemption (SIGTERM) loses every resident request's
    prefill work to a from-scratch requeue.  ``migration=True`` (or
    the ``serving_migration`` flag) moves residents warm instead —
    snapshot -> KV-page transfer -> restore through the import
    scatter; token streams are bitwise-identical
    (tests/test_migration.py gates this), only drain latency and
    re-prefill work move.  Note-level advice: single-replica rigs and
    fleets that never scale in are legitimate."""
    for node in _walk_fn(fndef):
        if not isinstance(node, ast.Call) \
                or (_dotted(node.func) or "").split(".")[-1] \
                != "FleetRouter":
            continue
        kws = {kw.arg for kw in node.keywords if kw.arg}
        if kws & _ROUTER_SLO_KWARGS \
                and _migration_off_or_absent(node):
            yield node, (
                "fleet router is judged on latency (fleet_slo/"
                "default_deadline_ms/scalein_hold_s) but drains cold: "
                "scale-in and preemption wait out every resident "
                "request's tail decode while deadlines tick, and a "
                "SIGTERM loses resident prefill work to a cold "
                "requeue — pass migration=True (or the "
                "serving_migration flag) so residents move warm over "
                "KVPageTransport; token streams are bitwise-"
                "identical, only drain latency moves")

"""``paddle_tpu.analysis`` — graph lint: two-front-end static analysis.

A diagnostics engine with a registry of coded checks:

- ``PDT1xx`` (AST front-end, ``ast_checks.py``): tracer-safety lint run
  over a function's source before ``jit.to_static`` conversion — host
  syncs, trace-time side effects, graph-break escape sites, host
  entropy, unconvertible-function features.
- ``PDT2xx`` (IR front-end, ``ir_checks.py``): checks over the traced
  jaxpr — unintended f64, blocking host callbacks, undonated state
  buffers, dead computation, weak-typed inputs — plus runtime-reported
  codes (trip-bound truncation).

Severities: note / warn / error. Reporting is gated by
``PDTPU_ANALYSIS=off|warn|error`` (``FLAGS_analysis``): ``warn`` emits
:class:`LintWarning`, ``error`` raises ``StaticAnalysisError`` on any
warn-or-worse finding. Suppress per line with ``# pdtpu: noqa[PDT101]``
(bare ``# pdtpu: noqa`` silences all codes on the line), per scope with
``analysis.suppress("PDT101")`` as context manager or decorator.

Wired into ``jit.to_static`` (AST lint before conversion, IR lint after
capture), ``jit/dy2static.py`` (graph-break decline sites report
PDT105/PDT107), and ``hapi.Model.prepare``. Standalone CLI::

    python -m paddle_tpu.analysis paddle_tpu/ [--assume-jit] [--strict]
"""
from __future__ import annotations

# FLAGS_analysis lives in core/state.py with the other core flags
# (define_flag("analysis", ...); env override PDTPU_ANALYSIS).

from .registry import (  # noqa: E402,F401
    REGISTRY, CheckSpec, Diagnostic, Severity, pragma_suppressed,
    register, register_runtime, spec, suppress)
from . import ast_checks  # noqa: E402,F401  (registers PDT1xx)
from . import ir_checks   # noqa: E402,F401  (registers PDT20x)
from . import program     # noqa: E402,F401  (registers PDT22x/23x/24x)
from .engine import (  # noqa: E402,F401
    LintWarning, analyze_file, analyze_source, check_executable,
    check_function, check_jaxpr, check_traced, collect, exercise,
    lint_callable, lint_executable, mode, report, report_runtime,
    reset_reported)
from .program import (  # noqa: E402,F401
    AuditResult, CollectiveOp, audit_counts, audit_executable,
    audit_jaxpr, audit_jitted, collective_schedule, flat_eqn_count,
    live_ranges, schedule_hash, static_peak_bytes, verify_schedule)

__all__ = [
    "REGISTRY", "AuditResult", "CheckSpec", "CollectiveOp", "Diagnostic",
    "Severity", "LintWarning", "analyze_file", "analyze_source",
    "audit_counts", "audit_executable", "audit_jaxpr", "audit_jitted",
    "check_executable", "check_function", "check_jaxpr", "check_traced",
    "collect", "collective_schedule", "exercise", "flat_eqn_count",
    "lint_callable",
    "lint_executable", "live_ranges", "mode", "pragma_suppressed",
    "register", "register_runtime", "report", "report_runtime",
    "reset_reported", "schedule_hash", "spec", "static_peak_bytes",
    "suppress", "verify_schedule",
]
